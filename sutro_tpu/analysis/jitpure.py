"""Jit-purity and scheduler-determinism pass.

Entry points ("roots"):

- functions decorated with ``jax.jit`` (directly or through
  ``functools.partial(jax.jit, ...)``),
- Pallas kernel bodies — the callable handed to ``pl.pallas_call``
  (directly, via ``functools.partial(kernel, ...)`` inline, or via a
  local ``kernel = functools.partial(...)`` binding),
- the scheduler decode window: ``run`` / ``run_multi`` methods of a
  ``*Batcher`` class in a ``*scheduler`` module.

From each root the pass walks the package-reachable call set and
reports:

- ``jit-host-sync``        ``.item()`` / ``np.asarray`` / ``np.array``
                           / ``jax.device_get`` / ``.block_until_ready``
                           / ``float()``/``int()`` on a traced
                           parameter inside a jit root
- ``jit-nondeterminism``   wall clocks or Python/global-numpy RNG in a
                           jit/Pallas root
- ``sched-nondeterminism`` ``time.time()`` / ``random.*`` /
                           ``np.random.*`` / ``uuid.uuid4`` /
                           ``os.urandom`` reachable from the decode
                           window (``time.monotonic`` and
                           ``time.perf_counter`` stay legal: elapsed-
                           time measurement, not wall-clock decisions;
                           ``jax.random`` is keyed and legal)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import FunctionInfo, ModuleInfo, PackageIndex, dotted
from .core import Finding

_MAX_DEPTH = 8

HOST_SYNC_EXACT = {"numpy.asarray", "numpy.array", "jax.device_get"}
HOST_SYNC_SUFFIX = (".item", ".block_until_ready", ".copy_to_host")
JIT_NONDET_EXACT = {"time.time", "time.monotonic", "time.perf_counter"}
SCHED_NONDET_EXACT = {"time.time", "os.urandom", "uuid.uuid4"}
_RNG_PREFIXES = ("random.", "numpy.random.")


def _decorator_is_jit(mod: ModuleInfo, dec: ast.AST) -> bool:
    for sub in ast.walk(dec):
        text = dotted(sub)
        if text is None:
            continue
        expanded = mod.expand(text)
        if expanded == "jax.jit" or expanded.endswith(".jit") or text == "jit":
            return True
    return False


def _static_params(mod: ModuleInfo, func: FunctionInfo) -> Set[str]:
    """Params named in static_argnames (static under jit: python values,
    so float()/int() on them is fine)."""
    out: Set[str] = set()
    node = func.node
    for dec in getattr(node, "decorator_list", []):
        for sub in ast.walk(dec):
            if isinstance(sub, ast.keyword) and sub.arg in (
                "static_argnames",
            ):
                for c in ast.walk(sub.value):
                    if isinstance(c, ast.Constant) and isinstance(
                        c.value, str
                    ):
                        out.add(c.value)
            if isinstance(sub, ast.keyword) and sub.arg in (
                "static_argnums",
            ):
                nums = [
                    c.value
                    for c in ast.walk(sub.value)
                    if isinstance(c, ast.Constant)
                    and isinstance(c.value, int)
                ]
                args = getattr(node, "args", None)
                if args is not None:
                    all_names = [
                        a.arg
                        for a in list(args.posonlyargs) + list(args.args)
                    ]
                    for n in nums:
                        if 0 <= n < len(all_names):
                            out.add(all_names[n])
    return out


def _find_roots(
    index: PackageIndex,
) -> List[Tuple[FunctionInfo, str]]:
    """(function, kind) with kind in {jit, pallas, sched}."""
    roots: List[Tuple[FunctionInfo, str]] = []
    for mod in index.modules.values():
        for func in mod.functions.values():
            decs = getattr(func.node, "decorator_list", [])
            if any(_decorator_is_jit(mod, d) for d in decs):
                roots.append((func, "jit"))
        # pallas kernel bodies
        for func in list(mod.functions.values()):
            for node in ast.walk(func.node):
                if not isinstance(node, ast.Call):
                    continue
                text = dotted(node.func)
                if not text or not mod.expand(text).endswith(
                    "pallas_call"
                ):
                    continue
                if not node.args:
                    continue
                kernel_name = _kernel_target(mod, func, node.args[0])
                if kernel_name is None:
                    continue
                tgt = mod.functions.get(kernel_name) or mod.functions.get(
                    f"{func.qualname}.{kernel_name}"
                )
                if tgt is not None:
                    roots.append((tgt, "pallas"))
        # scheduler decode window
        if mod.name.split(".")[-1].endswith("scheduler"):
            for cls, quals in mod.classes.items():
                if "Batcher" not in cls:
                    continue
                for q in quals:
                    if q.split(".")[-1] in ("run", "run_multi"):
                        roots.append((mod.functions[q], "sched"))
    # dedupe, jit/pallas kinds win over sched for the same function
    seen: Dict[str, Tuple[FunctionInfo, str]] = {}
    for func, kind in roots:
        seen.setdefault(f"{func.label}|{kind}", (func, kind))
    return list(seen.values())


def _kernel_target(
    mod: ModuleInfo, func: FunctionInfo, arg: ast.AST
) -> Optional[str]:
    if isinstance(arg, ast.Name):
        return func.partial_targets.get(arg.id, arg.id)
    if isinstance(arg, ast.Call):
        text = dotted(arg.func)
        if text and mod.expand(text) == "functools.partial" and arg.args:
            return dotted(arg.args[0])
    return None


class _PurityWalker:
    def __init__(self, index: PackageIndex):
        self.index = index
        self.findings: List[Finding] = []
        self._seen: Set[str] = set()

    def _emit(self, f: Finding) -> None:
        fp = f.fingerprint() + f"@{f.line}"
        if fp in self._seen:
            return
        self._seen.add(fp)
        self.findings.append(f)

    def _flag_call(
        self,
        func: FunctionInfo,
        call: ast.Call,
        kind: str,
        root: FunctionInfo,
        traced_params: Set[str],
    ) -> None:
        raw = dotted(call.func) or ""
        text = func.module.expand(raw) if raw else ""
        via = (
            "" if func is root else f" (reached from {root.qualname})"
        )
        if kind in ("jit", "pallas"):
            sync = text in HOST_SYNC_EXACT or any(
                text.endswith(s) for s in HOST_SYNC_SUFFIX
            )
            if (
                not sync
                and isinstance(call.func, ast.Name)
                and call.func.id in ("float", "int")
                and len(call.args) == 1
                and isinstance(call.args[0], ast.Name)
                and call.args[0].id in traced_params
            ):
                sync = True
                raw = f"{call.func.id}({call.args[0].id})"
            if sync:
                self._emit(
                    Finding(
                        rule="jit-host-sync",
                        path=func.module.path,
                        line=call.lineno,
                        symbol=func.label,
                        key=f"{root.qualname}|{text or raw}",
                        message=(
                            f"host-sync `{raw}` inside jit entry point "
                            f"`{root.qualname}`{via}"
                        ),
                    )
                )
                return
            if text in JIT_NONDET_EXACT or any(
                text.startswith(p) for p in _RNG_PREFIXES
            ):
                self._emit(
                    Finding(
                        rule="jit-nondeterminism",
                        path=func.module.path,
                        line=call.lineno,
                        symbol=func.label,
                        key=f"{root.qualname}|{text}",
                        message=(
                            f"nondeterministic `{raw}` inside jit entry "
                            f"point `{root.qualname}`{via}"
                        ),
                    )
                )
        else:  # sched
            if text in SCHED_NONDET_EXACT or any(
                text.startswith(p) for p in _RNG_PREFIXES
            ):
                self._emit(
                    Finding(
                        rule="sched-nondeterminism",
                        path=func.module.path,
                        line=call.lineno,
                        symbol=func.label,
                        key=f"{root.qualname}|{text}",
                        message=(
                            f"`{raw}` reachable from the scheduler "
                            f"decode window `{root.qualname}` — decode "
                            "decisions must be deterministic and "
                            "host-clock-free"
                        ),
                    )
                )

    def walk_root(self, root: FunctionInfo, kind: str) -> None:
        traced = set(root.params) - _static_params(root.module, root)
        visited: Set[str] = set()
        stack: List[Tuple[FunctionInfo, int]] = [(root, 0)]
        while stack:
            func, depth = stack.pop()
            if func.label in visited:
                continue
            visited.add(func.label)
            for node in ast.walk(func.node):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and node is not func.node:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                self._flag_call(func, node, kind, root, traced)
                if depth < _MAX_DEPTH:
                    _, target = self.index.resolve_call(func, node)
                    if target is not None:
                        stack.append((target, depth + 1))


def run(index: PackageIndex) -> List[Finding]:
    w = _PurityWalker(index)
    for func, kind in _find_roots(index):
        w.walk_root(func, kind)
    w.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return w.findings
