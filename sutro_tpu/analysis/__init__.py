"""graftlint: engine-aware static analysis for sutro_tpu (ISSUE 2).

AST-walking passes that enforce the concurrency and accelerator
discipline the engine's dynamic tests only catch probabilistically:
lock-order consistency, no blocking I/O or callbacks under locks, jit
purity / scheduler determinism, thread teardown hygiene, and no silent
exception swallows. See ``core.RULES`` for the catalog, ``__main__``
for the CLI, and ``baseline.json`` for the accepted pre-existing
findings the CI gate diffs against.

Programmatic use::

    from sutro_tpu.analysis import analyze
    findings, suppressed, index = analyze(["sutro_tpu"])
"""

from .core import (  # noqa: F401
    Finding,
    RULES,
    analyze,
    baseline_counts,
    compare_baseline,
    load_baseline,
    write_baseline,
)
