"""Package-wide AST index and best-effort call resolution.

The passes (locks, jitpure, hygiene) share one parsed view of the
scanned tree: per-module import maps, function/method tables with
lexical nesting (closures), and per-function records of the objects the
rules care about — locks, threads, queues, ``functools.partial``
bindings. Resolution is deliberately *best effort*: ``self.method()``
resolves within the enclosing class, bare names through the lexical
chain then module then imports, ``alias.func()`` through the import
map. Attribute chains on arbitrary objects (``self.eng.jobs.flush``)
do not resolve — the passes treat unresolvable calls as opaque.

``FlowWalker`` adds path-sensitive return-path and exception-edge
tracking over a single function body (loops unrolled once, Try routing
with finalbody replay on every exit) — the substrate for the
resource-lifecycle pass.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}
QUEUE_CTORS = {
    "queue.Queue",
    "queue.PriorityQueue",
    "queue.LifoQueue",
    "queue.SimpleQueue",
}
THREAD_CTORS = {"threading.Thread", "threading.Timer"}
EVENT_CTORS = {
    "threading.Event",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "threading.Barrier",
}

# names that look like a lock when we cannot see the constructor
# (e.g. ``with self._queue.mutex:`` — queue.Queue's internal lock)
_LOCKISH = ("lock", "mutex", "cond", "_cv", "condition")


def looks_like_lock(name: str) -> bool:
    low = name.lower()
    return any(low == t or low.endswith(t) or t in low for t in _LOCKISH)


def dotted(node: ast.AST) -> Optional[str]:
    """Name/Attribute chain as ``a.b.c`` text, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


@dataclasses.dataclass
class FunctionInfo:
    module: "ModuleInfo"
    qualname: str  # dotted; classes and nested functions included
    node: ast.AST
    class_name: Optional[str]
    parent: Optional["FunctionInfo"]
    params: Tuple[str, ...]
    local_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    thread_vars: Set[str] = dataclasses.field(default_factory=set)
    queue_vars: Set[str] = dataclasses.field(default_factory=set)
    partial_targets: Dict[str, str] = dataclasses.field(
        default_factory=dict
    )  # var -> function name it wraps via functools.partial

    @property
    def label(self) -> str:
        return f"{self.module.name}:{self.qualname}"

    def all_params(self) -> Set[str]:
        """Own params plus every lexically-enclosing function's (a
        closure calling an outer callback param counts)."""
        out: Set[str] = set()
        f: Optional[FunctionInfo] = self
        while f is not None:
            out.update(f.params)
            f = f.parent
        return out


@dataclasses.dataclass
class ModuleInfo:
    path: str  # as reported in findings (posix-relative)
    name: str  # dotted module name, best effort
    tree: ast.Module
    lines: List[str]
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict
    )
    attr_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    module_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    # "{Class}.{attr}" -> expanded ctor text for every ``self.x = Ctor()``
    # assignment seen in the class (first ctor wins) — the races pass
    # uses it to tell sync objects (queues/events/threads) from plain
    # shared state
    attr_ctors: Dict[str, str] = dataclasses.field(default_factory=dict)
    rlock_ids: Set[str] = dataclasses.field(default_factory=set)
    classes: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict
    )  # class name -> method qualnames

    def expand(self, text: str) -> str:
        """Rewrite the first segment through the import map so curated
        pattern lists match regardless of local aliases (``_time.sleep``
        -> ``time.sleep``, ``pd.read_parquet`` -> ``pandas.read_parquet``)."""
        head, sep, rest = text.partition(".")
        target = self.imports.get(head)
        if target is None:
            return text
        return f"{target}{sep}{rest}" if rest else target


def module_name_for(path: Path) -> str:
    parts = [] if path.stem == "__init__" else [path.stem]
    d = path.parent
    while (d / "__init__.py").exists():
        parts.insert(0, d.name)
        parent = d.parent
        if parent == d:
            break
        d = parent
    return ".".join(parts) or path.stem


def _collect_imports(mod: ModuleInfo) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    mod.imports[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    mod.imports[head] = head
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base_parts = mod.name.split(".")
                base_parts = base_parts[: -node.level] if node.level <= len(
                    base_parts
                ) else []
                base = ".".join(base_parts)
                if node.module:
                    base = f"{base}.{node.module}" if base else node.module
            else:
                base = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                mod.imports[a.asname or a.name] = (
                    f"{base}.{a.name}" if base else a.name
                )


class _Indexer(ast.NodeVisitor):
    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.class_stack: List[str] = []
        self.func_stack: List[FunctionInfo] = []

    # -- helpers -------------------------------------------------------
    def _qual(self, name: str) -> str:
        parts = self.class_stack + [
            f.qualname.split(".")[-1] for f in self.func_stack
        ]
        # func_stack entries already carry full quals; rebuild from the
        # innermost enclosing scope instead
        if self.func_stack:
            return f"{self.func_stack[-1].qualname}.{name}"
        if self.class_stack:
            return f"{'.'.join(self.class_stack)}.{name}"
        return name

    def _ctor_of(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            text = dotted(value.func)
            if text:
                return self.mod.expand(text)
        return None

    def _lock_id_for_expr(self, node: ast.AST) -> Optional[str]:
        """Resolve an expression naming an existing lock (for Condition
        aliasing): ``self.lock`` or a bare name."""
        text = dotted(node)
        if text is None:
            return None
        if text.startswith("self.") and self.class_stack:
            return self.mod.attr_locks.get(
                f"{self.class_stack[-1]}.{text[5:]}"
            )
        f = self.func_stack[-1] if self.func_stack else None
        while f is not None:
            if text in f.local_locks:
                return f.local_locks[text]
            f = f.parent
        return self.mod.module_locks.get(text)

    def _record_assign(self, target: ast.AST, value: ast.AST) -> None:
        ctor = self._ctor_of(value)
        func = self.func_stack[-1] if self.func_stack else None
        name = dotted(target)
        if name is None:
            return
        if (
            ctor is not None
            and self.class_stack
            and name.startswith("self.")
            and "." not in name[5:]
        ):
            self.mod.attr_ctors.setdefault(
                f"{self.class_stack[-1]}.{name[5:]}", ctor
            )
        if ctor in LOCK_CTORS:
            lock_id: Optional[str] = None
            if ctor == "threading.Condition" and isinstance(
                value, ast.Call
            ) and value.args:
                lock_id = self._lock_id_for_expr(value.args[0])
            final_id: Optional[str] = None
            if name.startswith("self.") and self.class_stack:
                attr = name[5:]
                key = f"{self.class_stack[-1]}.{attr}"
                final_id = lock_id or f"{self.mod.name}:{key}"
                self.mod.attr_locks[key] = final_id
            elif "." not in name:
                if func is not None:
                    final_id = (
                        lock_id
                        or f"{self.mod.name}:{func.qualname}.{name}"
                    )
                    func.local_locks[name] = final_id
                else:
                    final_id = lock_id or f"{self.mod.name}:{name}"
                    self.mod.module_locks[name] = final_id
            if ctor == "threading.RLock" and final_id is not None:
                self.mod.rlock_ids.add(final_id)
        elif ctor == "threading.Thread" and func is not None:
            if "." not in name:
                func.thread_vars.add(name)
            elif name.startswith("self.") and self.class_stack:
                # attribute-held thread: track under its attr text so
                # ``self._worker.join(...)`` anywhere in the class counts
                func.thread_vars.add(name)
        elif ctor in QUEUE_CTORS and func is not None and "." not in name:
            func.queue_vars.add(name)
        elif ctor == "functools.partial" and func is not None:
            if isinstance(value, ast.Call) and value.args:
                tgt = dotted(value.args[0])
                if tgt and "." not in name:
                    func.partial_targets[name] = tgt

    # -- visitors ------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.mod.classes.setdefault(node.name, [])
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node) -> None:
        qual = self._qual(node.name)
        args = node.args
        params = tuple(
            a.arg
            for a in (
                list(args.posonlyargs)
                + list(args.args)
                + list(args.kwonlyargs)
            )
            if a.arg not in ("self", "cls")
        )
        info = FunctionInfo(
            module=self.mod,
            qualname=qual,
            node=node,
            class_name=self.class_stack[-1] if self.class_stack else None,
            parent=self.func_stack[-1] if self.func_stack else None,
            params=params,
        )
        self.mod.functions[qual] = info
        if self.class_stack:
            self.mod.classes[self.class_stack[-1]].append(qual)
        self.func_stack.append(info)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_assign(t, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_assign(node.target, node.value)
        self.generic_visit(node)


def calls_in(node: ast.AST, skip_nested: bool = True):
    """Every ast.Call under ``node``, excluding (by default) calls that
    only run inside nested function/class definitions — those execute
    later, not on this statement's path."""
    stack = [node]
    while stack:
        n = stack.pop()
        if skip_nested and n is not node and isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def names_in(node: ast.AST) -> Set[str]:
    """Bare names referenced anywhere under ``node`` (incl. nested
    defs: a closure capturing a variable keeps it alive/escaped)."""
    return {
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    }


# -- path-sensitive flow walking ---------------------------------------
#
# Exception-edge and return-path tracking over a function body: the
# walker enumerates execution paths statement by statement, modelling
# If/For/While branching (loops unrolled once), Try routing (exception
# edges flow to handlers, finalbody runs on every exit), and the three
# exit kinds the lifecycle passes care about — explicit ``return``,
# explicit ``raise``, and implicit exception edges escaping from calls.
# Subclasses own the state object and the per-statement effects; the
# walker owns control flow.

_MAX_FLOW_STATES = 48  # per-block path cap; beyond it paths are dropped

# exit kinds delivered to on_exit()
EXIT_RETURN = "return"
EXIT_RAISE = "raise"
EXIT_EXCEPTION = "exception"  # implicit: a call on the path may raise
EXIT_FALLTHROUGH = "fallthrough"

_LOOP_EXITS = ("break", "continue")


class FlowWalker:
    """Subclass contract:

    - ``copy_state(state)``: independent copy for a forked path.
    - ``state_key(state)``: hashable dedupe key (paths with equal keys
      merge; keeps path count bounded).
    - ``on_stmt(state, stmt)``: apply a simple statement's effects.
    - ``stmt_may_raise(state, stmt)``: True if an exception edge should
      fork off *before* the statement's effects apply.
    - ``assume(state, test, truth)``: refine ``state`` under branch
      condition ``test`` being ``truth``; return None for infeasible.
    - ``on_exit(state, kind, node)``: a path leaves the function
      (finalbodies already applied). ``kind`` is one of EXIT_*.
    """

    # -- subclass hooks ------------------------------------------------
    def copy_state(self, state):  # pragma: no cover - trivial default
        return dict(state)

    def state_key(self, state):  # pragma: no cover - trivial default
        return repr(state)

    def on_stmt(self, state, stmt) -> None:
        pass

    def stmt_may_raise(self, state, stmt) -> bool:
        return False

    def assume(self, state, test, truth: bool):
        return state

    def on_exit(self, state, kind: str, node: ast.AST) -> None:
        pass

    # -- driver --------------------------------------------------------
    def run(self, body: List[ast.stmt]) -> None:
        """Walk a function body from a fresh initial state."""
        states, exits = self._exec_block(body, [self.initial_state()])
        for st in states:
            self.on_exit(st, EXIT_FALLTHROUGH, body[-1] if body else None)
        for kind, node, st in exits:
            if kind in _LOOP_EXITS:  # stray break/continue: treat as end
                self.on_exit(st, EXIT_FALLTHROUGH, node)
            else:
                self.on_exit(st, kind, node)

    def initial_state(self):  # pragma: no cover - trivial default
        return {}

    def _dedupe(self, states):
        out, seen = [], set()
        for st in states:
            k = self.state_key(st)
            if k not in seen:
                seen.add(k)
                out.append(st)
            if len(out) >= _MAX_FLOW_STATES:
                break
        return out

    def _exec_block(self, stmts, states):
        """Returns ``(fallthrough_states, exits)`` where exits is a list
        of ``(kind, node, state)`` propagating past this block."""
        exits: List[Tuple[str, ast.AST, object]] = []
        for stmt in stmts:
            if not states:
                break
            next_states: List[object] = []
            for st in states:
                ft, ex = self._exec_stmt(stmt, st)
                next_states.extend(ft)
                exits.extend(ex)
            states = self._dedupe(next_states)
        return states, exits

    def _exec_stmt(self, stmt, state):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            self.on_stmt(state, stmt)  # closures can capture/escape vars
            return [state], []
        if isinstance(stmt, ast.Return):
            return [], [(EXIT_RETURN, stmt, state)]
        if isinstance(stmt, ast.Raise):
            return [], [(EXIT_RAISE, stmt, state)]
        if isinstance(stmt, ast.Break):
            return [], [("break", stmt, state)]
        if isinstance(stmt, ast.Continue):
            return [], [("continue", stmt, state)]
        if isinstance(stmt, ast.If):
            return self._exec_if(stmt, state)
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return self._exec_loop(stmt, state)
        if isinstance(stmt, ast.Try):
            return self._exec_try(stmt, state)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._exec_with(stmt, state)
        # simple statement: fork the exception edge off the pre-effect
        # state, then apply effects to the surviving path
        exits = []
        if self.stmt_may_raise(state, stmt):
            exits.append((EXIT_EXCEPTION, stmt, self.copy_state(state)))
        self.on_stmt(state, stmt)
        return [state], exits

    def _exec_if(self, stmt, state):
        t = self.assume(self.copy_state(state), stmt.test, True)
        f = self.assume(state, stmt.test, False)
        states, exits = [], []
        if t is not None:
            ft, ex = self._exec_block(stmt.body, [t])
            states.extend(ft)
            exits.extend(ex)
        if f is not None:
            ft, ex = self._exec_block(stmt.orelse, [f])
            states.extend(ft)
            exits.extend(ex)
        return states, exits

    def _exec_loop(self, stmt, state):
        zero = self.copy_state(state)  # zero-iteration path
        self.on_stmt(state, stmt)  # loop header effects (For target etc.)
        ft, ex = self._exec_block(stmt.body, [state])
        states = [zero]
        exits = []
        for kind, node, st in ex:
            if kind in _LOOP_EXITS:
                states.append(st)  # break/continue end up after the loop
            else:
                exits.append((kind, node, st))
        states.extend(ft)  # one-iteration fallthrough
        if stmt.orelse:
            states, ex2 = self._exec_block(stmt.orelse, self._dedupe(states))
            exits.extend(ex2)
        return self._dedupe(states), exits

    def _exec_with(self, stmt, state):
        exits = []
        if self.stmt_may_raise(state, stmt):
            exits.append((EXIT_EXCEPTION, stmt, self.copy_state(state)))
        self.on_stmt(state, stmt)
        ft, ex = self._exec_block(stmt.body, [state])
        exits.extend(ex)
        return ft, exits

    def _exec_try(self, stmt, state):
        body_ft, body_ex = self._exec_block(stmt.body, [state])
        after: List[object] = []
        exits: List[Tuple[str, ast.AST, object]] = []
        caught: List[object] = []
        for kind, node, st in body_ex:
            if kind in (EXIT_EXCEPTION, EXIT_RAISE) and stmt.handlers:
                caught.append(st)
            else:
                exits.append((kind, node, st))
        # handlers: conservatively assume a present handler catches the
        # edge (broad excepts dominate this codebase); a Raise inside
        # the handler body re-escapes naturally
        if caught and stmt.handlers:
            for h in stmt.handlers:
                for st in self._dedupe(caught):
                    hst = self.copy_state(st)
                    self.on_stmt(hst, h)  # ``except E as e:`` binding
                    ft, ex = self._exec_block(h.body, [hst])
                    after.extend(ft)
                    exits.extend(ex)
        if stmt.orelse:
            body_ft, ex = self._exec_block(stmt.orelse, body_ft)
            exits.extend(ex)
        after.extend(body_ft)
        if stmt.finalbody:
            # finalbody runs on normal completion AND on every
            # propagating exit; its own exits replace the pending one
            after, fex = self._exec_block(stmt.finalbody, self._dedupe(after))
            exits_out: List[Tuple[str, ast.AST, object]] = list(fex)
            for kind, node, st in exits:
                ft, fex2 = self._exec_block(stmt.finalbody, [st])
                exits_out.extend(fex2)
                for st2 in ft:
                    exits_out.append((kind, node, st2))
            exits = exits_out
        return self._dedupe(after), exits


class PackageIndex:
    """All scanned modules plus cross-module lookup."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}

    def add_source(self, path: str, source: str, name: str) -> ModuleInfo:
        tree = ast.parse(source, filename=path)
        mod = ModuleInfo(
            path=path, name=name, tree=tree, lines=source.splitlines()
        )
        _collect_imports(mod)
        # two indexing passes: locks discovered in ``__init__`` must be
        # visible when other (earlier) methods resolve them
        _Indexer(mod).visit(mod.tree)
        _Indexer(mod).visit(mod.tree)
        self.modules[name] = mod
        return mod

    def add_file(self, path: Path, report_path: str) -> ModuleInfo:
        return self.add_source(
            report_path,
            path.read_text(encoding="utf-8"),
            module_name_for(path),
        )

    def find_module(self, dotted_name: str) -> Optional[ModuleInfo]:
        m = self.modules.get(dotted_name)
        if m is not None:
            return m
        for name, mod in self.modules.items():
            if name.endswith(f".{dotted_name}") or dotted_name.endswith(
                f".{name}"
            ):
                return mod
        return None

    # -- call resolution ----------------------------------------------
    def resolve_call(
        self, func: FunctionInfo, call: ast.Call
    ) -> Tuple[str, Optional[FunctionInfo]]:
        """Returns ``(expanded_text, target)`` where target is the
        package-local FunctionInfo when resolvable, else None."""
        text = dotted(call.func)
        if text is None:
            return "", None
        mod = func.module
        expanded = mod.expand(text)
        # self.method() -> same-class method
        if text.startswith("self.") and func.class_name:
            rest = text[5:]
            if "." not in rest:
                tgt = mod.functions.get(f"{func.class_name}.{rest}")
                return f"{mod.name}:{func.class_name}.{rest}", tgt
            return expanded, None
        if "." not in text:
            # nested def in the lexical chain
            f: Optional[FunctionInfo] = func
            while f is not None:
                tgt = mod.functions.get(f"{f.qualname}.{text}")
                if tgt is not None:
                    return tgt.label, tgt
                f = f.parent
            # module-level function or class-level sibling
            tgt = mod.functions.get(text)
            if tgt is None and func.class_name:
                tgt = mod.functions.get(f"{func.class_name}.{text}")
            if tgt is not None:
                return tgt.label, tgt
            # imported symbol: from pkg.mod import fn
            imp = mod.imports.get(text)
            if imp and "." in imp:
                owner, _, sym = imp.rpartition(".")
                target_mod = self.find_module(owner)
                if target_mod is not None:
                    tgt = target_mod.functions.get(sym)
                    if tgt is not None:
                        return tgt.label, tgt
            return expanded, None
        # alias.func() where alias maps to a scanned module
        head, _, rest = text.partition(".")
        imp = mod.imports.get(head)
        if imp and "." not in rest:
            target_mod = self.find_module(imp)
            if target_mod is not None:
                tgt = target_mod.functions.get(rest)
                if tgt is not None:
                    return tgt.label, tgt
        return expanded, None

    def resolve_callable_ref(
        self, func: FunctionInfo, expr: ast.AST
    ) -> Tuple[str, Optional[FunctionInfo]]:
        """Resolve a *reference* to a callable (a ``Thread(target=...)``
        operand, not a call site): ``self.method``, bare names through
        the lexical chain / module / imports / ``functools.partial``
        bindings, ``alias.func`` through the import map."""
        if isinstance(expr, ast.Call):
            # functools.partial(fn, ...) passed inline as the target
            if func.module.expand(
                dotted(expr.func) or ""
            ) == "functools.partial" and expr.args:
                return self.resolve_callable_ref(func, expr.args[0])
            return dotted(expr.func) or "", None
        if isinstance(expr, ast.Lambda):
            return "<lambda>", None
        text = dotted(expr)
        if text is None:
            return "", None
        mod = func.module
        if text.startswith("self.") and func.class_name:
            rest = text[5:]
            if "." not in rest:
                tgt = mod.functions.get(f"{func.class_name}.{rest}")
                if tgt is not None:
                    return tgt.label, tgt
            return mod.expand(text), None
        if "." not in text:
            f: Optional[FunctionInfo] = func
            while f is not None:
                tgt = mod.functions.get(f"{f.qualname}.{text}")
                if tgt is not None:
                    return tgt.label, tgt
                if text in f.partial_targets:
                    inner = f.partial_targets[text]
                    tgt = mod.functions.get(inner)
                    if tgt is None and func.class_name:
                        tgt = mod.functions.get(
                            f"{func.class_name}.{inner}"
                        )
                    if tgt is not None:
                        return tgt.label, tgt
                f = f.parent
            tgt = mod.functions.get(text)
            if tgt is None and func.class_name:
                tgt = mod.functions.get(f"{func.class_name}.{text}")
            if tgt is not None:
                return tgt.label, tgt
            imp = mod.imports.get(text)
            if imp and "." in imp:
                owner, _, sym = imp.rpartition(".")
                target_mod = self.find_module(owner)
                if target_mod is not None:
                    tgt = target_mod.functions.get(sym)
                    if tgt is not None:
                        return tgt.label, tgt
            return mod.expand(text), None
        head, _, rest = text.partition(".")
        imp = mod.imports.get(head)
        if imp and "." not in rest:
            target_mod = self.find_module(imp)
            if target_mod is not None:
                tgt = target_mod.functions.get(rest)
                if tgt is not None:
                    return tgt.label, tgt
        return mod.expand(text), None

    def called_labels(self) -> Set[str]:
        """Labels of every function that is the resolved target of at
        least one call anywhere in the scanned tree. Functions *not* in
        this set have no visible in-package caller — the races pass
        treats them as reachable from the main thread."""
        out: Set[str] = set()
        for mod in self.modules.values():
            for func in mod.functions.values():
                for call in calls_in(func.node, skip_nested=False):
                    _, tgt = self.resolve_call(func, call)
                    if tgt is not None:
                        out.add(tgt.label)
        return out
