"""Data-race and atomicity analysis over the thread fleet.

Three cooperating pieces over the shared AST index:

- **thread-root inventory** — every ``threading.Thread(target=...)``
  and ``threading.Timer`` spawn site in the tree, resolved to its
  package-local target where possible, with daemon / loop-spawn flags
  (``python -m sutro_tpu.analysis --threads`` dumps it).
- **Eraser-style lockset pass** (``shared-state-unlocked``,
  ``lockset-inconsistent``) — an interprocedural walk from every root
  (each spawned target, plus one ``<main>`` pseudo-root covering the
  functions with no resolvable in-package caller) records each
  ``self.<field>`` read/write together with the set of locks held.
  A field touched from two distinct roots (or one root spawned in a
  loop) with at least one non-exempt write and an empty pairwise
  lockset intersection is a race: ``shared-state-unlocked`` when one
  side holds nothing at all, ``lockset-inconsistent`` when both sides
  lock — just not the same lock.
- **atomicity pass** (``check-then-act``) — two sequential ``with``
  blocks on the same lock in one function where the first reads a
  field into a local and the second writes the field using that local:
  the classic dropped-update window across a release/reacquire.

Engine-aware happens-before edges keep the lockset pass honest:

- *queue/event handoff*: a function that touches a sync-object field
  (``self.q.put/get``, ``self.evt.set/wait``) holds a pseudo-lock
  token ``hb:<field>`` for its accesses, so producer/consumer pairs
  synchronised through that object intersect on the token.
- *publication*: accesses in the function that spawns root R are
  ordered before R until R's ``.start()`` call, and everything in
  ``__init__`` is ordered before roots the class spawns elsewhere
  (the constructor completes before anyone can call ``.start()``).
- *bounded join*: accesses after ``t.join(...)`` in the same function
  are ordered after root ``t``.
- sync-object fields themselves (locks, queues, events, threads,
  condition variables) are internally serialized and never tracked
  as shared state.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .callgraph import (
    EVENT_CTORS,
    LOCK_CTORS,
    QUEUE_CTORS,
    THREAD_CTORS,
    FunctionInfo,
    PackageIndex,
    calls_in,
    dotted,
    looks_like_lock,
)
from .core import Finding
from .locks import resolve_lock_expr

_MAX_DEPTH = 8

# fields holding these are synchronization/thread objects, not shared
# state — their own methods serialize internally
_SYNC_CTORS = (
    set(LOCK_CTORS) | set(QUEUE_CTORS) | THREAD_CTORS | EVENT_CTORS
)

# method calls on a field that mutate the underlying container
_MUTATORS = {
    "append",
    "appendleft",
    "add",
    "clear",
    "discard",
    "extend",
    "insert",
    "pop",
    "popleft",
    "popitem",
    "remove",
    "setdefault",
    "update",
}

# any method call on a sync-object field grants the function the
# field's happens-before token (put/get, set/wait/clear, join, ...)
_HB_CTORS = set(QUEUE_CTORS) | {"threading.Event"}

# ctors whose instances a mutator-method call actually mutates in
# place; a ``.update()``/``.pop()`` on a package-local class (JobStore,
# MetricsBus, ...) is a domain call that synchronizes internally —
# its own fields are analyzed separately
_CONTAINER_CTORS = {
    "set",
    "dict",
    "list",
    "frozenset",
    "collections.deque",
    "collections.OrderedDict",
    "collections.defaultdict",
    "collections.Counter",
}

MAIN_ROOT = "<main>"


@dataclasses.dataclass
class ThreadRoot:
    """One distinct spawn target (sites spawning the same target
    merge into a single root)."""

    root_id: str  # target label, or spawn-site text when unresolved
    target: Optional[FunctionInfo]
    kind: str  # "thread" | "timer" | "main"
    sites: List[Tuple[str, int, str]] = dataclasses.field(
        default_factory=list
    )  # (path, line, spawning function label)
    daemon: bool = False
    multi: bool = False  # spawned in a loop or from >1 site

    def describe(self) -> str:
        flags = []
        if self.daemon:
            flags.append("daemon")
        if self.multi:
            flags.append("multi")
        where = ", ".join(f"{p}:{ln}" for p, ln, _ in self.sites[:3])
        extra = f" (+{len(self.sites) - 3} more)" if len(
            self.sites
        ) > 3 else ""
        tag = f" [{'/'.join(flags)}]" if flags else ""
        return f"{self.kind:6s} {self.root_id}{tag} <- {where}{extra}"


@dataclasses.dataclass
class _Spawn:
    """One spawn site, before merging into roots."""

    root_id: str
    target: Optional[FunctionInfo]
    kind: str
    path: str
    line: int
    spawner: FunctionInfo
    var: Optional[str]  # ``t`` / ``self._worker`` when assigned
    daemon: bool
    in_loop: bool
    started_inline: bool  # ``threading.Thread(...).start()``


@dataclasses.dataclass
class _Access:
    field: str  # "{mod}:{Class}.{attr}"
    attr: str  # "{Class}.{attr}"
    write: bool
    root: str
    locks: FrozenSet[str]
    path: str
    line: int
    symbol: str
    before: FrozenSet[str]  # roots this access is ordered before
    after: FrozenSet[str]  # roots this access is ordered after

    def ordered_against(self, root: str) -> bool:
        return root in self.before or root in self.after


def _bool_kw(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name:
            return isinstance(
                kw.value, ast.Constant
            ) and kw.value.value is True
    return False


def _target_expr(call: ast.Call, ctor: str) -> Optional[ast.AST]:
    if ctor == "threading.Timer":
        for kw in call.keywords:
            if kw.arg == "function":
                return kw.value
        return call.args[1] if len(call.args) > 1 else None
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return call.args[0] if call.args else None


class _RaceAnalysis:
    def __init__(self, index: PackageIndex):
        self.index = index
        self.spawns: List[_Spawn] = []
        self.roots: Dict[str, ThreadRoot] = {}
        self.accesses: Dict[str, List[_Access]] = {}
        self.findings: List[Finding] = []
        # per-root interprocedural visited set
        self._visited: Set[Tuple] = set()
        # roots spawned from each class: "{mod}:{Class}" -> root ids
        self._class_roots: Dict[str, Set[str]] = {}
        self._called: Set[str] = set()
        self._hb_cache: Dict[str, FrozenSet[str]] = {}

    # -- inventory -----------------------------------------------------
    def collect_roots(self) -> None:
        for mod in sorted(
            self.index.modules.values(), key=lambda m: m.path
        ):
            for qual in sorted(mod.functions):
                func = mod.functions[qual]
                self._collect_spawns_in(func)
        for sp in self.spawns:
            root = self.roots.get(sp.root_id)
            if root is None:
                root = ThreadRoot(
                    root_id=sp.root_id, target=sp.target, kind=sp.kind
                )
                self.roots[sp.root_id] = root
            root.sites.append((sp.path, sp.line, sp.spawner.label))
            root.daemon = root.daemon or sp.daemon
            root.multi = (
                root.multi or sp.in_loop or len(root.sites) > 1
            )
            if sp.spawner.class_name:
                key = (
                    f"{sp.spawner.module.name}:"
                    f"{sp.spawner.class_name}"
                )
                self._class_roots.setdefault(key, set()).add(
                    sp.root_id
                )

    def _collect_spawns_in(self, func: FunctionInfo) -> None:
        mod = func.module
        # calls_in() walks whole subtrees and scan() recurses into the
        # same statements, so a nested ctor call is yielded once per
        # ancestor level — dedupe by node identity (first visit wins:
        # it is the one with the assignment var in scope)
        seen_calls: Set[int] = set()

        def scan(node: ast.AST, in_loop: bool) -> None:
            for stmt in ast.iter_child_nodes(node):
                if isinstance(
                    stmt,
                    (
                        ast.FunctionDef,
                        ast.AsyncFunctionDef,
                        ast.ClassDef,
                        ast.Lambda,
                    ),
                ):
                    continue
                loop_here = in_loop or isinstance(
                    stmt, (ast.For, ast.AsyncFor, ast.While)
                )
                var: Optional[str] = None
                value: Optional[ast.AST] = None
                if isinstance(stmt, ast.Assign) and len(
                    stmt.targets
                ) == 1:
                    var = dotted(stmt.targets[0])
                    value = stmt.value
                for call in calls_in(stmt):
                    ctor = mod.expand(dotted(call.func) or "")
                    started_inline = False
                    if ctor not in THREAD_CTORS:
                        # threading.Thread(...).start() in one step
                        if (
                            isinstance(call.func, ast.Attribute)
                            and call.func.attr == "start"
                            and isinstance(call.func.value, ast.Call)
                        ):
                            inner = call.func.value
                            ictor = mod.expand(
                                dotted(inner.func) or ""
                            )
                            if ictor in THREAD_CTORS:
                                ctor = ictor
                                call = inner
                                started_inline = True
                            else:
                                continue
                        else:
                            continue
                    tgt_expr = _target_expr(call, ctor)
                    text, target = ("", None)
                    if tgt_expr is not None:
                        text, target = (
                            self.index.resolve_callable_ref(
                                func, tgt_expr
                            )
                        )
                    if id(call) in seen_calls:
                        continue
                    seen_calls.add(id(call))
                    root_id = (
                        target.label
                        if target is not None
                        else text
                        or f"{mod.path}:{call.lineno}"
                    )
                    self.spawns.append(
                        _Spawn(
                            root_id=root_id,
                            target=target,
                            kind=(
                                "timer"
                                if ctor == "threading.Timer"
                                else "thread"
                            ),
                            path=mod.path,
                            line=call.lineno,
                            spawner=func,
                            var=(
                                var
                                if value is not None
                                and call is value
                                else None
                            ),
                            daemon=_bool_kw(call, "daemon"),
                            in_loop=loop_here,
                            started_inline=started_inline,
                        )
                    )
                scan(stmt, loop_here)

        scan(func.node, False)

    # -- field classification -----------------------------------------
    def _field_of(
        self, func: FunctionInfo, node: ast.Attribute
    ) -> Optional[Tuple[str, str]]:
        """``self.<attr>`` in a method -> (field_key, attr_key), with
        sync objects, locks, and methods filtered out."""
        if not (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and func.class_name
        ):
            return None
        mod = func.module
        attr_key = f"{func.class_name}.{node.attr}"
        if attr_key in mod.functions:  # method reference, not state
            return None
        if attr_key in mod.attr_locks or looks_like_lock(node.attr):
            return None
        if mod.attr_ctors.get(attr_key) in _SYNC_CTORS:
            return None
        return f"{mod.name}:{attr_key}", attr_key

    def _is_container(
        self, func: FunctionInfo, node: ast.AST
    ) -> bool:
        """True when ``self.<attr>`` is known (or assumed) to hold a
        plain container, so mutator-method calls write the field."""
        if not (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and func.class_name
        ):
            return True  # non-field receivers keep old behaviour
        ctor = func.module.attr_ctors.get(
            f"{func.class_name}.{node.attr}"
        )
        return ctor is None or ctor in _CONTAINER_CTORS

    def _hb_tokens(self, func: FunctionInfo) -> FrozenSet[str]:
        """Happens-before pseudo-locks granted to every access in
        ``func``: one token per sync-object field the function calls a
        method on (queue put/get, event set/wait, ...)."""
        cached = self._hb_cache.get(func.label)
        if cached is not None:
            return cached
        toks: Set[str] = set()
        mod = func.module
        if func.class_name:
            for call in calls_in(func.node):
                if not isinstance(call.func, ast.Attribute):
                    continue
                recv = dotted(call.func.value)
                if not recv or not recv.startswith("self."):
                    continue
                attr = recv[5:]
                if "." in attr:
                    continue
                attr_key = f"{func.class_name}.{attr}"
                if mod.attr_ctors.get(attr_key) in _HB_CTORS:
                    toks.add(f"hb:{mod.name}:{attr_key}")
        out = frozenset(toks)
        self._hb_cache[func.label] = out
        return out

    # -- access walk ---------------------------------------------------
    def walk_all(self) -> None:
        self._called = self.index.called_labels()
        target_labels = {
            r.target.label
            for r in self.roots.values()
            if r.target is not None
        }
        # each resolved spawn target is a root
        for root in self.roots.values():
            if root.target is None:
                continue
            self._visited.clear()
            self._walk_function(
                root.target,
                root.root_id,
                held=frozenset(),
                before=frozenset(),
                after=frozenset(),
                depth=0,
            )
        # one <main> pseudo-root from every function with no visible
        # in-package caller (conservative: unresolvable call sites
        # leave the callee main-reachable)
        self._visited.clear()
        for mod in sorted(
            self.index.modules.values(), key=lambda m: m.path
        ):
            for qual in sorted(mod.functions):
                func = mod.functions[qual]
                if func.label in self._called:
                    continue
                if func.label in target_labels:
                    continue
                before: FrozenSet[str] = frozenset()
                if func.class_name and func.qualname.endswith(
                    "__init__"
                ):
                    # the ctor completes before anyone can .start()
                    # a thread this class spawns elsewhere
                    before = frozenset(
                        self._class_roots.get(
                            f"{mod.name}:{func.class_name}",
                            (),
                        )
                    )
                self._walk_function(
                    func,
                    MAIN_ROOT,
                    held=frozenset(),
                    before=before,
                    after=frozenset(),
                    depth=0,
                )

    def _spawn_vars_for(
        self, func: FunctionInfo
    ) -> Dict[str, str]:
        """thread-variable text -> root id, visible from ``func``:
        locals assigned in this function plus ``self.<attr>`` threads
        spawned anywhere in the same class."""
        out: Dict[str, str] = {}
        for sp in self.spawns:
            if sp.var is None:
                continue
            if sp.spawner is func:
                out[sp.var] = sp.root_id
            elif (
                sp.var.startswith("self.")
                and func.class_name
                and sp.spawner.class_name == func.class_name
                and sp.spawner.module is func.module
            ):
                out[sp.var] = sp.root_id
        return out

    def _walk_function(
        self,
        func: FunctionInfo,
        root: str,
        held: FrozenSet[str],
        before: FrozenSet[str],
        after: FrozenSet[str],
        depth: int,
    ) -> None:
        key = (root, func.label, held, before, after)
        if key in self._visited or depth > _MAX_DEPTH:
            return
        self._visited.add(key)
        held = held | self._hb_tokens(func)
        spawn_vars = self._spawn_vars_for(func)
        # roots spawned *in this function*: ordered-after this
        # function's accesses until their .start() is seen
        local_pre: Set[str] = {
            sp.root_id
            for sp in self.spawns
            if sp.spawner is func and not sp.started_inline
        }
        state = {
            "before": set(before) | local_pre,
            "after": set(after),
        }
        self._visit(func, func.node.body, root, held, state, depth)

    def _visit(
        self,
        func: FunctionInfo,
        body: List[ast.AST],
        root: str,
        held: FrozenSet[str],
        state: Dict[str, Set[str]],
        depth: int,
    ) -> None:
        for stmt in body:
            self._visit_node(func, stmt, root, held, state, depth)

    def _visit_node(
        self,
        func: FunctionInfo,
        node: ast.AST,
        root: str,
        held: FrozenSet[str],
        state: Dict[str, Set[str]],
        depth: int,
    ) -> None:
        if isinstance(
            node,
            (
                ast.FunctionDef,
                ast.AsyncFunctionDef,
                ast.Lambda,
                ast.ClassDef,
            ),
        ):
            return  # deferred execution
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                lock_id = resolve_lock_expr(func, item.context_expr)
                if lock_id is not None:
                    new_held = new_held | {lock_id}
                else:
                    self._visit_node(
                        func,
                        item.context_expr,
                        root,
                        held,
                        state,
                        depth,
                    )
            self._visit(
                func, list(node.body), root, new_held, state, depth
            )
            return
        if isinstance(node, ast.Assign):
            self._visit_node(
                func, node.value, root, held, state, depth
            )
            for t in node.targets:
                self._record_store(func, t, root, held, state)
            return
        if isinstance(node, ast.AugAssign):
            self._visit_node(
                func, node.value, root, held, state, depth
            )
            # read-modify-write: record both sides
            self._record_load(func, node.target, root, held, state)
            self._record_store(
                func, node.target, root, held, state
            )
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._record_store(func, t, root, held, state)
            return
        if isinstance(node, ast.Call):
            self._handle_call(func, node, root, held, state, depth)
            return
        if isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            self._record_access(
                func, node, False, root, held, state
            )
            self._visit_node(
                func, node.value, root, held, state, depth
            )
            return
        for child in ast.iter_child_nodes(node):
            self._visit_node(func, child, root, held, state, depth)

    def _record_store(
        self,
        func: FunctionInfo,
        target: ast.AST,
        root: str,
        held: FrozenSet[str],
        state: Dict[str, Set[str]],
    ) -> None:
        if isinstance(target, ast.Attribute):
            self._record_access(
                func, target, True, root, held, state
            )
        elif isinstance(target, ast.Subscript):
            # self.x[k] = v mutates self.x
            if isinstance(target.value, ast.Attribute):
                self._record_access(
                    func, target.value, True, root, held, state
                )
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_store(func, elt, root, held, state)
        elif isinstance(target, ast.Starred):
            self._record_store(
                func, target.value, root, held, state
            )

    def _record_load(
        self,
        func: FunctionInfo,
        target: ast.AST,
        root: str,
        held: FrozenSet[str],
        state: Dict[str, Set[str]],
    ) -> None:
        node = target
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            self._record_access(
                func, node, False, root, held, state
            )

    def _record_access(
        self,
        func: FunctionInfo,
        node: ast.Attribute,
        write: bool,
        root: str,
        held: FrozenSet[str],
        state: Dict[str, Set[str]],
    ) -> None:
        resolved = self._field_of(func, node)
        if resolved is None:
            return
        field, attr_key = resolved
        self.accesses.setdefault(field, []).append(
            _Access(
                field=field,
                attr=attr_key,
                write=write,
                root=root,
                locks=held,
                path=func.module.path,
                line=node.lineno,
                symbol=func.label,
                before=frozenset(state["before"]),
                after=frozenset(state["after"]),
            )
        )

    def _handle_call(
        self,
        func: FunctionInfo,
        call: ast.Call,
        root: str,
        held: FrozenSet[str],
        state: Dict[str, Set[str]],
        depth: int,
    ) -> None:
        raw = dotted(call.func) or ""
        # .start()/.join() on a tracked thread variable flips the
        # publication/join ordering for the rest of this function
        if raw.endswith(".start") or raw.endswith(".join"):
            recv = raw.rsplit(".", 1)[0]
            rid = self._spawn_vars_for(func).get(recv)
            if rid is not None:
                if raw.endswith(".start"):
                    state["before"].discard(rid)
                else:
                    state["after"].add(rid)
        # mutator method on a field: self.x.append(...) writes x
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in _MUTATORS and isinstance(
                call.func.value, ast.Attribute
            ) and self._is_container(func, call.func.value):
                self._record_access(
                    func,
                    call.func.value,
                    True,
                    root,
                    held,
                    state,
                )
            else:
                self._visit_node(
                    func, call.func.value, root, held, state, depth
                )
        # arguments (and the receiver chain) carry reads
        for arg in call.args:
            self._visit_node(func, arg, root, held, state, depth)
        for kw in call.keywords:
            self._visit_node(
                func, kw.value, root, held, state, depth
            )
        # interprocedural propagation
        _, target = self.index.resolve_call(func, call)
        if target is not None:
            self._walk_function(
                target,
                root,
                held=held,
                before=frozenset(state["before"]),
                after=frozenset(state["after"]),
                depth=depth + 1,
            )

    # -- lockset verdicts ---------------------------------------------
    def lockset_findings(self) -> None:
        for field in sorted(self.accesses):
            accs = self.accesses[field]
            pair = self._best_conflict(accs)
            if pair is None:
                continue
            a, b = pair
            unlocked = not a.locks or not b.locks
            rule = (
                "shared-state-unlocked"
                if unlocked
                else "lockset-inconsistent"
            )
            short = field.split(":", 1)[-1]
            self.findings.append(
                Finding(
                    rule=rule,
                    path=a.path,
                    line=a.line,
                    symbol=a.symbol,
                    key=short,
                    message=(
                        f"`{short}` {_kind(a)} by {_who(a)} "
                        f"holding {_locks(a)} and {_kind(b)} by "
                        f"{_who(b)} at {b.path}:{b.line} holding "
                        f"{_locks(b)} — no common lock or "
                        "happens-before edge"
                    ),
                )
            )

    def _best_conflict(
        self, accs: List[_Access]
    ) -> Optional[Tuple[_Access, _Access]]:
        """Deterministic worst conflicting pair for one field, or
        None. Preference: a pair with an unlocked write first, then
        any unlocked access, then inconsistent locksets."""
        best: Optional[Tuple[int, _Access, _Access]] = None
        seen: Set[Tuple] = set()
        for a in accs:
            for b in accs:
                if not self._conflicts(a, b):
                    continue
                # canonical orientation: flag the write (prefer the
                # unlocked one) as the primary site
                x, y = a, b
                if (y.write, not y.locks) > (x.write, not x.locks):
                    x, y = y, x
                sig = (x.path, x.line, x.root, y.path, y.line, y.root)
                if sig in seen:
                    continue
                seen.add(sig)
                rank = (
                    0
                    if (x.write and not x.locks)
                    else 1
                    if (not x.locks or not y.locks)
                    else 2
                )
                cand = (rank, x, y)
                if best is None or (
                    cand[0],
                    x.path,
                    x.line,
                    y.path,
                    y.line,
                ) < (
                    best[0],
                    best[1].path,
                    best[1].line,
                    best[2].path,
                    best[2].line,
                ):
                    best = cand
        return None if best is None else (best[1], best[2])

    def _conflicts(self, a: _Access, b: _Access) -> bool:
        if not (a.write or b.write):
            return False
        if a.root == b.root:
            root = self.roots.get(a.root)
            if root is None or not root.multi:
                return False
            if a is b and not a.write:
                return False
        if a.ordered_against(b.root) or b.ordered_against(a.root):
            return False
        if a.locks & b.locks:
            return False
        return True

    # -- atomicity -----------------------------------------------------
    def atomicity_findings(self) -> None:
        for mod in sorted(
            self.index.modules.values(), key=lambda m: m.path
        ):
            for qual in sorted(mod.functions):
                self._check_then_act(mod.functions[qual])

    def _own_stmts(self, node: ast.AST) -> List[List[ast.stmt]]:
        """Every statement list in ``node``'s own body (nested defs
        excluded — they are indexed as their own functions)."""
        out: List[List[ast.stmt]] = []
        stack: List[ast.AST] = [node]
        while stack:
            n = stack.pop()
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(n, field, None)
                if isinstance(sub, list) and sub and isinstance(
                    sub[0], ast.stmt
                ):
                    out.append(sub)
            for h in getattr(n, "handlers", []) or []:
                out.append(h.body)
            for child in ast.iter_child_nodes(n):
                if isinstance(
                    child,
                    (
                        ast.FunctionDef,
                        ast.AsyncFunctionDef,
                        ast.ClassDef,
                        ast.Lambda,
                    ),
                ):
                    continue
                if isinstance(child, ast.stmt) or isinstance(
                    child, ast.excepthandler
                ):
                    stack.append(child)
        return out

    def _fields_read(
        self, func: FunctionInfo, node: ast.AST
    ) -> Set[str]:
        out: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and isinstance(
                sub.ctx, ast.Load
            ):
                resolved = self._field_of(func, sub)
                if resolved is not None:
                    out.add(resolved[1])
        return out

    def _fields_written(
        self, func: FunctionInfo, node: ast.AST
    ) -> Set[str]:
        out: Set[str] = set()
        for sub in ast.walk(node):
            tgt: Optional[ast.AST] = None
            if isinstance(sub, ast.Attribute) and isinstance(
                sub.ctx, (ast.Store, ast.Del)
            ):
                tgt = sub
            elif isinstance(sub, ast.Subscript) and isinstance(
                sub.ctx, (ast.Store, ast.Del)
            ):
                tgt = sub.value
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _MUTATORS
                and self._is_container(func, sub.func.value)
            ):
                tgt = sub.func.value
            if isinstance(tgt, ast.Attribute):
                resolved = self._field_of(func, tgt)
                if resolved is not None:
                    out.add(resolved[1])
        return out

    @staticmethod
    def _rebound_between(
        between: List[ast.stmt], var: str
    ) -> bool:
        """True when ``var`` is rebound to an unrelated value between
        the two lock blocks — a plain assignment whose right-hand side
        doesn't mention ``var`` severs the check-then-act data flow
        (``tok = build_fresh()``), while derivations (``cur = cur + 1``
        or ``cur += 1``) keep it."""
        for stmt in between:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Assign):
                    continue
                hit = any(
                    isinstance(t, ast.Name) and t.id == var
                    for t in sub.targets
                )
                if hit and var not in {
                    n.id
                    for n in ast.walk(sub.value)
                    if isinstance(n, ast.Name)
                }:
                    return True
        return False

    def _check_then_act(self, func: FunctionInfo) -> None:
        for body in self._own_stmts(func.node):
            withs: List[Tuple[int, ast.stmt, Set[str]]] = []
            for pos, stmt in enumerate(body):
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    ids = {
                        resolve_lock_expr(func, it.context_expr)
                        for it in stmt.items
                    }
                    ids.discard(None)
                    if ids:
                        withs.append((pos, stmt, ids))  # type: ignore[arg-type]
            for i, (p1, w1, l1) in enumerate(withs):
                reads: Dict[str, Set[str]] = {}
                for sub in ast.walk(w1):
                    if (
                        isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)
                    ):
                        fields = self._fields_read(func, sub.value)
                        if fields:
                            reads.setdefault(
                                sub.targets[0].id, set()
                            ).update(fields)
                if not reads:
                    continue
                for p2, w2, l2 in withs[i + 1 :]:
                    common = l1 & l2
                    if not common:
                        continue
                    written = self._fields_written(func, w2)
                    if not written:
                        continue
                    used = {
                        n.id
                        for n in ast.walk(w2)
                        if isinstance(n, ast.Name)
                    }
                    between = body[p1 + 1 : p2]
                    for var in sorted(reads):
                        hit = sorted(reads[var] & written)
                        if not hit or var not in used:
                            continue
                        if self._rebound_between(between, var):
                            continue
                        lock = sorted(common)[0].split(":", 1)[-1]
                        self.findings.append(
                            Finding(
                                rule="check-then-act",
                                path=func.module.path,
                                line=w2.lineno,
                                symbol=func.label,
                                key=f"{hit[0]}|{var}",
                                message=(
                                    f"`{var}` read from "
                                    f"`{hit[0]}` under `{lock}` at "
                                    f"line {w1.lineno} is used to "
                                    f"write `{hit[0]}` after the "
                                    "lock was released and "
                                    "re-acquired — the update can "
                                    "be lost to a concurrent "
                                    "writer in the window"
                                ),
                            )
                        )
                        break  # one finding per with-pair

    # -- entry ---------------------------------------------------------
    def run(self) -> List[Finding]:
        self.collect_roots()
        self.walk_all()
        self.lockset_findings()
        self.atomicity_findings()
        self.findings.sort(
            key=lambda f: (f.path, f.line, f.rule, f.key)
        )
        return self.findings


def _kind(a: _Access) -> str:
    return "written" if a.write else "read"


def _who(a: _Access) -> str:
    root = "main thread" if a.root == MAIN_ROOT else f"root {a.root}"
    return f"{a.symbol} ({root})"


def _locks(a: _Access) -> str:
    if not a.locks:
        return "no locks"
    names = sorted(x.split(":", 1)[-1] for x in a.locks)
    return "[" + ", ".join(names) + "]"


def run(index: PackageIndex) -> List[Finding]:
    return _RaceAnalysis(index).run()


def inventory(index: PackageIndex) -> List[ThreadRoot]:
    """The thread-root inventory alone (``--threads``)."""
    rr = _RaceAnalysis(index)
    rr.collect_roots()
    return sorted(rr.roots.values(), key=lambda r: r.root_id)
