"""Telemetry label-cardinality pass.

The metrics registry bounds every labelled metric's series count and
collapses overflow to ``("_overflow", ...)`` — but the *static* intent
matters too: a label whose values come from an unbounded domain (job
ids, row ids, request ids) churns the cap and destroys the series you
actually wanted, silently. Rule ``telemetry-cardinality``:

- a metric op (``.inc``/``.set``/``.observe``) passing a **non-constant
  label value** is only allowed when the metric's declaration carries
  an explicit ``max_series=`` — the declared fixed-cardinality
  whitelist budget (key ``<metric>:uncapped``);
- an **identifier-shaped** label value (``job_id``/``row_id``/
  ``req_id``-style names, f-strings, ``str(...)`` of a variable) is
  flagged even on capped metrics — identifiers never become labels,
  per-job numbers belong in JobCounters (key ``<metric>:identifier``).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional

from .callgraph import ModuleInfo, PackageIndex, dotted
from .core import Finding

_DECL_METHODS = ("counter", "gauge", "histogram")
_OPS = ("inc", "set", "observe")
_IDENT_RE = re.compile(
    r"(^|_)(job|row|req|request|trace|span)_?id$|^rid$|^uuid$", re.I
)


@dataclasses.dataclass
class _Decl:
    metric: str
    var: str
    labelled: bool
    capped: bool
    module: str
    line: int


def _collect_decls(index: PackageIndex) -> Dict[str, _Decl]:
    decls: Dict[str, _Decl] = {}
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            t = dotted(value.func) or ""
            if t.rsplit(".", 1)[-1] not in _DECL_METHODS or "." not in t:
                continue
            if not (
                value.args
                and isinstance(value.args[0], ast.Constant)
                and isinstance(value.args[0].value, str)
            ):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            names = [x.id for x in targets if isinstance(x, ast.Name)]
            if not names:
                continue
            kw = {k.arg: k.value for k in value.keywords if k.arg}
            labelled = "labels" in kw and not (
                isinstance(kw["labels"], (ast.Tuple, ast.List))
                and not kw["labels"].elts
            )
            decl = _Decl(
                metric=value.args[0].value,
                var=names[0],
                labelled=labelled,
                capped="max_series" in kw,
                module=mod.name,
                line=node.lineno,
            )
            prev = decls.get(names[0])
            if prev is not None and prev.capped and not decl.capped:
                decls[names[0]] = decl  # conservative: uncapped wins
            elif prev is None:
                decls[names[0]] = decl
    return decls


def _identifier_shaped(arg: ast.AST) -> Optional[str]:
    if isinstance(arg, ast.JoinedStr):
        return "f-string"
    if isinstance(arg, ast.Name) and _IDENT_RE.search(arg.id):
        return arg.id
    if isinstance(arg, ast.Attribute) and _IDENT_RE.search(arg.attr):
        return arg.attr
    if isinstance(arg, ast.Call):
        t = dotted(arg.func)
        if t == "str" and arg.args and not isinstance(
            arg.args[0], ast.Constant
        ):
            return "str(...)"
        if t is not None and t.endswith(".format"):
            return "format(...)"
    return None


def run(index: PackageIndex) -> List[Finding]:
    decls = _collect_decls(index)
    out: List[Finding] = []
    for mod in index.modules.values():
        for func in mod.functions.values():
            for n in ast.walk(func.node):
                if not isinstance(n, ast.Call):
                    continue
                t = dotted(n.func) or ""
                parts = t.split(".")
                if len(parts) < 2 or parts[-1] not in _OPS:
                    continue
                decl = decls.get(parts[-2])
                if decl is None:
                    continue
                labels = n.args[1:]
                for arg in labels:
                    ident = _identifier_shaped(arg)
                    if ident is not None:
                        out.append(
                            Finding(
                                rule="telemetry-cardinality",
                                path=func.module.path,
                                line=n.lineno,
                                message=f"identifier-shaped label value "
                                f"({ident}) on metric "
                                f"`{decl.metric}` — unbounded identifiers "
                                "never become labels (use JobCounters)",
                                symbol=func.label,
                                key=f"{decl.metric}:identifier",
                            )
                        )
                    elif not isinstance(arg, ast.Constant) and not decl.capped:
                        out.append(
                            Finding(
                                rule="telemetry-cardinality",
                                path=func.module.path,
                                line=n.lineno,
                                message=f"non-constant label value on "
                                f"metric `{decl.metric}` whose declaration "
                                "has no explicit max_series= cardinality "
                                "whitelist budget",
                                symbol=func.label,
                                key=f"{decl.metric}:uncapped",
                            )
                        )
    return out
