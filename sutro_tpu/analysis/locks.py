"""Lock-discipline pass.

Builds an inter-procedural lock graph from every ``threading.Lock`` /
``RLock`` / ``Condition`` acquisition in the scanned tree (``with``
statements; ``queue.Queue().mutex`` counts too), then reports:

- ``lock-order``          both (A, B) and (B, A) nesting observed
                          anywhere in the package (classic inversion)
- ``lock-reentrant``      a non-reentrant lock re-acquired on a call
                          path that already holds it
- ``lock-blocking-call``  a curated blocking operation (socket sends,
                          file/parquet I/O, ``time.sleep``, thread
                          joins, queue gets, futures) under a lock
- ``lock-callback``       an externally-supplied callable (a function
                          parameter, or an ``on_*``/``*callback*``
                          name) invoked under a lock

Held-lock state propagates through package-local calls (``self.m()``,
bare names including closures, ``mod.f()`` through imports) with a
depth cap; nested function *definitions* under a lock are not treated
as running under it.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import (
    FunctionInfo,
    PackageIndex,
    dotted,
    looks_like_lock,
)
from .core import Finding

_MAX_DEPTH = 8

BLOCKING_EXACT = {
    "time.sleep",
    "socket.create_connection",
    "socket.create_server",
    "os.replace",
    "os.rename",
    "json.load",
    "json.dump",
    "subprocess.run",
    "subprocess.check_call",
    "subprocess.check_output",
    "shutil.copy",
    "shutil.copy2",
    "shutil.copytree",
    "shutil.rmtree",
    "shutil.move",
    "select.select",
    "requests.get",
    "requests.post",
}
BLOCKING_SUFFIX = (
    ".sendall",
    ".recv",
    ".accept",
    ".connect",
    ".makefile",
    ".read_text",
    ".write_text",
    ".read_bytes",
    ".write_bytes",
    ".to_parquet",
    ".to_csv",
    ".read_parquet",
    ".read_csv",
    ".read_schema",
    ".communicate",
    ".urlopen",
)
_CALLBACKISH = re.compile(r"(^on_[a-z0-9_]+$)|callback|(^|_)cb$")


def _short(lock_id: str) -> str:
    return lock_id.split(":", 1)[-1]


def resolve_lock_expr(
    func: FunctionInfo, expr: ast.AST
) -> Optional[str]:
    """Stable identity of the lock named by ``expr`` in ``func``, or
    None when the expression doesn't look like a lock. Shared by the
    lock-discipline and data-race passes so both agree on which lock a
    ``with`` statement acquires."""
    text = dotted(expr)
    if text is None:
        return None
    mod = func.module
    if text.startswith("self.") and func.class_name:
        rest = text[5:]
        known = mod.attr_locks.get(f"{func.class_name}.{rest}")
        if known:
            return known
        if looks_like_lock(rest.split(".")[-1]):
            return f"{mod.name}:{func.class_name}.{rest}"
        return None
    if "." not in text:
        f: Optional[FunctionInfo] = func
        while f is not None:
            if text in f.local_locks:
                return f.local_locks[text]
            f = f.parent
        if text in mod.module_locks:
            return mod.module_locks[text]
        if looks_like_lock(text):
            return f"{mod.name}:{func.qualname}.{text}"
        return None
    # attribute chain on an arbitrary object: only accept clearly
    # lock-ish tails (e.g. ``jm.lock``, ``self._queue.mutex``).
    # Module-scoped identity (not per-function): the same chain text
    # in two functions is taken to mean the same lock, which is what
    # lets cross-function inversions on shared objects surface.
    tail = text.split(".")[-1]
    if looks_like_lock(tail):
        return f"{mod.name}:{text}"
    return None


class _LockWalker:
    def __init__(self, index: PackageIndex):
        self.index = index
        self.findings: List[Finding] = []
        self._seen_fp: Set[str] = set()
        # ordered pair -> list of (path, line, "A -> B while in symbol")
        self.pairs: Dict[
            Tuple[str, str], List[Tuple[str, int, str]]
        ] = {}
        self.rlocks: Set[str] = set()
        self._visited: Set[Tuple[str, frozenset]] = set()

    # -- lock resolution ----------------------------------------------
    def _resolve_lock(
        self, func: FunctionInfo, expr: ast.AST
    ) -> Optional[str]:
        return resolve_lock_expr(func, expr)

    # -- finding emission ---------------------------------------------
    def _emit(self, f: Finding) -> None:
        fp = f.fingerprint() + f"@{f.path}:{f.line}"
        if fp in self._seen_fp:
            return
        self._seen_fp.add(fp)
        self.findings.append(f)

    def _check_call(
        self,
        func: FunctionInfo,
        call: ast.Call,
        held: Tuple[Tuple[str, str], ...],
        chain: Tuple[str, ...],
        depth: int,
    ) -> None:
        text, target = self.index.resolve_call(func, call)
        raw = dotted(call.func) or ""
        via = (
            ""
            if len(chain) <= 1
            else f" (call chain {' -> '.join(chain)})"
        )
        held_names = ", ".join(_short(h[0]) for h in held)
        # blocking?
        blocking = text in BLOCKING_EXACT or any(
            text.endswith(s) for s in BLOCKING_SUFFIX
        )
        if not blocking and isinstance(call.func, ast.Name):
            if call.func.id == "open":
                blocking = True
        if not blocking and raw.endswith(".join"):
            recv = raw[: -len(".join")]
            f: Optional[FunctionInfo] = func
            while f is not None and not blocking:
                if recv in f.thread_vars:
                    blocking = True
                f = f.parent
        if not blocking and raw.endswith(".get"):
            recv = raw[: -len(".get")]
            f = func
            while f is not None and not blocking:
                if recv in f.queue_vars:
                    blocking = True
                f = f.parent
        if blocking:
            self._emit(
                Finding(
                    rule="lock-blocking-call",
                    path=func.module.path,
                    line=call.lineno,
                    symbol=func.label,
                    key=f"{_short(held[-1][0])}|{text or raw}",
                    message=(
                        f"blocking call `{raw}` while holding "
                        f"[{held_names}]{via}"
                    ),
                )
            )
            return
        # externally-supplied callback?
        cb_name: Optional[str] = None
        if isinstance(call.func, ast.Name):
            name = call.func.id
            if name in func.all_params() or _CALLBACKISH.search(name):
                cb_name = name
        elif raw.startswith("self.") and _CALLBACKISH.search(
            raw.split(".")[-1]
        ):
            # calling a stored callback attribute under a lock
            cb_name = raw
        if cb_name is not None and target is None:
            self._emit(
                Finding(
                    rule="lock-callback",
                    path=func.module.path,
                    line=call.lineno,
                    symbol=func.label,
                    key=f"{_short(held[-1][0])}|{cb_name}",
                    message=(
                        f"callback `{cb_name}` invoked while holding "
                        f"[{held_names}]{via}"
                    ),
                )
            )
            return
        # inter-procedural propagation
        if target is not None and depth < _MAX_DEPTH:
            key = (
                target.label,
                frozenset(h[0] for h in held),
            )
            if key in self._visited:
                return
            self._visited.add(key)
            self._walk_body(
                target,
                list(target.node.body),
                held,
                chain + (target.qualname,),
                depth + 1,
            )

    # -- statement walking --------------------------------------------
    def _walk_body(
        self,
        func: FunctionInfo,
        body: List[ast.AST],
        held: Tuple[Tuple[str, str], ...],
        chain: Tuple[str, ...],
        depth: int,
    ) -> None:
        for stmt in body:
            self._visit(func, stmt, held, chain, depth)

    def _visit(
        self,
        func: FunctionInfo,
        node: ast.AST,
        held: Tuple[Tuple[str, str], ...],
        chain: Tuple[str, ...],
        depth: int,
    ) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
        ):
            return  # deferred execution: not under this lock
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                lock_id = self._resolve_lock(func, item.context_expr)
                if lock_id is None:
                    # still look for calls inside the item expression
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call) and held:
                            self._check_call(
                                func, sub, held, chain, depth
                            )
                    continue
                site = (func.module.path, node.lineno)
                for held_id, _ in new_held:
                    if held_id == lock_id:
                        if lock_id not in self.rlocks:
                            self._emit(
                                Finding(
                                    rule="lock-reentrant",
                                    path=func.module.path,
                                    line=node.lineno,
                                    symbol=func.label,
                                    key=_short(lock_id),
                                    message=(
                                        f"`{_short(lock_id)}` re-"
                                        "acquired while already held "
                                        f"(chain {' -> '.join(chain)})"
                                    ),
                                )
                            )
                        continue
                    self.pairs.setdefault(
                        (held_id, lock_id), []
                    ).append((site[0], site[1], func.label))
                new_held = new_held + (
                    (lock_id, f"{site[0]}:{site[1]}"),
                )
            self._walk_body(func, list(node.body), new_held, chain, depth)
            return
        if isinstance(node, ast.Call):
            if held:
                self._check_call(func, node, held, chain, depth)
            for child in ast.iter_child_nodes(node):
                self._visit(func, child, held, chain, depth)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(func, child, held, chain, depth)

    # -- entry ---------------------------------------------------------
    def run(self) -> List[Finding]:
        # RLocks are reentrant: no lock-reentrant findings for them
        for mod in self.index.modules.values():
            self.rlocks.update(mod.rlock_ids)
        for mod in sorted(self.index.modules.values(), key=lambda m: m.path):
            for qual in sorted(mod.functions):
                func = mod.functions[qual]
                self._visited.clear()
                self._walk_body(
                    func, list(func.node.body), (), (qual,), 0
                )
        # inversions
        for (a, b), sites in sorted(self.pairs.items()):
            if a >= b:
                continue
            rev = self.pairs.get((b, a))
            if not rev:
                continue
            s1, s2 = sites[0], rev[0]
            self._emit(
                Finding(
                    rule="lock-order",
                    path=s1[0],
                    line=s1[1],
                    symbol=s1[2],
                    key=f"{_short(a)}<->{_short(b)}",
                    fp=f"lock-order|{_short(a)}<->{_short(b)}",
                    message=(
                        f"lock order inversion: `{_short(a)}` -> "
                        f"`{_short(b)}` at {s1[0]}:{s1[1]} but "
                        f"`{_short(b)}` -> `{_short(a)}` at "
                        f"{s2[0]}:{s2[1]}"
                    ),
                )
            )
        return self.findings


def run(index: PackageIndex) -> List[Finding]:
    return _LockWalker(index).run()
