"""graftlint CLI: ``python -m sutro_tpu.analysis [paths...]``.

Exit codes: 0 clean (vs baseline unless ``--no-baseline``), 1 new
findings, 2 usage/internal error.

``--diff <git-ref>`` restricts the report to findings on lines changed
vs the ref (fast pre-commit gate); ``--write-wire-schema`` regenerates
``analysis/wire_schema.json`` from the current senders (``make
lint-schema`` wraps it with an uncommitted-drift check);
``--format sarif`` emits SARIF 2.1.0 for code-scanning upload;
``--threads`` dumps the thread-root inventory the data-race pass
analyzes over.
"""

from __future__ import annotations

import argparse
import re
import subprocess
import sys
from pathlib import Path
from typing import Dict, Set

from . import core, protocol

_HUNK_RE = re.compile(r"^@@ -\d+(?:,\d+)? \+(\d+)(?:,(\d+))? @@")


def changed_lines(ref: str) -> Dict[str, Set[int]]:
    """Repo-root-relative path -> 1-based added/changed line numbers in
    the working tree vs ``ref`` (zero-context unified diff)."""
    root = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"],
        capture_output=True,
        text=True,
        check=True,
    ).stdout.strip()
    diff = subprocess.run(
        ["git", "-C", root, "diff", "--unified=0", ref, "--", "*.py"],
        capture_output=True,
        text=True,
        check=True,
    ).stdout
    out: Dict[str, Set[int]] = {}
    cur: Set[int] = set()
    for line in diff.splitlines():
        if line.startswith("+++ "):
            name = line[4:].strip()
            if name.startswith("b/"):
                name = name[2:]
            cur = out.setdefault(name, set()) if name != "/dev/null" else set()
        else:
            m = _HUNK_RE.match(line)
            if m:
                start = int(m.group(1))
                count = int(m.group(2)) if m.group(2) is not None else 1
                cur.update(range(start, start + count))
    return out


def _to_root_rel(path: str) -> str:
    try:
        root = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True,
            text=True,
            check=True,
        ).stdout.strip()
        return Path(path).resolve().relative_to(Path(root)).as_posix()
    except (subprocess.CalledProcessError, ValueError, OSError):
        return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sutro_tpu.analysis",
        description=(
            "graftlint: engine-aware static analysis (lock discipline, "
            "jit purity, thread/exception hygiene)"
        ),
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=["sutro_tpu"],
        help="files or directories to scan (default: sutro_tpu)",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text"
    )
    ap.add_argument(
        "--threads",
        action="store_true",
        help="print the thread-root inventory (every Thread/Timer "
        "spawn site with its resolved target) and exit 0",
    )
    ap.add_argument(
        "--baseline",
        default=str(core.DEFAULT_BASELINE),
        help="baseline file (default: sutro_tpu/analysis/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding; exit 1 if any",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write the current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--rules",
        default="",
        help="comma-separated rule subset (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    ap.add_argument(
        "--diff",
        metavar="GIT_REF",
        default=None,
        help="report only findings on lines changed vs GIT_REF "
        "(ignores the baseline; exit 1 if any)",
    )
    ap.add_argument(
        "--write-wire-schema",
        action="store_true",
        help="regenerate analysis/wire_schema.json from the current "
        "dp/elastic senders and exit 0",
    )
    ap.add_argument(
        "-v",
        "--verbose",
        action="store_true",
        help="also print baselined (non-new) findings",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(core.RULES.items()):
            print(f"{rule:24s} {desc}")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    for r in rules:
        if r not in core.RULES:
            print(f"graftlint: unknown rule {r!r}", file=sys.stderr)
            return 2
    paths = args.paths or ["sutro_tpu"]
    for p in paths:
        if not Path(p).exists():
            print(f"graftlint: no such path {p!r}", file=sys.stderr)
            return 2

    if args.threads:
        # inventory needs only the index, not the finding passes
        from . import races

        try:
            roots = races.inventory(core.build_index(paths))
        except SyntaxError as e:
            print(f"graftlint: parse error: {e}", file=sys.stderr)
            return 2
        for root in roots:
            print(root.describe())
        print(f"graftlint: {len(roots)} thread root(s)")
        return 0

    try:
        active, suppressed, index = core.analyze(paths, rules or None)
    except SyntaxError as e:
        print(f"graftlint: parse error: {e}", file=sys.stderr)
        return 2

    if args.write_wire_schema:
        doc = protocol.write_schema(index)
        print(
            f"graftlint: wrote {len(doc['frames'])} frame type(s) to "
            f"{protocol.DEFAULT_SCHEMA_PATH}"
        )
        return 0

    if args.diff is not None:
        try:
            changed = changed_lines(args.diff)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"graftlint: git diff failed: {e}", file=sys.stderr)
            return 2
        hits = [
            f
            for f in active
            if f.line in changed.get(_to_root_rel(f.path), ())
        ]
        if args.format == "json":
            print(core.render_json(hits, suppressed_count=len(suppressed)))
        elif args.format == "sarif":
            print(core.render_sarif(hits))
        else:
            for f in hits:
                print(f.render())
            print(
                f"graftlint: {len(hits)} finding(s) on lines changed "
                f"vs {args.diff}"
            )
        return 1 if hits else 0

    baseline_path = Path(args.baseline)
    if args.write_baseline:
        core.write_baseline(baseline_path, active)
        print(
            f"graftlint: wrote {len(active)} finding(s) to "
            f"{baseline_path}"
        )
        return 0

    if args.no_baseline or not baseline_path.exists():
        if args.format == "json":
            print(
                core.render_json(
                    active, suppressed_count=len(suppressed)
                )
            )
        elif args.format == "sarif":
            print(core.render_sarif(active))
        else:
            print(
                core.render_text(
                    active, suppressed_count=len(suppressed)
                )
            )
        if not args.no_baseline and not baseline_path.exists():
            print(
                f"graftlint: no baseline at {baseline_path} "
                "(create one with --write-baseline)",
                file=sys.stderr,
            )
        return 1 if active else 0

    baseline = core.load_baseline(baseline_path)
    new, stale = core.compare_baseline(active, baseline)
    if args.format == "json":
        print(
            core.render_json(
                active if args.verbose else new,
                new=new,
                stale=stale,
                suppressed_count=len(suppressed),
            )
        )
    elif args.format == "sarif":
        print(core.render_sarif(active if args.verbose else new))
    else:
        if args.verbose:
            for f in active:
                print(f.render())
        print(
            core.render_text(
                active,
                new=new,
                stale=stale,
                suppressed_count=len(suppressed),
            )
        )
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
