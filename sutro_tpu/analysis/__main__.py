"""graftlint CLI: ``python -m sutro_tpu.analysis [paths...]``.

Exit codes: 0 clean (vs baseline unless ``--no-baseline``), 1 new
findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import core


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sutro_tpu.analysis",
        description=(
            "graftlint: engine-aware static analysis (lock discipline, "
            "jit purity, thread/exception hygiene)"
        ),
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=["sutro_tpu"],
        help="files or directories to scan (default: sutro_tpu)",
    )
    ap.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    ap.add_argument(
        "--baseline",
        default=str(core.DEFAULT_BASELINE),
        help="baseline file (default: sutro_tpu/analysis/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding; exit 1 if any",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write the current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--rules",
        default="",
        help="comma-separated rule subset (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    ap.add_argument(
        "-v",
        "--verbose",
        action="store_true",
        help="also print baselined (non-new) findings",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(core.RULES.items()):
            print(f"{rule:24s} {desc}")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    for r in rules:
        if r not in core.RULES:
            print(f"graftlint: unknown rule {r!r}", file=sys.stderr)
            return 2
    paths = args.paths or ["sutro_tpu"]
    for p in paths:
        if not Path(p).exists():
            print(f"graftlint: no such path {p!r}", file=sys.stderr)
            return 2

    try:
        active, suppressed, _index = core.analyze(paths, rules or None)
    except SyntaxError as e:
        print(f"graftlint: parse error: {e}", file=sys.stderr)
        return 2

    baseline_path = Path(args.baseline)
    if args.write_baseline:
        core.write_baseline(baseline_path, active)
        print(
            f"graftlint: wrote {len(active)} finding(s) to "
            f"{baseline_path}"
        )
        return 0

    if args.no_baseline or not baseline_path.exists():
        if args.format == "json":
            print(
                core.render_json(
                    active, suppressed_count=len(suppressed)
                )
            )
        else:
            print(
                core.render_text(
                    active, suppressed_count=len(suppressed)
                )
            )
        if not args.no_baseline and not baseline_path.exists():
            print(
                f"graftlint: no baseline at {baseline_path} "
                "(create one with --write-baseline)",
                file=sys.stderr,
            )
        return 1 if active else 0

    baseline = core.load_baseline(baseline_path)
    new, stale = core.compare_baseline(active, baseline)
    if args.format == "json":
        print(
            core.render_json(
                active if args.verbose else new,
                new=new,
                stale=stale,
                suppressed_count=len(suppressed),
            )
        )
    else:
        if args.verbose:
            for f in active:
                print(f.render())
        print(
            core.render_text(
                active,
                new=new,
                stale=stale,
                suppressed_count=len(suppressed),
            )
        )
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
