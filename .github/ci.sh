#!/usr/bin/env bash
# CI entry point: build native helpers, compile-check, run the suite on
# CPU with 8 virtual devices. (The reference's CI was compileall only —
# .github/monorepo-ci.sh in /root/reference; SURVEY §4 calls for better.)
set -euo pipefail
cd "$(dirname "$0")/.."
make native
make compile-check
# tier-1 gate: graftlint static analysis vs the committed baseline —
# any new lock-discipline / jit-purity / hygiene / resource-lifecycle /
# kill-switch / wire-protocol / cardinality finding fails CI
make lint
# code-scanning artifact: the same findings as SARIF 2.1.0 for upload
# (warn-only — `make lint` above is the gate)
python -m sutro_tpu.analysis sutro_tpu --no-baseline --format sarif \
    > graftlint.sarif || true
# tier-1 gate: the committed wire-frame schema must match what the
# dp/elastic senders actually produce
make lint-schema
# tier-1 gate: seeded chaos subset — deterministic fault injection must
# keep reaching terminal states with partial-store consistency
make chaos
# tier-1 gate: telemetry — exporter golden file, flight-recorder
# reconciliation, and the telemetry-on/off host-overhead budget
make telemetry-check
# tier-1 gate: live monitor — SLO hysteresis/debounce, streaming doctor
# verdicts, tenant attribution, and the monitor tick-cost budget
# (zero sampling work with telemetry off, asserted in code)
make monitor-check
# tier-1 gate: enforcement control plane — tenant admission buckets,
# priority-ladder preemption, autotuner hysteresis, degradation to
# pass-through under injected controller faults, and the control-on/off
# host-overhead budget (zero cost with SUTRO_CONTROL=0)
make control-check
# tier-1 gate: cross-job radix prefix store — repeat-template jobs must
# prefill only the novel tail, bit-identically to the store-off engine,
# with exact page conservation under eviction pressure and lookup
# faults degrading to plain misses
make prefix-check
# tier-1 gate: tiered paged-KV pool + session hibernation — demote/
# promote and hibernate/resume must be bit-identical on the int8 pool,
# SUTRO_KV_TIERS=0 must be bit-identical with a zero tier-op census,
# and torn migrations (demote/promote/disk-write) must never corrupt
# or lose a row
make tier-check
# tier-1 gate: replica fleet front door — breaker discipline, health-
# checked routing with warm-prefix affinity, batch-job failover with
# zero rows lost or duplicated (bit-identical at temperature 0),
# mid-stream structured errors instead of silent hangs, protocol-skew
# degradation to probe-only routing, and the per-request routing-
# decision host budget (zero telemetry ops when off)
make fleet-check
# tier-1 gate: fleet observability plane — cross-replica trace
# stitching (X-Sutro-Trace propagation, golden Perfetto export, no
# negative gaps after skew re-anchoring), federated /metrics under the
# replica label with the _fleet aggregate and exemplar trace ids, the
# fleet monitor firing AND resolving stock SLO rules under live chaos,
# protocol skew in both directions, the replay JSONL round-trip, and
# the --fleet-obs census (zero obs ops and zero federation sends with
# SUTRO_TELEMETRY=0)
make fleet-obs-check
# tier-1 gate: server-side stage graphs — DAG validation (structured
# INVALID_GRAPH 400), generate->score->rank bit-identity vs the
# client-side sequence at temp 0, streaming inter-stage admission,
# per-stage quarantine, crash/resume replaying only missing stage
# chunks, and the zero-overhead census for stage-less jobs
make graph-check
# warn-only: bench-artifact trend report (never fails the build)
make bench-trend
# tier-1 gate: interactive tier CPU smoke — TTFT/ITL legs + the
# co-resident-batch throughput retention grade (tests/test_serving.py
# rides the chunked suite below)
make serve-bench
bash .github/run_tests_chunked.sh
