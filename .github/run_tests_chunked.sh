#!/bin/bash
# Full-suite runner that survives the environment's XLA CPU compile
# segfault flake: two consecutive full-process runs this round died
# inside jax backend_compile_and_load (different test files each time,
# both pass in isolation; single-core host). Running per-file isolates
# the blast radius and a crashed file retries up to 2x — a TEST failure
# (rc 1) never retries, so real regressions still fail fast.
# Usage: bash .github/run_tests_chunked.sh [pytest-args...]
cd "$(dirname "$0")/.." || exit 1
trap 'echo "CHUNKED SUITE INTERRUPTED"; exit 130' INT
# multi-process / thread-timing files that can fail (rc 1) under heavy
# host load while passing in isolation — these get ONE failure retry;
# every other file's failures are terminal on the first attempt
LOAD_SENSITIVE="test_dphost test_multihost test_races"
FAILED=()
for f in tests/test_*.py; do
  ok=""
  base=$(basename "$f" .py)
  fail_budget=1
  case " $LOAD_SENSITIVE " in
    *" $base "*) fail_budget=2 ;;
  esac
  fails=0
  for attempt in 1 2 3; do
    python -m pytest "$f" -q "$@"
    rc=$?
    if [ "$rc" -eq 0 ]; then ok=1; break; fi
    # rc 5 = no tests collected: fine under filter args, a silent
    # coverage hole otherwise
    if [ "$rc" -eq 5 ] && [ "$#" -gt 0 ]; then ok=1; break; fi
    # rc 1 = test failure, rc 2 = collection error (pytest also uses
    # 2 for Ctrl-C, which the INT trap above handles)
    if [ "$rc" -eq 1 ] || [ "$rc" -eq 2 ]; then
      fails=$((fails + 1))
      [ "$fails" -ge "$fail_budget" ] && break
      echo "=== $f failed under load (attempt $attempt) - one retry"
      continue
    fi
    echo "=== $f crashed (rc=$rc, attempt $attempt) - retrying"
  done
  [ -z "$ok" ] && FAILED+=("$f:rc$rc")
done
if [ "${#FAILED[@]}" -gt 0 ]; then
  echo "CHUNKED SUITE FAILED: ${FAILED[*]}"
  exit 1
fi
echo "CHUNKED SUITE GREEN (all files)"
