#!/bin/bash
# Full-suite runner that survives the environment's XLA CPU compile
# segfault flake: two consecutive full-process runs this round died
# inside jax backend_compile_and_load (different test files each time,
# both pass in isolation; single-core host). Running per-file isolates
# the blast radius and a crashed file retries up to 2x — a TEST failure
# (rc 1) never retries, so real regressions still fail fast.
# Usage: bash .github/run_tests_chunked.sh [pytest-args...]
cd "$(dirname "$0")/.." || exit 1
FAILED=()
for f in tests/test_*.py; do
  ok=""
  for attempt in 1 2 3; do
    python -m pytest "$f" -q "$@"
    rc=$?
    if [ "$rc" -eq 0 ] || [ "$rc" -eq 5 ]; then ok=1; break; fi
    # rc 5 = no tests collected (filter args deselected this file)
    if [ "$rc" -eq 1 ]; then break; fi  # real test failure: no retry
    if [ "$rc" -eq 2 ]; then            # interrupted (Ctrl-C): abort
      echo "CHUNKED SUITE INTERRUPTED at $f"
      exit 2
    fi
    echo "=== $f crashed (rc=$rc, attempt $attempt) - retrying"
  done
  [ -z "$ok" ] && FAILED+=("$f:rc$rc")
done
if [ "${#FAILED[@]}" -gt 0 ]; then
  echo "CHUNKED SUITE FAILED: ${FAILED[*]}"
  exit 1
fi
echo "CHUNKED SUITE GREEN (all files)"
