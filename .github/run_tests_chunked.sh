#!/bin/bash
# Per-file suite runner. NO retry policy (VERDICT r4 item 5): every
# file runs exactly once and any failure is terminal.
#
# Why per-file processes at all — the pinned cause, from a round-5
# discrimination matrix (4 reproductions, full dumps preserved):
#   r4 full suite (torch loaded, 223 ext modules)      -> SIGSEGV in
#     XLA:CPU backend_compile_and_load @ test_prefix_cache
#   r5 suite minus test_golden (NO torch, 73 modules)  -> same site,
#     test_prefix_cache (different test)  [torch EXONERATED]
#   r5 + SUTRO_NATIVE_RUNTIME=0                        -> same
#     [native runtime.cpp EXONERATED]
#   r5 + SUTRO_NATIVE_RUNTIME=0 SUTRO_NATIVE_FSM=0     -> same
#     [ALL in-repo C++ EXONERATED]
#   2000 distinct fresh XLA:CPU compiles, one process  -> no crash
#     [raw compile count EXONERATED]
# Every crashed FILE passes in isolation; the victim test varies but
# the crash file is test_prefix_cache 4/4 — i.e. the trigger is the
# accumulated in-process state (live executables/threads/arenas) by
# the time the suite reaches that point, not the test itself. The
# persistent compile cache is OFF under tests (conftest sets
# SUTRO_COMPILE_CACHE=0), ruling out cache corruption. Conclusion:
# upstream XLA:CPU compiler flake in long-lived many-compile
# processes; per-file processes bound the blast radius so it cannot
# take down the whole gate.
# The former "load-sensitive retry" is retired: the multi-process
# timing tests (test_dphost/test_multihost) now carry deadlines sized
# for a loaded single-core host instead.
# Usage: bash .github/run_tests_chunked.sh [pytest-args...]
cd "$(dirname "$0")/.." || exit 1
trap 'echo "CHUNKED SUITE INTERRUPTED"; exit 130' INT
FAILED=()
for f in tests/test_*.py; do
  python -m pytest "$f" -q "$@"
  rc=$?
  # rc 5 = no tests collected: fine under filter args, a silent
  # coverage hole otherwise
  if [ "$rc" -eq 5 ] && [ "$#" -gt 0 ]; then rc=0; fi
  [ "$rc" -ne 0 ] && FAILED+=("$f:rc$rc")
done
if [ "${#FAILED[@]}" -gt 0 ]; then
  echo "CHUNKED SUITE FAILED: ${FAILED[*]}"
  exit 1
fi
echo "CHUNKED SUITE GREEN (all files)"
