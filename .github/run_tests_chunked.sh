#!/bin/bash
# Per-file suite runner. NO retry policy (VERDICT r4 item 5): every
# file runs exactly once and any failure is terminal.
#
# Why per-file processes at all — the pinned cause: long-lived
# many-compile pytest processes flakily segfault INSIDE XLA:CPU's
# backend_compile_and_load on this host (fatal dumps in
# pytest_full.log round 4 and the round-5 reproduction). The round-5
# crash had only 73 extension modules loaded — torch NOT among them —
# so the round-4 "torch._C + jaxlib co-residency" suspicion is
# falsified; the trigger correlates with compile count / process
# lifetime, not co-loaded libraries. Every crashed file passes in
# isolation, the crash file differs run to run, and the persistent
# compile cache is OFF under tests (conftest sets
# SUTRO_COMPILE_CACHE=0), which rules out cache corruption. Upstream
# XLA:CPU flake; per-file processes bound the blast radius so a
# one-in-hundreds compile crash cannot take down the whole gate.
# The former "load-sensitive retry" is retired: the multi-process
# timing tests (test_dphost/test_multihost) now carry deadlines sized
# for a loaded single-core host instead.
# Usage: bash .github/run_tests_chunked.sh [pytest-args...]
cd "$(dirname "$0")/.." || exit 1
trap 'echo "CHUNKED SUITE INTERRUPTED"; exit 130' INT
FAILED=()
for f in tests/test_*.py; do
  python -m pytest "$f" -q "$@"
  rc=$?
  # rc 5 = no tests collected: fine under filter args, a silent
  # coverage hole otherwise
  if [ "$rc" -eq 5 ] && [ "$#" -gt 0 ]; then rc=0; fi
  [ "$rc" -ne 0 ] && FAILED+=("$f:rc$rc")
done
if [ "${#FAILED[@]}" -gt 0 ]; then
  echo "CHUNKED SUITE FAILED: ${FAILED[*]}"
  exit 1
fi
echo "CHUNKED SUITE GREEN (all files)"
