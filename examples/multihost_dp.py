"""Engine-level multi-host data parallelism: one job, two pod slices.

Demonstrates `engine/dphost.py` on a single machine by treating two OS
processes as the two pod slices: both submit the SAME job to their own
`LocalEngine`; rows are strided across ranks, the worker streams its
finished rows to the rank-0 coordinator over TCP, and the coordinator's
jobstore produces the single, input-ordered result set.

On a real pod, a launcher starts one engine process per slice with:

    SUTRO_DP_WORLD=<slices> SUTRO_DP_RANK=<r> \
    SUTRO_DP_COORD=<rank0-host>:<port>  python your_job.py

Run: python examples/multihost_dp.py --cpu
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
from pathlib import Path

from _common import example_client

N_ROWS = 12


def child() -> None:
    import time

    so, model, _ = example_client(__doc__)
    jid = so.infer(
        [f"review {i}: works great" for i in range(N_ROWS)],
        model=model,
        system_prompt="Summarize in three words.",
        sampling_params={"max_new_tokens": 8, "temperature": 0.0},
        stay_attached=False,
    )
    rank = os.environ["SUTRO_DP_RANK"]
    if rank == "0":
        df = so.await_job_completion(jid, unpack_json=False)
        assert df is not None and len(df) == N_ROWS
        print(f"[rank 0] merged {len(df)} rows, input order preserved:")
        print(df.head(4).to_string())
    else:
        # worker stores are non-authoritative (results live on rank 0):
        # await the STATUS only, never fetch results here
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            s = so.get_job_status(jid)
            if s in ("SUCCEEDED", "FAILED", "CANCELLED"):
                break
            time.sleep(0.2)
        if s != "SUCCEEDED":
            raise SystemExit(f"[rank {rank}] shard did not complete: {s}")
        print(f"[rank {rank}] shard streamed to coordinator (status {s})")


def main() -> None:
    if os.environ.get("SUTRO_DP_WORLD"):
        child()
        return
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            SUTRO_DP_WORLD="2",
            SUTRO_DP_RANK=str(rank),
            SUTRO_DP_COORD=f"127.0.0.1:{port}",
            # each "slice" needs its own store; rank 0's is authoritative
            SUTRO_HOME=tempfile.mkdtemp(prefix=f"sutro-dp-ex-r{rank}-"),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, __file__, *sys.argv[1:]], env=env
            )
        )
    try:
        rcs = [p.wait(timeout=1200) for p in procs]
    finally:
        for p in procs:  # never orphan a rank holding the chip
            if p.poll() is None:
                p.kill()
    if any(rcs):
        raise SystemExit(f"ranks exited {rcs}")
    print(json.dumps({"dp_example": "ok", "world": 2, "rows": N_ROWS}))


if __name__ == "__main__":
    main()
