"""Constrained decoding: every row's output is schema-valid JSON.

Shows both schema forms the reference accepts (Pydantic model or plain
JSON-schema dict) plus the constraint features compiled to the byte FSM:
enums, integer ranges (minimum/maximum), and regex string patterns.
"""

import json

from pydantic import BaseModel, Field

from _common import example_client


class Ticket(BaseModel):
    category: str = Field(
        description="one of billing/shipping/product/other"
    )
    severity: int = Field(ge=1, le=5)


def main() -> None:
    so, model, _ = example_client(__doc__)
    rows = [
        "my package never arrived and support won't answer",
        "the invoice charged me twice this month",
    ]

    # Pydantic form
    jid = so.infer(
        rows, model=model, output_schema=Ticket, stay_attached=False
    )
    df = so.await_job_completion(jid)
    for v in df["inference_result"]:
        print("pydantic:", json.loads(v))

    # dict form with enum + integer range + regex pattern
    schema = {
        "type": "object",
        "properties": {
            "label": {"enum": ["refund", "replace", "escalate"]},
            "confidence": {"type": "integer", "minimum": 0, "maximum": 100},
            "case_id": {"type": "string", "pattern": r"^CASE-\d{4}$"},
        },
        "required": ["label", "confidence", "case_id"],
    }
    jid = so.infer(
        rows, model=model, output_schema=schema, stay_attached=False
    )
    df = so.await_job_completion(jid)
    for v in df["inference_result"]:
        print("dict-schema:", json.loads(v))


if __name__ == "__main__":
    main()
