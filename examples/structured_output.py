"""Constrained decoding: every row's output is schema-valid JSON.

Shows both schema forms the reference accepts (Pydantic model or plain
JSON-schema dict) plus the constraint features compiled to the byte FSM:
enums, integer ranges and multipleOf, strict number bounds, regex string
patterns, date formats, and uniqueItems enum arrays. If the engine's
minimal-JSON bound exceeds max_new_tokens, the cap is raised
automatically so outputs always parse.
"""

import json

from pydantic import BaseModel, Field

from _common import example_client


class Ticket(BaseModel):
    category: str = Field(
        description="one of billing/shipping/product/other"
    )
    severity: int = Field(ge=1, le=5)


def main() -> None:
    so, model, _ = example_client(__doc__)
    rows = [
        "my package never arrived and support won't answer",
        "the invoice charged me twice this month",
    ]

    # Pydantic form
    jid = so.infer(
        rows, model=model, output_schema=Ticket, stay_attached=False
    )
    df = so.await_job_completion(jid)
    for v in df["inference_result"]:
        print("pydantic:", json.loads(v))

    # dict form: enum, integer range + multipleOf, regex pattern, date
    # format, strict number bounds, unique enum array — every field is
    # guaranteed by the token-level FSM, whatever the model wants
    schema = {
        "type": "object",
        "properties": {
            "label": {"enum": ["refund", "replace", "escalate"]},
            "confidence": {"type": "integer", "minimum": 0, "maximum": 100},
            "sla_days": {"type": "integer", "multipleOf": 7,
                         "minimum": 7, "maximum": 28},
            "case_id": {"type": "string", "pattern": r"^CASE-\d{4}$"},
            "opened": {"type": "string", "format": "date"},
            "refund_usd": {"type": "number", "exclusiveMinimum": 0,
                           "maximum": 500},
            "tags": {"type": "array", "items": {"enum": ["vip", "repeat",
                     "fraud-risk"]}, "uniqueItems": True, "minItems": 1},
        },
        "required": ["label", "confidence", "sla_days", "case_id",
                     "opened", "refund_usd", "tags"],
    }
    jid = so.infer(
        rows, model=model, output_schema=schema, stay_attached=False
    )
    df = so.await_job_completion(jid)
    for v in df["inference_result"]:
        print("dict-schema:", json.loads(v))


if __name__ == "__main__":
    main()
