"""Shared example bootstrap: `--cpu` forces the CPU backend + a tiny
random-weights model so every example runs anywhere in seconds."""

import argparse
import sys
from pathlib import Path

# runnable from a source checkout without installation
_repo = Path(__file__).resolve().parent.parent
if str(_repo) not in sys.path:
    sys.path.insert(0, str(_repo))


def example_client(description: str, engine_config: dict | None = None):
    """Returns (Sutro client, generation model, embedding model).
    ``engine_config`` entries are merged over the defaults."""
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument(
        "--cpu", action="store_true",
        help="tiny random model on the CPU backend (fast smoke run)",
    )
    ap.add_argument("--model", default=None, help="catalog model override")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from sutro_tpu.sdk import Sutro

        # context must cover template system prompts (~250 bytes through
        # the byte tokenizer) PLUS each schema's minimal JSON
        ecfg = dict(
            kv_page_size=8, max_pages_per_seq=48, decode_batch_size=4,
            max_model_len=384, max_new_tokens=64, use_pallas=False,
            param_dtype="float32",
        )
        ecfg.update(engine_config or {})
        client = Sutro(engine_config=ecfg)
        return client, args.model or "tiny-dense", "tiny-emb"

    from sutro_tpu.sdk import Sutro

    return (
        Sutro(engine_config=engine_config) if engine_config else Sutro(),
        args.model or "qwen-3-0.6b",
        "qwen-3-embedding-0.6b",
    )
