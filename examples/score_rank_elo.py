"""Eval templates: LLM-judge scores, ranking, and Bradley-Terry Elo."""

import pandas as pd

from _common import example_client


def main() -> None:
    so, model, _ = example_client(__doc__)
    df = pd.DataFrame(
        {
            "answer_a": ["Paris is the capital of France.", "It is 42."],
            "answer_b": ["France's capital is Paris, founded long ago.",
                         "The answer is forty-two."],
        }
    )

    scored = so.score(
        df,
        criteria="Rate the factual quality of this answer.",
        column="answer_a",
        min_score=1,
        max_score=5,
        model=model,
    )
    print(scored)

    ranked = so.rank(
        df,
        options=["answer_a", "answer_b"],
        criteria="Which answer is clearer?",
        model=model,
        compute_elo=True,
    )
    print(ranked)


if __name__ == "__main__":
    main()
