"""Job lifecycle and datasets: detach, poll, attach, results cache,
dataset CRUD — the reference's ops workflows (SURVEY §3.1/§3.5)."""

import tempfile
from pathlib import Path

from _common import example_client


def main() -> None:
    so, model, _ = example_client(__doc__)

    # detached submit -> poll -> results (cached to ~/.sutro/job-results)
    jid = so.infer(
        ["first row", "second row"], model=model, stay_attached=False
    )
    print("job:", jid, "status:", so.get_job_status(jid))
    df = so.await_job_completion(jid)
    print(df)
    # second fetch hits the local parquet cache
    df2 = so.get_job_results(jid)
    assert df2 is not None

    # datasets: create -> upload -> list -> download
    ds = so.create_dataset()
    with tempfile.TemporaryDirectory() as td:
        p = Path(td) / "rows.csv"
        p.write_text("text\nalpha\nbeta\n")
        so.upload_to_dataset(ds, str(p))
        print("datasets:", [d["dataset_id"] for d in so.list_datasets()])
        print("files:", so.list_dataset_files(ds))
        so.download_from_dataset(ds, output_path=td + "/out")
        print("downloaded:", list((Path(td) / "out").iterdir()))


if __name__ == "__main__":
    main()
