"""Embedding jobs: rows -> unit-norm vectors -> similarity matrix."""

import numpy as np

from _common import example_client


def main() -> None:
    so, _, emb_model = example_client(__doc__)
    rows = [
        "the battery lasts forever",
        "battery life is amazing",
        "the screen cracked immediately",
    ]
    df = so.embed(rows, model=emb_model)
    vecs = np.array(df["embedding"].tolist())
    sims = vecs @ vecs.T
    print("similarity matrix:")
    print(np.round(sims, 3))


if __name__ == "__main__":
    main()
