"""Round-4 throughput features on one templated job + an interactive
job co-batched alongside it.

Every row of a templated job (classify/extract) shares its system
prompt, so the engine:
- prefills the shared prefix ONCE (prefix_cache, on by default) and
  shares its KV pages read-only across rows;
- optionally computes the prefix's DECODE attention once per step for
  the whole batch (prefix_split, Hydragen-style carry injection —
  Pallas path, chip-A/B gated);
- optionally speculates greedy rows from their own prompt/output
  n-grams (spec_ngram_draft — exact for greedy, acceptance-rate
  metrics in the job perf record);
- stores the KV cache int8 with per-token scales (kv_quantize) for
  2x page capacity / half the decode HBM traffic;
- co-batches a small interactive job into the SAME decode batch
  without preempting the big job's slots.
"""

import pandas as pd

from _common import example_client


def main() -> None:
    so, model, _ = example_client(
        __doc__,
        engine_config=dict(
            spec_ngram_draft=6,      # n-gram speculative decoding
            kv_quantize="int8",      # int8 KV cache
            # prefix_split=True,     # flip after the chip A/B
        ),
    )
    reviews = pd.DataFrame(
        {"review_text": [f"review {i}: works great" for i in range(64)]}
    )
    big = so.classify(
        reviews,
        column="review_text",
        classes=["positive", "negative", "neutral"],
        model=model,
        job_priority=1,
    )
    print(big.head())
    # an interactive priority-0 submit rides the same decode batch as
    # a running priority-1 job (co-batching: no preemption,
    # ~single-job latency)
    jid = so.infer(
        ["summarize: the device is reliable"],
        model=model,
        job_priority=0,
    )
    print(so.await_job_completion(jid))
    rec = so.engine.get_job(jid)
    spec = (rec.get("perf") or {}).get("spec_ngram")
    if spec:
        print("speculation acceptance:", spec)


if __name__ == "__main__":
    main()
