"""The README golden path: classify 3 product reviews by sentiment.

Mirrors the reference quickstart (SURVEY §6 "Quickstart golden path"):
DataFrame in, labeled DataFrame out, schema-guaranteed labels.
"""

import pandas as pd

from _common import example_client


def main() -> None:
    so, model, _ = example_client(__doc__)
    df = pd.DataFrame(
        {
            "review_text": [
                "great product, works perfectly",
                "broke after one day, do not buy",
                "it's fine I guess",
            ]
        }
    )
    out = so.classify(
        df,
        column="review_text",
        classes=["positive", "negative", "neutral"],
        model=model,
    )
    print(out)


if __name__ == "__main__":
    main()
