"""CLI smoke tests via click's test runner (reference cli.py command set)."""

import pytest
from click.testing import CliRunner

from sutro_tpu.cli import cli


@pytest.fixture()
def runner(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    return CliRunner()


def test_quotas(runner):
    res = runner.invoke(cli, ["quotas"])
    assert res.exit_code == 0
    assert "row_quota" in res.output


def test_engine_models(runner):
    res = runner.invoke(cli, ["engine", "models"])
    assert res.exit_code == 0
    assert "qwen-3-32b" in res.output
    assert "gpt-oss-120b" in res.output


def test_engine_info(runner):
    res = runner.invoke(cli, ["engine", "info"])
    assert res.exit_code == 0
    assert "mesh:" in res.output


def test_datasets_create_and_files(runner, tmp_path):
    res = runner.invoke(cli, ["datasets", "create"])
    assert res.exit_code == 0
    ds = res.output.strip().splitlines()[-1]
    assert ds.startswith("dataset-")
    f = tmp_path / "a.txt"
    f.write_text("row1\nrow2\n")
    res = runner.invoke(cli, ["datasets", "upload", ds, str(f)])
    assert res.exit_code == 0
    res = runner.invoke(cli, ["datasets", "files", ds])
    assert "a.txt" in res.output
    res = runner.invoke(cli, ["datasets", "list"])
    assert ds in res.output


def test_cache_show_empty(runner):
    res = runner.invoke(cli, ["cache", "show"])
    assert res.exit_code == 0


def test_set_base_url_and_backend(runner, tmp_path):
    res = runner.invoke(cli, ["set-base-url", "https://example.test"])
    assert res.exit_code == 0
    res = runner.invoke(cli, ["set-backend", "tpu"])
    assert res.exit_code == 0
    from sutro_tpu.validation import load_config

    cfg = load_config()
    assert cfg["base_url"] == "https://example.test"
    assert cfg["backend"] == "tpu"


def test_jobs_list_empty(runner):
    res = runner.invoke(cli, ["jobs", "list"])
    assert res.exit_code == 0


# ---------------------------------------------------------------------------
# job lifecycle against the live local engine (reference
# cli.py:204-273,344-360,419-435). Module-scoped home + tiny engine.json
# so every `get_sdk()` the CLI constructs shares one tiny singleton.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def live(tmp_path_factory, monkeypatch_module):
    import json

    home = tmp_path_factory.mktemp("cli-home")
    monkeypatch_module.setenv("SUTRO_HOME", str(home))
    (home / "engine.json").write_text(
        json.dumps(
            dict(
                kv_page_size=8, max_pages_per_seq=16,
                decode_batch_size=4, max_model_len=128,
                use_pallas=False, param_dtype="float32",
                activation_dtype="float32", max_new_tokens=8,
            )
        )
    )
    from sutro_tpu.engine.api import reset_engine
    from sutro_tpu.sdk import Sutro

    reset_engine()
    sdk = Sutro()
    yield CliRunner(), sdk, home
    reset_engine()


def _submitted_job(sdk, n=2, await_done=True, **kw):
    jid = sdk.infer(
        [f"cli row {i}" for i in range(n)],
        model="tiny-dense",
        stay_attached=False,
        sampling_params={"max_new_tokens": 4, "temperature": 0.0},
        **kw,
    )
    if await_done:
        sdk.await_job_completion(jid, unpack_json=False)
    return jid


def test_jobs_status_and_list_show_job(live):
    runner, sdk, _ = live
    jid = _submitted_job(sdk)
    res = runner.invoke(cli, ["jobs", "status", jid])
    assert res.exit_code == 0
    assert "SUCCEEDED" in res.output
    res = runner.invoke(cli, ["jobs", "list"])
    assert res.exit_code == 0
    assert jid in res.output


def test_jobs_results_stdout_and_parquet(live, tmp_path):
    import pandas as pd

    runner, sdk, _ = live
    jid = _submitted_job(sdk)
    res = runner.invoke(cli, ["jobs", "results", jid])
    assert res.exit_code == 0
    assert "inference_result" in res.output
    out = tmp_path / "res.parquet"
    res = runner.invoke(
        cli, ["jobs", "results", jid, "--output-path", str(out)]
    )
    assert res.exit_code == 0
    df = pd.read_parquet(out)
    assert len(df) == 2
    assert "inference_result" in df.columns


def test_jobs_results_unknown_id_exits_nonzero(live):
    runner, _, _ = live
    res = runner.invoke(cli, ["jobs", "results", "job-nonexistent"])
    assert res.exit_code != 0


def test_jobs_cancel_then_resume(live):
    runner, sdk, _ = live
    # enough rows that cancellation lands mid-flight or queued
    jid = _submitted_job(sdk, n=6, await_done=False)
    res = runner.invoke(cli, ["jobs", "cancel", jid])
    assert res.exit_code == 0
    assert "Status:" in res.output
    # wait for the terminal state, then resume via the CLI
    import time

    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if sdk.get_job_status(jid) in ("CANCELLED", "SUCCEEDED"):
            break
        time.sleep(0.05)
    status = sdk.get_job_status(jid)
    res = runner.invoke(cli, ["jobs", "resume", jid])
    assert res.exit_code == 0
    if status == "CANCELLED":
        assert "Resumed" in res.output
        sdk.await_job_completion(jid, unpack_json=False)
        assert sdk.get_job_status(jid) == "SUCCEEDED"
    else:
        # raced to completion before cancel landed — resume must refuse
        assert "Not resumed" in res.output


def test_doctor_command_renders_verdict(live):
    runner, sdk, _ = live
    jid = _submitted_job(sdk)
    res = runner.invoke(cli, ["doctor", jid])
    assert res.exit_code == 0
    assert "verdict:" in res.output
    assert "rank0" in res.output
    res = runner.invoke(cli, ["doctor", jid, "--json"])
    assert res.exit_code == 0
    import json

    diag = json.loads(res.output)
    assert diag["job_id"] == jid and diag["verdict"]


def test_trace_command_writes_perfetto_json(live, tmp_path):
    """`sutro trace <job_id> -o out.json` exports the job's forensics
    trace as Chrome trace-event JSON (Perfetto-loadable) and prints
    the embedded per-request verdict."""
    import json

    runner, sdk, _ = live
    jid = _submitted_job(sdk)
    out = tmp_path / "trace.json"
    res = runner.invoke(cli, ["trace", jid, "-o", str(out)])
    assert res.exit_code == 0, res.output
    assert "ui.perfetto.dev" in res.output
    assert "verdict:" in res.output
    doc = json.loads(out.read_text())
    events = doc["traceEvents"]
    assert any(e.get("ph") == "X" for e in events)
    names = {e.get("name") for e in events}
    assert "decode_window" in names and "queue_wait" in names
    assert doc["otherData"]["verdict"]["trace_id"] == f"tr-{jid}"
    # --json prints the document to stdout instead
    res = runner.invoke(cli, ["trace", f"tr-{jid}", "--json"])
    assert res.exit_code == 0
    assert json.loads(res.output)["otherData"]["verdict"]
    # unknown ids exit non-zero, like every other id-taking command
    res = runner.invoke(cli, ["trace", "tr-nope"])
    assert res.exit_code != 0


def test_jobs_status_hints_at_telemetry_dump(live):
    runner, sdk, _ = live
    jid = _submitted_job(sdk)
    # force a dump (on-demand refresh persists telemetry.json)
    sdk.get_job_telemetry(jid)
    res = runner.invoke(cli, ["jobs", "status", jid])
    assert res.exit_code == 0
    assert "sutro doctor" in res.output


def test_jobs_resume_succeeded_refuses(live):
    runner, sdk, _ = live
    jid = _submitted_job(sdk)
    res = runner.invoke(cli, ["jobs", "resume", jid])
    assert res.exit_code == 0
    assert "Not resumed" in res.output
    assert "succeeded" in res.output


def test_jobs_attach_latest_completed(live):
    runner, sdk, _ = live
    _submitted_job(sdk)
    res = runner.invoke(cli, ["jobs", "attach", "--latest"])
    assert res.exit_code == 0


def test_jobs_attach_no_jobs_fails(runner):
    # fresh empty home (the `runner` fixture) — no jobs to attach to.
    # The engine is a process singleton, so drop any instance bound to
    # another test's home before and after.
    from sutro_tpu.engine.api import reset_engine

    reset_engine()
    try:
        res = runner.invoke(cli, ["jobs", "attach", "--latest"])
        assert res.exit_code == 1
        assert "No jobs" in res.output
    finally:
        reset_engine()


def test_login_local_backend_no_key(live):
    runner, _, home = live
    res = runner.invoke(cli, ["login"], input="\n")
    assert res.exit_code == 0
    assert "Logged in" in res.output


def test_login_stores_key(live):
    runner, _, home = live
    res = runner.invoke(cli, ["login"], input="sk-test-123\n")
    assert res.exit_code == 0
    from sutro_tpu.validation import load_config

    assert load_config().get("api_key") == "sk-test-123"


def test_datasets_download(live, tmp_path):
    runner, _, _ = live
    res = runner.invoke(cli, ["datasets", "create"])
    assert res.exit_code == 0
    ds = res.output.strip().splitlines()[-1]
    src = tmp_path / "up.txt"
    src.write_text("hello\nworld\n")
    res = runner.invoke(cli, ["datasets", "upload", ds, str(src)])
    assert res.exit_code == 0
    dest = tmp_path / "down"
    dest.mkdir()
    res = runner.invoke(
        cli, ["datasets", "download", ds, "--output-path", str(dest)]
    )
    assert res.exit_code == 0
    got = dest / "up.txt"
    assert got.exists()
    assert got.read_text() == "hello\nworld\n"
