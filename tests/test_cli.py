"""CLI smoke tests via click's test runner (reference cli.py command set)."""

import pytest
from click.testing import CliRunner

from sutro_tpu.cli import cli


@pytest.fixture()
def runner(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    return CliRunner()


def test_quotas(runner):
    res = runner.invoke(cli, ["quotas"])
    assert res.exit_code == 0
    assert "row_quota" in res.output


def test_engine_models(runner):
    res = runner.invoke(cli, ["engine", "models"])
    assert res.exit_code == 0
    assert "qwen-3-32b" in res.output
    assert "gpt-oss-120b" in res.output


def test_engine_info(runner):
    res = runner.invoke(cli, ["engine", "info"])
    assert res.exit_code == 0
    assert "mesh:" in res.output


def test_datasets_create_and_files(runner, tmp_path):
    res = runner.invoke(cli, ["datasets", "create"])
    assert res.exit_code == 0
    ds = res.output.strip().splitlines()[-1]
    assert ds.startswith("dataset-")
    f = tmp_path / "a.txt"
    f.write_text("row1\nrow2\n")
    res = runner.invoke(cli, ["datasets", "upload", ds, str(f)])
    assert res.exit_code == 0
    res = runner.invoke(cli, ["datasets", "files", ds])
    assert "a.txt" in res.output
    res = runner.invoke(cli, ["datasets", "list"])
    assert ds in res.output


def test_cache_show_empty(runner):
    res = runner.invoke(cli, ["cache", "show"])
    assert res.exit_code == 0


def test_set_base_url_and_backend(runner, tmp_path):
    res = runner.invoke(cli, ["set-base-url", "https://example.test"])
    assert res.exit_code == 0
    res = runner.invoke(cli, ["set-backend", "tpu"])
    assert res.exit_code == 0
    from sutro_tpu.validation import load_config

    cfg = load_config()
    assert cfg["base_url"] == "https://example.test"
    assert cfg["backend"] == "tpu"


def test_jobs_list_empty(runner):
    res = runner.invoke(cli, ["jobs", "list"])
    assert res.exit_code == 0
