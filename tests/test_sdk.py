"""SDK-level tests against the live local engine (tiny models, CPU).

Unlike the reference suite — which mocks all HTTP (SURVEY §4) and has gone
stale — these run the real in-process engine end to end: the 3-row
quickstart golden path (reference README.md:124-160), constrained decode,
results semantics (§2.4), lifecycle, datasets, quotas, cache.
"""

import json

import numpy as np
import pandas as pd
import pytest

from sutro_tpu.interfaces import JobStatus


@pytest.fixture(scope="module")
def sdk(live_engine, monkeypatch_module):
    """Local-backend SDK over the session-shared engine (conftest
    ``live_engine``) — one tiny-model compile for this module AND
    test_serving.py instead of one each."""
    engine, _url, home = live_engine
    monkeypatch_module.setenv("SUTRO_HOME", home)
    from sutro_tpu.sdk import Sutro

    client = Sutro(api_key="test-key")
    client._engine = engine
    yield client


def test_infer_list_returns_ordered_results(sdk):
    job_id = sdk.infer(
        ["row a", "row b", "row c"],
        model="tiny-dense",
        stay_attached=False,
        sampling_params={"max_new_tokens": 6},
    )
    assert job_id
    df = sdk.await_job_completion(job_id, unpack_json=False)
    assert df is not None and len(df) == 3
    assert "inference_result" in df.columns


def test_infer_dataframe_with_column(sdk):
    df_in = pd.DataFrame({"text": ["x", "y"], "junk": [1, 2]})
    job_id = sdk.infer(
        df_in,
        model="tiny-dense",
        column="text",
        stay_attached=False,
        sampling_params={"max_new_tokens": 4},
    )
    out = sdk.await_job_completion(
        job_id, unpack_json=False, with_original_df=df_in
    )
    assert list(out["text"]) == ["x", "y"]
    assert "inference_result" in out.columns


def test_infer_requires_column_for_df(sdk):
    with pytest.raises(ValueError, match="column"):
        sdk.infer(pd.DataFrame({"a": ["1"]}), model="tiny-dense")


def test_name_length_validation(sdk):
    with pytest.raises(ValueError, match="45"):
        sdk.infer(["x"], model="tiny-dense", name="n" * 46)
    with pytest.raises(ValueError, match="512"):
        sdk.infer(["x"], model="tiny-dense", description="d" * 513)


def test_unknown_model_fails_job(sdk):
    with pytest.raises(ValueError, match="Unknown model"):
        sdk.infer(["x"], model="not-a-model", stay_attached=False)


def test_constrained_output_schema(sdk):
    schema = {
        "type": "object",
        "properties": {
            "sentiment": {"enum": ["positive", "negative", "neutral"]}
        },
        "required": ["sentiment"],
    }
    job_id = sdk.infer(
        ["good", "bad"],
        model="tiny-dense",
        output_schema=schema,
        stay_attached=False,
        sampling_params={"max_new_tokens": 40, "temperature": 1.0},
    )
    df = sdk.await_job_completion(job_id)
    for raw in sdk.get_job_results(job_id, unpack_json=False, disable_cache=True)[
        "inference_result"
    ]:
        assert json.loads(raw)["sentiment"] in (
            "positive", "negative", "neutral",
        )
    # unpack_json promoted the field to a column
    assert "sentiment" in df.columns


def test_dry_run_returns_estimate(sdk):
    est = sdk.infer(["a"] * 10, model="tiny-dense", dry_run=True)
    assert est is not None and est >= 0


def test_results_cache_roundtrip(sdk):
    job_id = sdk.infer(
        ["c1", "c2"],
        model="tiny-dense",
        stay_attached=False,
        sampling_params={"max_new_tokens": 4},
    )
    sdk.await_job_completion(job_id, obtain_results=False)
    df1 = sdk.get_job_results(job_id, unpack_json=False)
    assert any(
        job_id in e["file"] for e in sdk.show_job_results_cache()
    )
    df2 = sdk.get_job_results(job_id, unpack_json=False)  # cache hit
    assert list(df1["inference_result"]) == list(df2["inference_result"])


def test_job_lifecycle_and_record_fields(sdk):
    job_id = sdk.infer(
        ["life"],
        model="tiny-dense",
        name="lifecycle-test",
        stay_attached=False,
        sampling_params={"max_new_tokens": 4},
    )
    sdk.await_job_completion(job_id, obtain_results=False)
    rec = sdk._fetch_job(job_id)
    assert rec["status"] == JobStatus.SUCCEEDED.value
    assert rec["name"] == "lifecycle-test"
    assert rec["num_rows"] == 1
    assert rec["input_tokens"] > 0
    assert rec["job_cost"] is not None
    assert any(j["job_id"] == job_id for j in sdk.list_jobs())


def test_embedding_job(sdk):
    df = sdk.embed(["e1", "e2", "e3"], model="tiny-emb")
    assert len(df) == 3
    v = np.asarray(df["embedding"][0])
    assert v.shape == (128,)
    np.testing.assert_allclose(np.linalg.norm(v), 1.0, rtol=1e-3)


def test_classify_template_mechanics():
    """Template logic (prompt build, schema, scratchpad stripping) against a
    stub client — deterministic, unlike running a random-weight model
    through a free-string scratchpad."""
    import pandas as pd

    from sutro_tpu.templates.classification import ClassificationTemplates

    captured = {}

    class Stub(ClassificationTemplates):
        def infer(self, data, **kw):
            captured.update(kw)
            return "job-stub"

        def await_job_completion(self, job_id, **kw):
            return pd.DataFrame(
                {
                    "inference_result": ['{"scratchpad":"s","classification":"cat"}'],
                    "scratchpad": ["s"],
                    "classification": ["cat"],
                }
            )

    out = Stub().classify(["x"], classes={"cat": "feline", "dog": "canine"})
    assert "classification" in out.columns
    assert "scratchpad" not in out.columns  # stripped by default
    assert "cat: feline" in captured["system_prompt"]
    schema = captured["output_schema"].model_json_schema()
    assert list(schema["properties"]) == ["scratchpad", "classification"]

    out2 = Stub().classify(["x"], classes=["cat", "dog"], keep_scratchpad=True)
    assert "scratchpad" in out2.columns

    with pytest.raises(ValueError, match="non-empty"):
        Stub().classify(["x"], classes=[])


def test_classify_e2e_constrained(sdk):
    """End-to-end classify through the real engine: the classification field
    is enum-constrained, so even a random model must emit a valid label once
    the scratchpad closes. Uses a generous token budget and accepts
    length-truncated rows, but requires the job itself to succeed."""
    out = sdk.classify(
        ["thing one"],
        classes=["cat", "dog"],
        model="tiny-dense",
        sampling_params={"max_new_tokens": 96, "temperature": 1.0},
    )
    assert out is not None and len(out) == 1
    if "classification" in out.columns:
        assert out["classification"][0] in ("cat", "dog")


def test_datasets_roundtrip(sdk, tmp_path):
    ds = sdk.create_dataset()
    assert ds.startswith("dataset-")
    p = tmp_path / "rows.parquet"
    pd.DataFrame({"review_text": ["r1", "r2"]}).to_parquet(p)
    sdk.upload_to_dataset(ds, str(p), verbose=False)
    assert sdk.list_dataset_files(ds) == ["rows.parquet"]
    assert any(d["dataset_id"] == ds for d in sdk.list_datasets())
    got = sdk.download_from_dataset(ds, output_path=str(tmp_path / "dl"))
    assert len(got) == 1
    job_id = sdk.infer(
        ds,
        model="tiny-dense",
        column="review_text",
        stay_attached=False,
        sampling_params={"max_new_tokens": 4},
    )
    df = sdk.await_job_completion(job_id, unpack_json=False)
    assert len(df) == 2


def test_quotas_shape(sdk):
    q = sdk.get_quotas()
    assert len(q) >= 2
    assert {"row_quota", "token_quota"} <= set(q[0])


def test_quota_rejection(sdk):
    err = sdk.engine.jobs.check_quota(0, 10**9, 0)
    assert err and "quota" in err


def test_infer_per_model(sdk):
    ids = sdk.infer_per_model(
        ["fan"],
        models=["tiny-dense", "tiny-dense"],
        sampling_params={"max_new_tokens": 2},
    )
    assert len(ids) == 2
    for jid in ids:
        sdk.await_job_completion(jid, obtain_results=False)


def test_unpack_json_thinking_contract(sdk):
    from sutro_tpu.sdk import Sutro

    df = pd.DataFrame(
        {
            "out": [
                json.dumps(
                    {
                        "content": json.dumps({"a": 1}),
                        "reasoning_content": "thought",
                    }
                )
            ]
        }
    )
    got = Sutro._unpack_json_outputs(df, "out")
    assert got["reasoning_content"][0] == "thought"
    assert got["a"][0] == 1


def test_run_function_local_contract(sdk):
    """Local Functions path carries the full reference response contract
    (/root/reference/sutro/sdk.py:535-544): response text, a real
    confidence score (geometric-mean token probability), and a run id."""
    out = sdk.run_function("tiny-dense", {"q": "hello"})
    assert set(out) == {"response", "confidence", "predictions", "run_id"}
    assert isinstance(out["response"], str)
    assert out["run_id"].startswith("job-")
    assert out["confidence"] is not None
    assert 0.0 < out["confidence"] <= 1.0
    assert out["predictions"] == []


def test_stop_sequences_truncate_output(sdk):
    """sampling_params["stop"]: generation ends at the sequence and the
    rendered output excludes it (vLLM semantics). Two-pass: greedy
    decode once, pick a character from the real output, decode again
    with that character as the stop."""
    base_jid = sdk.infer(
        ["alpha"], model="tiny-dense",
        sampling_params={"temperature": 0.0, "max_new_tokens": 16},
        stay_attached=False,
    )
    base = sdk.await_job_completion(base_jid)["inference_result"][0]
    probe = next(
        (c for c in base[1:] if c.isascii() and c not in base[:1]), None
    )
    if probe is None:
        import pytest

        pytest.skip("greedy output has no usable probe char")
    jid = sdk.infer(
        ["alpha"], model="tiny-dense",
        sampling_params={
            "temperature": 0.0, "max_new_tokens": 16, "stop": probe
        },
        stay_attached=False,
    )
    got = sdk.await_job_completion(jid)["inference_result"][0]
    assert got == base[: base.index(probe)], (base, probe, got)


def test_stop_ignored_for_schema_jobs(sdk):
    """Stop sequences must not break the guaranteed-JSON contract: they
    are ignored (with a warning) when output_schema is set."""
    import json
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        jid = sdk.infer(
            ["row"],
            model="tiny-dense",
            output_schema={"const": "a|b"},
            sampling_params={"stop": "|"},
            stay_attached=False,
        )
        assert any(
            "output_schema" in str(x.message) for x in w
        ), "submit-time warning missing"
        df = sdk.await_job_completion(jid)
    assert df is not None
    assert json.loads(df["inference_result"][0]) == "a|b"


def test_feature_composition_end_to_end(sdk):
    """Kitchen-sink: a penalized p1 generation job and an interactive
    p0 schema job in flight together, then an embedding job — all
    through the public SDK surface with every contract holding.
    (Deterministic preemption ordering is asserted in
    tests/test_priority.py; here the point is feature composition.)"""
    # long-ish p1 batch with penalties (single-step decode path)
    p1 = sdk.infer(
        [f"background row {i}" for i in range(6)],
        model="tiny-dense",
        job_priority=1,
        sampling_params={
            "temperature": 0.7, "repetition_penalty": 1.3,
            "max_new_tokens": 24,
        },
        stay_attached=False,
    )
    # interactive p0 schema job submitted while p1 runs
    p0 = sdk.infer(
        ["urgent"],
        model="tiny-dense",
        job_priority=0,
        output_schema={
            "type": "object",
            "properties": {
                "score": {"type": "integer", "minimum": 1, "maximum": 5}
            },
            "required": ["score"],
        },
        stay_attached=False,
    )
    df0 = sdk.await_job_completion(p0)
    obj = json.loads(df0["inference_result"][0])
    assert 1 <= obj["score"] <= 5
    df1 = sdk.await_job_completion(p1)
    assert df1 is not None and len(df1) == 6
    # embedding job on the same engine process
    dfe = sdk.embed(["alpha", "beta"], model="tiny-emb")
    assert len(dfe) == 2
    assert sdk.get_job_status(p0) == "SUCCEEDED"
    assert sdk.get_job_status(p1) == "SUCCEEDED"
