"""Native scheduler runtime (native/runtime.cpp via
engine/native_runtime.py): allocator/admission semantics match the
pure-Python path, and the continuous batcher produces identical greedy
output with the native core on and off."""

import numpy as np
import pytest

from sutro_tpu.engine import native_runtime
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
from sutro_tpu.models.configs import MODEL_CONFIGS

pytestmark = pytest.mark.skipif(
    not native_runtime.is_available(),
    reason="native toolchain unavailable",
)


def _rt(**kw):
    base = dict(
        num_pages=17, num_slots=4, max_pages_per_seq=8, page_size=8,
        max_batch_tokens=1 << 20, max_context=64,
    )
    base.update(kw)
    return native_runtime.NativeRuntime(**base)


def test_admission_and_release_cycle():
    rt = _rt()
    assert rt.free_count == 16  # page 0 reserved
    s0 = rt.try_admit(10, 6)  # total 16 -> 2 pages
    assert s0 == 0
    assert rt.free_count == 14
    assert rt.inflight_tokens == 16
    assert len(rt.slot_pages(s0)) == 2
    s1 = rt.try_admit(60, 60)  # clamped to max_context 64 -> 8 pages
    assert s1 == 1
    assert rt.free_count == 6
    rt.release(s0)
    assert rt.free_count == 8
    assert rt.slot_pages(s0) == []
    assert rt.try_admit(100, 100) == 0  # reuses the freed slot
    rt.release(0)
    rt.release(1)
    assert rt.free_count == 16


def test_admission_rejections():
    rt = _rt(num_pages=5)  # 4 usable pages
    assert rt.try_admit(60, 60) == -1  # needs 8 pages > 4 free
    s = rt.try_admit(20, 4)  # 24 tokens -> 3 pages
    assert s == 0
    assert rt.try_admit(20, 4) == -1  # only 1 page left
    # slot exhaustion
    rt2 = _rt(num_slots=1)
    assert rt2.try_admit(4, 4) == 0
    assert rt2.try_admit(4, 4) == -1
    # token budget: second admission would exceed max_batch_tokens
    rt3 = _rt(max_batch_tokens=20)
    assert rt3.try_admit(10, 6) == 0  # 16 <= 20 (first always admitted)
    assert rt3.try_admit(10, 6) == -1


def test_dense_views_track_state():
    rt = _rt()
    s = rt.try_admit(9, 4)
    rt.arm_slot(s, 9, 42, 0.5, 0.9, 7)
    assert rt.last[s] == 42
    assert rt.past_len[s] == 9
    assert rt.temp[s] == np.float32(0.5)
    assert rt.top_p[s] == np.float32(0.9)
    assert rt.top_k[s] == 7
    assert rt.table[s, 0] != 0 and rt.table[s, 2] == 0
    rt.note_token(s, 43)
    assert rt.last[s] == 43 and rt.past_len[s] == 10
    assert rt.emitted(s) == 2  # arm counts the prefill-sampled token
    rt.release(s)
    assert rt.last[s] == 0 and rt.past_len[s] == 0
    assert not rt.is_active(s)


def test_batcher_native_vs_python_parity(tiny_ecfg, byte_tok, monkeypatch):
    """Greedy generation must be bit-identical with the native core
    disabled (SUTRO_NATIVE_RUNTIME=0) and enabled."""
    from sutro_tpu.engine.runner import ModelRunner

    texts = ["alpha", "beta gamma", "delta epsilon zeta", ""]

    def run(native: bool):
        monkeypatch.setenv("SUTRO_NATIVE_RUNTIME", "1" if native else "0")
        # reset the module's load cache so the env var takes effect
        native_runtime._lib = None
        native_runtime._lib_failed = False
        runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], tiny_ecfg)
        b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
        assert (b.native is not None) == native
        res = {}
        b.run(
            [
                GenRequest(
                    row_id=i,
                    prompt_ids=np.array(byte_tok.encode(t), np.int32),
                    max_new_tokens=12,
                    temperature=0.0,
                )
                for i, t in enumerate(texts)
            ],
            on_result=lambda r: res.__setitem__(r.row_id, r),
        )
        return {
            i: (tuple(r.token_ids), r.finish_reason) for i, r in res.items()
        }

    py = run(False)
    nat = run(True)
    assert py == nat
    native_runtime._lib = None
    native_runtime._lib_failed = False


def test_admit_pfx_prefix_filling_whole_row_rejected():
    """A shared prefix occupying the full table row leaves no room for
    the slot's mandatory own page: admission must fail cleanly (the
    round-5 C++ audit found row[npfx + own - 1] would otherwise write
    one int past the row — past the whole table vector for the last
    slot)."""
    rt = _rt(num_pages=65, max_pages_per_seq=8, max_context=64)
    pfx = rt.alloc_pages(8)          # prefix fills the whole row
    assert pfx is not None
    # probe every slot INCLUDING the last (the heap-smash position):
    # occupy preceding slots with plain rows so each rejected pfx
    # admission actually lands on a later free slot
    for i in range(rt.num_slots):
        assert rt.try_admit_pfx(60, 4, pfx) == -1
        if i < rt.num_slots - 1:
            assert rt.try_admit(8, 8) >= 0   # occupy this slot
    # sanity: a prefix that leaves room still admits
    rt.free_pages(pfx)
    pfx7 = rt.alloc_pages(7)
    slot = rt.try_admit_pfx(58, 6, pfx7)   # need=8, own=1 fits
    assert slot >= 0
    assert rt.slot_pages(slot)  # one own page at the tail
