"""Live SLO monitor (sutro_tpu/telemetry/monitor.py, OBSERVABILITY.md
"Live monitor").

Covers the PR's acceptance criteria and test satellites:

1. rule units — hysteresis + debounce state machine: flapping at the
   threshold produces EXACTLY one fire/resolve pair; a dormant metric
   holds a firing alert and disarms a pending one;
2. windowed percentiles — bucket-interpolated p50/p99 agree with a
   brute-force recompute to within bucket resolution;
3. tenant attribution — the cardinality cap collapses excess tenant
   labels into ``_overflow`` instead of growing without bound;
4. the live acceptance run — a multi-window job is observed MID-JOB
   via ``GET /monitor``: a doctor verdict with the in-flight marker,
   one alert firing AND resolving before the job terminates, all while
   concurrent ``/monitor`` + ``/metrics`` scrapers hammer the server;
5. disabled semantics — ``SUTRO_TELEMETRY=0`` (or ``SUTRO_MONITOR=0``)
   means no monitor object, 404s on both endpoints, and a stopped
   sampler doing zero work (the op-census leg in
   benchmarks/profile_host_overhead.py --monitor asserts the budget).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from sutro_tpu import telemetry
from sutro_tpu.engine import faults
from sutro_tpu.engine.api import LocalEngine
from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.interfaces import JobStatus
from sutro_tpu.telemetry.monitor import (
    Monitor,
    SLORule,
    percentile_from_buckets,
)
from sutro_tpu.telemetry.registry import MetricsRegistry


# ---------------------------------------------------------------------------
# rule units: hysteresis + debounce
# ---------------------------------------------------------------------------


def _drive(rule, values):
    """Feed a value sequence through one rule; returns the
    (rule, state) transition events in order."""
    mon = Monitor(rules=[rule])
    events = []
    for i, v in enumerate(values):
        stats = {} if v is None else {rule.metric: v}
        events.extend(
            (e["rule"], e["state"])
            for e in mon._evaluate_rules(stats, float(i))
        )
    return events, mon


def test_flap_at_threshold_exactly_one_fire_resolve_pair():
    """The hysteresis band (clear < value <= threshold) holds state:
    a value flapping between breach and the band fires ONCE, and only
    a genuine drop past the clear level resolves — no alert churn."""
    rule = SLORule(
        "q", metric="quarantine_rate", op=">", threshold=0.05,
        clear=0.01, for_ticks=2, clear_ticks=2,
    )
    events, mon = _drive(
        rule, [0.10, 0.10, 0.03, 0.10, 0.03, 0.005, 0.005]
    )
    assert events == [("q", "firing"), ("q", "resolved")]
    assert mon._rule_state["q"].state == "ok"


def test_debounce_single_breach_never_fires():
    """One breaching tick (< for_ticks) arms pending only; the next
    cleared tick disarms it. No events."""
    rule = SLORule(
        "q", metric="quarantine_rate", op=">", threshold=0.05,
        clear=0.01, for_ticks=2, clear_ticks=2,
    )
    events, mon = _drive(rule, [0.10, 0.005, 0.10, 0.005])
    assert events == []
    assert mon._rule_state["q"].state == "ok"


def test_less_than_rule_and_clear_default():
    """op="<" rules (fleet shrunk, rows stalled) breach below the
    threshold; clear defaults to the threshold itself."""
    rule = SLORule(
        "fleet", metric="dp_fleet_size", op="<", threshold=1.0,
        for_ticks=1, clear_ticks=1, severity="critical",
    )
    events, _ = _drive(rule, [2.0, 0.0, 0.0, 1.0])
    assert events == [("fleet", "firing"), ("fleet", "resolved")]


def test_dormant_metric_holds_firing_and_disarms_pending():
    """No data is not evidence of recovery: a missing metric (workload
    not running) must hold a firing alert, but disarm a pending one."""
    rule = SLORule(
        "q", metric="quarantine_rate", op=">", threshold=0.05,
        clear=0.01, for_ticks=2, clear_ticks=2,
    )
    # fire, then the metric disappears: alert must stay firing
    events, mon = _drive(rule, [0.10, 0.10, None, None])
    assert events == [("q", "firing")]
    assert mon._rule_state["q"].state == "firing"
    # pending (one breach), then dormant: disarmed without firing
    events, mon = _drive(rule, [0.10, None, 0.005])
    assert events == []
    assert mon._rule_state["q"].state == "ok"


def test_resolve_requires_consecutive_clear_ticks():
    """clear_ticks debounce on the way down mirrors for_ticks on the
    way up: clear, re-breach resets the clear streak."""
    rule = SLORule(
        "q", metric="quarantine_rate", op=">", threshold=0.05,
        clear=0.01, for_ticks=1, clear_ticks=2,
    )
    events, mon = _drive(
        rule, [0.10, 0.005, 0.10, 0.005, 0.005]
    )
    # second breach while firing does NOT re-fire; the two final
    # cleared ticks resolve once
    assert events == [("q", "firing"), ("q", "resolved")]


def test_alert_dump_errors_are_swallowed():
    """A failing flight-recorder dump is best-effort by contract: the
    monitor logs and keeps sampling (the chaos suite covers the
    tick-raise degrade path end to end)."""
    calls = []

    def bad_dump(job_id, ev):
        calls.append(job_id)
        raise OSError("disk full")

    mon = Monitor(
        rules=[],
        jobs_provider=lambda: [("j1", "RUNNING"), ("j2", "RUNNING")],
        alert_dump=bad_dump,
    )
    mon._dump_for_alert({"rule": "r", "state": "firing"})
    assert calls == ["j1", "j2"]  # every job attempted despite errors
    assert mon.failed is None


# ---------------------------------------------------------------------------
# windowed percentiles vs brute force
# ---------------------------------------------------------------------------


def _acc_for(buckets, values):
    """Build a registry-layout accumulator [b0..bn, +Inf, sum, count]
    from raw observations."""
    acc = [0.0] * (len(buckets) + 1) + [0.0, 0.0]
    for v in values:
        for i, le in enumerate(buckets):
            if v <= le:
                acc[i] += 1
                break
        else:
            acc[len(buckets)] += 1
        acc[-2] += v
        acc[-1] += 1
    return acc


def test_windowed_percentile_matches_brute_force_within_bucket():
    """The interpolated quantile must land inside the SAME bucket as a
    brute-force recompute over the raw sample — bucket resolution is
    the honest error bound a histogram can promise."""
    import numpy as np

    buckets = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)
    rng = np.random.default_rng(7)
    values = rng.gamma(shape=2.0, scale=0.03, size=2000)
    acc = _acc_for(buckets, values)

    for q in (0.50, 0.90, 0.99):
        est = percentile_from_buckets(buckets, acc, q)
        true = float(np.quantile(values, q))
        # bucket containing the true quantile -> [lo, hi] bound
        lo = 0.0
        hi = buckets[-1]
        for le in buckets:
            if true <= le:
                hi = le
                break
            lo = le
        assert est is not None
        assert lo - 1e-12 <= est <= hi + 1e-12, (
            f"q={q}: est {est} outside true-quantile bucket "
            f"[{lo}, {hi}] (true {true})"
        )


def test_percentile_edge_cases():
    buckets = (0.1, 0.5, 1.0)
    # empty accumulator
    assert percentile_from_buckets(buckets, [0, 0, 0, 0, 0.0, 0], 0.5) \
        is None
    # mass in the +Inf bucket clamps to the top finite boundary
    acc = _acc_for(buckets, [5.0, 7.0, 9.0])
    assert percentile_from_buckets(buckets, acc, 0.5) == 1.0
    # linear interpolation inside one bucket: 2 below 0.1, 6 in
    # (0.1, 0.5], 2 in (0.5, 1.0] -> p50 target 5 of 10 -> 0.3
    acc = [2, 6, 2, 0, 5.0, 10]
    assert percentile_from_buckets(buckets, acc, 0.5) == pytest.approx(
        0.3
    )


# ---------------------------------------------------------------------------
# tenant cardinality cap
# ---------------------------------------------------------------------------


def test_tenant_cardinality_cap_collapses_to_overflow():
    """More distinct tenants than the cap must collapse into the
    ``_overflow`` series, never grow the registry unboundedly — the
    same contract every labeled metric carries."""
    # mechanics on a scratch registry with a tiny cap
    r = MetricsRegistry()
    c = r.counter("t_rows_total", labels=("tenant", "outcome"),
                  max_series=4)
    for i in range(10):
        c.inc(1.0, f"tenant-{i}", "ok")
    snap = dict()
    for name, lv, v in r.export_snapshot()["counters"]:
        if name == "t_rows_total":
            snap[tuple(lv)] = v
    assert len(snap) <= 5  # 4 admitted + the single overflow series
    assert snap[("_overflow", "_overflow")] >= 6.0

    # the REAL tenant counters carry the env-tunable cap
    assert telemetry.TENANT_ROWS_TOTAL.max_series == \
        telemetry.TENANT_MAX_SERIES
    telemetry.reset_for_tests()
    try:
        telemetry.set_enabled(True)
        for i in range(telemetry.TENANT_MAX_SERIES + 20):
            telemetry.TENANT_ROWS_TOTAL.inc(1.0, f"tenant-{i}", "ok")
        series = [
            tuple(lv)
            for name, lv, _v in
            telemetry.REGISTRY.export_snapshot()["counters"]
            if name == "sutro_tenant_rows_total"
        ]
        assert len(series) <= telemetry.TENANT_MAX_SERIES + 1
        assert ("_overflow", "_overflow") in series
    finally:
        telemetry.reset_for_tests()


# ---------------------------------------------------------------------------
# live acceptance: mid-job verdicts + alert lifecycle over GET /monitor
# ---------------------------------------------------------------------------


def _wait_terminal(eng, job_id, timeout=600):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = JobStatus(eng.job_status(job_id))
        if st.is_terminal() and st != JobStatus.CANCELLING:
            return st
        time.sleep(0.05)
    raise TimeoutError(f"{job_id} not terminal within {timeout}s")


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


@pytest.fixture()
def monitor_engine(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    monkeypatch.setenv("SUTRO_MONITOR_INTERVAL", "0.05")
    monkeypatch.setenv("SUTRO_MONITOR_WINDOW", "0.4")
    monkeypatch.delenv("SUTRO_MONITOR", raising=False)
    telemetry.reset_for_tests()
    telemetry.set_enabled(True)
    eng = LocalEngine(
        EngineConfig(
            kv_page_size=8,
            max_pages_per_seq=16,
            decode_batch_size=4,
            max_model_len=128,
            use_pallas=False,
            param_dtype="float32",
            activation_dtype="float32",
        )
    )
    yield eng
    faults.clear()
    eng.close(timeout=5)
    telemetry.reset_for_tests()


def test_live_monitor_acceptance(monitor_engine):
    """Acceptance criterion verbatim: a multi-window job is driven
    while ``GET /monitor`` observes (a) a mid-job doctor verdict with
    the in-flight marker and (b) one alert firing AND resolving —
    all BEFORE the job reaches a terminal state — while concurrent
    ``/monitor`` + ``/metrics`` scrapers run against the same server.
    The alert metric (windowed quarantine rate) is pumped through the
    real registry counters on a deterministic schedule so the test
    pins the window/rule machinery, not CPU decode timing."""
    from sutro_tpu.server import start_server_thread

    eng = monitor_engine
    assert eng.monitor is not None and eng.monitor.running
    eng.monitor.set_rules([
        SLORule(
            "q_rate", metric="quarantine_rate", op=">",
            threshold=0.05, clear=0.01, for_ticks=1, clear_ticks=1,
            workload="batch",
        ),
    ])
    server, _, url = start_server_thread(eng)
    stop = threading.Event()
    scrape_errors = []

    def scraper(path):
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"{url}/{path}", timeout=10
                ) as r:
                    body = r.read()
                    if path == "monitor":
                        json.loads(body)
                    elif b"sutro_rows_total" not in body:
                        scrape_errors.append(f"{path}: missing metric")
            except Exception as e:  # noqa: BLE001
                scrape_errors.append(f"{path}: {type(e).__name__}: {e}")
                return
            time.sleep(0.02)

    def feeder():
        # ~0.5s of quarantine burst (rate ~0.29 >> threshold), then ok
        # rows only until the window slides past the burst -> rate 0
        t0 = time.monotonic()
        while not stop.is_set() and time.monotonic() - t0 < 6.0:
            telemetry.ROWS_TOTAL.inc(5.0, "ok")
            if time.monotonic() - t0 < 0.5:
                telemetry.ROWS_TOTAL.inc(2.0, "quarantined")
            time.sleep(0.05)

    threads = [
        threading.Thread(target=scraper, args=("monitor",), daemon=True),
        threading.Thread(target=scraper, args=("metrics",), daemon=True),
        threading.Thread(target=feeder, daemon=True),
    ]
    try:
        jid = eng.submit_batch_inference({
            "model": "tiny-dense",
            "inputs": [f"monitor row {i}" for i in range(128)],
            "sampling_params": {"max_new_tokens": 16,
                                "temperature": 0.0},
            "tenant": "acme",
        })
        for t in threads:
            t.start()

        fired = resolved = verdict_seen = False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = JobStatus(eng.job_status(jid))
            doc = _get_json(f"{url}/monitor")["monitor"]
            if st.is_terminal():
                break
            states = {
                (e["rule"], e["state"])
                for e in doc["alerts"]["events"]
            }
            # every observation below happens while the job is
            # provably non-terminal (status read BEFORE the scrape)
            fired = fired or ("q_rate", "firing") in states
            resolved = resolved or ("q_rate", "resolved") in states
            for v in doc["verdicts"].values():
                if v.get("in_flight"):
                    verdict_seen = True
            if fired and resolved and verdict_seen:
                break
            time.sleep(0.05)

        assert fired, "alert never fired before the job terminated"
        assert resolved, (
            "alert never resolved before the job terminated"
        )
        assert verdict_seen, (
            "no in-flight doctor verdict observed mid-job"
        )

        # NDJSON stream: bounded tick count, then a terminal record
        with urllib.request.urlopen(
            f"{url}/monitor/stream?ticks=3", timeout=30
        ) as r:
            lines = [
                json.loads(ln)
                for ln in r.read().decode().splitlines() if ln
            ]
        assert [ln["t"] for ln in lines] == [
            "tick", "tick", "tick", "end",
        ]
        assert all("rates" in ln for ln in lines[:-1])

        assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
        stop.set()
        assert not scrape_errors, scrape_errors

        # tenant attribution survived the whole path (terminal
        # accounting lands on the NEXT tick's snapshot — poll briefly)
        acme = {}
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            doc = _get_json(f"{url}/monitor")["monitor"]
            acme = doc["stats"]["tenants"].get("acme", {})
            if acme.get("rows_ok"):
                break
            time.sleep(0.05)
        assert acme.get("requests_batch") == 1.0
        assert acme.get("rows_ok") == 128.0
        # the alert transitions also landed on the counter surface
        text = urllib.request.urlopen(f"{url}/metrics").read().decode()
        assert 'sutro_monitor_alerts_total{rule="q_rate",' in text
    finally:
        stop.set()
        server.shutdown()


# ---------------------------------------------------------------------------
# disabled semantics
# ---------------------------------------------------------------------------


def test_disabled_telemetry_no_monitor_and_404(tmp_path, monkeypatch):
    """SUTRO_TELEMETRY=0: the engine never constructs a monitor and
    both endpoints 404 — same contract as every telemetry surface."""
    from sutro_tpu.server import start_server_thread

    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    telemetry.set_enabled(False)
    eng = LocalEngine(
        EngineConfig(
            kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
            max_model_len=128, use_pallas=False, param_dtype="float32",
            activation_dtype="float32",
        )
    )
    server, _, url = start_server_thread(eng)
    try:
        assert eng.monitor is None
        for path in ("monitor", "monitor/stream?ticks=1"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"{url}/{path}", timeout=10)
            assert exc.value.code == 404
        with pytest.raises(KeyError):
            eng.monitor_doc()
    finally:
        telemetry.set_enabled(True)
        server.shutdown()
        eng.close(timeout=5)


def test_monitor_env_switch_alone_disables(tmp_path, monkeypatch):
    """SUTRO_MONITOR=0 with telemetry ON: metrics still flow, the
    sampler just never exists."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    monkeypatch.setenv("SUTRO_MONITOR", "0")
    telemetry.set_enabled(True)
    eng = LocalEngine(
        EngineConfig(
            kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
            max_model_len=128, use_pallas=False, param_dtype="float32",
            activation_dtype="float32",
        )
    )
    try:
        assert eng.monitor is None
        with pytest.raises(KeyError):
            eng.monitor_doc()
    finally:
        eng.close(timeout=5)


def test_stopped_monitor_with_telemetry_off_does_zero_work():
    """A RUNNING sampler thread under SUTRO_TELEMETRY=0 must tick zero
    times (the --monitor op-census leg asserts the same with op
    counting; this is the cheap in-suite version)."""
    was = telemetry.enabled()
    telemetry.set_enabled(False)
    mon = Monitor(interval_s=0.01)
    try:
        mon.start()
        time.sleep(0.15)
        assert mon.snapshot_doc()["ticks"] == 0
        assert mon.failed is None
    finally:
        mon.stop()
        telemetry.set_enabled(was)


def test_stream_event_cursor_survives_ring_saturation(monkeypatch):
    """Alert events live in a bounded deque: once it saturates, old
    entries shift out and a positional stream cursor would silently
    replay or drop events. The cursor tracks the monotonic appended
    count instead."""
    from sutro_tpu.telemetry import monitor as monitor_mod

    monkeypatch.setattr(monitor_mod, "EVENT_CAP", 4)
    mon = Monitor(rules=[])

    def publish(events):
        with mon._lock:
            mon._events.extend(events)
            mon._events_seen += len(events)
            mon._seq += 1

    gen = mon.stream(max_ticks=3, timeout_s=2.0)
    publish([{"rule": f"r{i}"} for i in range(3)])
    rec = next(gen)
    assert [e["rule"] for e in rec["alert_events"]] == ["r0", "r1", "r2"]
    # six more events overflow the cap-4 ring: the stream must deliver
    # the four newest (the overflowed two are genuinely gone), not the
    # index-shifted tail a positional cursor would compute
    publish([{"rule": f"r{i}"} for i in range(3, 9)])
    rec = next(gen)
    assert [e["rule"] for e in rec["alert_events"]] == [
        "r5", "r6", "r7", "r8",
    ]
    # caught up: a tick with no fresh events carries none, even though
    # the ring still holds four entries
    publish([])
    rec = next(gen)
    assert rec["alert_events"] == []
