"""Child process for tests/test_multihost.py.

Each of two processes contributes 4 virtual CPU devices to one global
8-device engine mesh (dp=2 outermost / tp=4 innermost — the DCN-out,
ICI-in ordering of parallel/mesh.py). The ``data`` axis spans the
PROCESS boundary, so the cross-``data`` psum below rides the
inter-process (DCN-analog) transport, while the ``model``-axis
all-gather stays intra-process (ICI analog). The reference has no
distributed layer to mirror (its transport is HTTPS, SURVEY §5.8);
this validates our replacement actually crosses hosts.

Run via the parent test only — it needs JAX_COORDINATOR_ADDRESS,
JAX_NUM_PROCESSES and JAX_PROCESS_ID in the environment.
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from sutro_tpu.parallel.mesh import init_distributed, make_mesh  # noqa: E402


def main() -> None:
    init_distributed()
    pid = jax.process_index()
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    assert len(jax.local_devices()) == 4

    mesh = make_mesh(dp=2, tp=4)
    assert mesh.shape["data"] == 2 and mesh.shape["model"] == 4

    # global [8, 16] array: row i carries value i; dp shards rows across
    # processes, tp shards columns within each process
    spec = P("data", "model")
    rows = jnp.broadcast_to(
        jnp.arange(8.0)[:, None], (8, 16)
    )
    arr = jax.make_array_from_callback(
        (8, 16),
        NamedSharding(mesh, spec),
        lambda idx: np.asarray(rows[idx]),
    )

    @jax.jit
    def reduce_all(x):
        # full sum touches BOTH axes: the partial sums of the two
        # process-local row shards combine across the data axis
        return jnp.sum(x)

    total = float(reduce_all(arr))
    assert total == float(sum(range(8)) * 16), total

    # cross-process collective inside shard_map: psum over "data"
    # moves activations between the two processes
    from functools import partial

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P("data", "model"),
        out_specs=P(None, "model"),
    )
    def dp_psum(x):
        return jax.lax.psum(x, "data")

    def fetch_replicated(x):
        """Gather a sharded global array to every process as numpy."""
        return np.asarray(
            jax.device_get(
                jax.jit(
                    lambda v: v, out_shardings=NamedSharding(mesh, P())
                )(x)
            )
        )

    out = jax.jit(dp_psum)(arr)
    # rows 0..3 (proc 0) + rows 4..7 (proc 1) pairwise: row r of the
    # result = r + (r+4)
    got = fetch_replicated(out)
    want = np.broadcast_to(
        (np.arange(4.0) + np.arange(4.0, 8.0))[:, None], (4, 16)
    )
    np.testing.assert_allclose(got, want)

    # full model forward across the boundary: params TP-sharded over
    # the intra-process "model" axis, batch DP-sharded over the
    # PROCESS-spanning "data" axis — the logits must match an
    # unsharded local forward bit-for-bit shape-wise and numerically
    from sutro_tpu.models import transformer
    from sutro_tpu.models.configs import MODEL_CONFIGS
    from sutro_tpu.parallel.sharding import param_shardings

    cfg = MODEL_CONFIGS["tiny-dense"]
    params = transformer.init_params(
        cfg, jax.random.PRNGKey(0), jnp.float32
    )
    sharded = jax.device_put(params, param_shardings(params, mesh))
    B, T = 4, 6
    ids_np = np.arange(B * T, dtype=np.int32).reshape(B, T) % 100
    ids = jax.make_array_from_callback(
        (B, T),
        NamedSharding(mesh, P("data", None)),
        lambda idx: ids_np[idx],
    )
    pos = jax.make_array_from_callback(
        (B, T),
        NamedSharding(mesh, P("data", None)),
        lambda idx: np.broadcast_to(
            np.arange(T, dtype=np.int32)[None], (B, T)
        )[idx],
    )
    vlen = jax.make_array_from_callback(
        (B,),
        NamedSharding(mesh, P("data")),
        lambda idx: np.full((B,), T, np.int32)[idx],
    )

    @jax.jit
    def fwd(p, i, po, vl):
        logits, _, _ = transformer.forward(cfg, p, i, po, vl)
        return logits

    logits = fwd(sharded, ids, pos, vlen)
    got = fetch_replicated(logits)
    ref, _, _ = transformer.forward(
        cfg,
        params,
        jnp.asarray(ids_np),
        jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T)),
        jnp.full((B,), T, jnp.int32),
    )
    np.testing.assert_allclose(got, np.asarray(ref), atol=2e-4, rtol=2e-4)

    print(f"MULTIHOST_OK process={pid}", flush=True)


if __name__ == "__main__":
    main()
