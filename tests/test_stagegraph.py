"""Server-side stage graphs (engine/stagegraph.py): DAG batch jobs.

Covers the acceptance contract end to end on the live tiny engine:

- submit-time validation: every structural defect is a structured
  ``InvalidGraph`` with a machine-readable ``reason``, surfaced as
  HTTP 400 ``INVALID_GRAPH`` through the server and as a typed raise
  through the SDK — never a 500 traceback or a half-created job;
- a generate -> score -> rank chain submitted as ONE job is
  bit-identical at temperature 0 to the client-side three-job
  sequence, while the per-stage telemetry proves downstream stages
  admitted rows BEFORE their upstream finished (no full-stage
  barrier) and the shared system prompt rode the prefix store;
- row failure domains stay row-level ACROSS stages: a poison row
  quarantined in stage one propagates as an error placeholder (no LM
  call downstream), recorded per stage in the parent failure_log;
- host-side reduce stages (filter / pair / elo) are pure and
  deterministic, so crash-resume recomputes them bit-identically;
- whole-DAG pricing: dry_run charges every stage, not just the root.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from sutro_tpu.engine import faults
from sutro_tpu.engine.stagegraph import (
    InvalidGraph,
    StageSpec,
    _parse_rankings,
    estimate_stage_rows,
    graph_cost_bounds,
    initial_stages_state,
    parse_graph,
    run_host_stage_kind,
    stage_job_id,
)
from sutro_tpu.interfaces import JobStatus


def _wait_terminal(eng, job_id, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = JobStatus(eng.job_status(job_id))
        if st.is_terminal() and st != JobStatus.CANCELLING:
            return st
        time.sleep(0.05)
    raise TimeoutError(f"{job_id} not terminal within {timeout}s")


def _submit(eng, inputs, stages=None, max_new=16, **kw):
    payload = {
        "model": "tiny-dense",
        "inputs": list(inputs),
        "sampling_params": {"temperature": 0.0, "max_new_tokens": max_new},
        "job_priority": 0,
    }
    if stages is not None:
        payload["stages"] = stages
    payload.update(kw)
    return eng.submit_batch_inference(payload)


# ---------------------------------------------------------------------------
# parse_graph: structured validation reasons
# ---------------------------------------------------------------------------


def _parse(stages, resolve=None):
    return parse_graph(stages, default_model="tiny-dense", resolve=resolve)


@pytest.mark.parametrize(
    "stages, reason",
    [
        ("not a list", "not_a_list"),
        ([], "not_a_list"),
        (
            [{"name": f"s{i}", "kind": "map",
              "after": [f"s{i - 1}"] if i else []} for i in range(17)],
            "too_many_stages",
        ),
        (["nope"], "not_a_dict"),
        # the name becomes a jobstore sub-directory: traversal must die
        # at validation, not at path-join time
        ([{"name": "../escape", "kind": "map"}], "bad_name"),
        ([{"kind": "map"}], "bad_name"),
        (
            [{"name": "a", "kind": "map"},
             {"name": "a", "kind": "map", "after": ["a"]}],
            "duplicate_name",
        ),
        ([{"name": "a", "kind": "reduce"}], "bad_kind"),
        ([{"name": "a", "kind": "map", "after": 7}], "bad_after"),
        (
            [{"name": "a", "kind": "map"}, {"name": "b", "kind": "map"},
             {"name": "c", "kind": "map", "after": ["a", "b"]}],
            "multi_parent_unsupported",
        ),
        ([{"name": "f", "kind": "filter"}], "missing_parent"),
        ([{"name": "e", "kind": "elo"}], "missing_parent"),
        (
            [{"name": "a", "kind": "map",
              "prompt_template": "no placeholder"}],
            "bad_template",
        ),
        (
            [{"name": "a", "kind": "map"},
             {"name": "f", "kind": "filter", "after": ["a"],
              "predicate": {"type": "regex"}}],
            "bad_predicate",
        ),
        (
            [{"name": "a", "kind": "map", "after": ["ghost"]}],
            "dangling_edge",
        ),
        ([{"name": "a", "kind": "map", "after": ["a"]}], "cycle"),
        (
            [{"name": "a", "kind": "map", "after": ["b"]},
             {"name": "b", "kind": "map", "after": ["a"]}],
            "cycle",
        ),
        (
            [{"name": "a", "kind": "map"}, {"name": "b", "kind": "map"}],
            "multiple_sinks",
        ),
    ],
)
def test_parse_graph_structured_reasons(stages, reason):
    with pytest.raises(InvalidGraph) as e:
        _parse(stages)
    assert e.value.reason == reason
    assert e.value.code == "INVALID_GRAPH"
    assert e.value.status == 400


def test_parse_graph_unknown_model_fails_at_submit():
    def resolve(model):
        if model != "tiny-dense":
            raise ValueError(f"Unknown model {model!r}")

    with pytest.raises(InvalidGraph) as e:
        _parse(
            [{"name": "a", "kind": "map", "model": "not-a-model"}],
            resolve=resolve,
        )
    assert e.value.reason == "unknown_model"
    # the default model fills unset map stages and must resolve too
    g = _parse([{"name": "a", "kind": "map"}], resolve=resolve)
    assert g.by_name["a"].model == "tiny-dense"


def test_parse_graph_valid_chain_topo_and_estimates():
    g = _parse(
        [
            # submit order deliberately scrambled: topo() must not care
            {"name": "elo", "kind": "elo", "after": ["rank"]},
            {"name": "rank", "kind": "map", "after": ["pairs"],
             "prompt_template": "rank: {input}"},
            {"name": "gen", "kind": "map"},
            {"name": "keep", "kind": "filter", "after": ["gen"]},
            {"name": "pairs", "kind": "pair", "after": ["keep"],
             "max_pairs": 5},
        ]
    )
    assert [s.name for s in g.topo()] == [
        "gen", "keep", "pairs", "rank", "elo",
    ]
    assert g.sink == "elo"
    rows = estimate_stage_rows(g, 8)
    # filter/elo are bounded by their parent; pair is n*(n-1)/2 capped
    assert rows == {"gen": 8, "keep": 8, "pairs": 5, "rank": 5, "elo": 5}
    state = initial_stages_state(g, 8)
    assert state["pairs"] == {
        "status": "pending", "kind": "pair", "rows_done": 0,
        "rows_total": 5, "quarantined": 0,
    }
    assert stage_job_id("job-1", "gen") == "job-1/stages/gen"
    # wire round-trip: to_payload re-parses to the same graph
    g2 = _parse(g.to_payload())
    assert [s.name for s in g2.topo()] == [s.name for s in g.topo()]


def test_graph_cost_bounds_price_downstream_stages():
    chain = _parse(
        [
            {"name": "gen", "kind": "map",
             "sampling_params": {"max_new_tokens": 16}},
            {"name": "score", "kind": "map", "after": ["gen"],
             "prompt_template": "score: {input}",
             "sampling_params": {"max_new_tokens": 8}},
        ]
    )
    extra_in, extra_new = graph_cost_bounds(chain, 10, 16)
    # the score stage adds 10 prompts bounded by gen's max_new plus
    # template overhead, and 10 * 8 output tokens
    assert extra_in >= 10 * 16
    assert extra_new == 10 * 8
    # a single root map at the default cap adds nothing beyond the
    # plain submit's own bound (the pricing side of the off switch)
    single = _parse([{"name": "gen", "kind": "map"}])
    assert graph_cost_bounds(single, 10, 16) == (0, 0)


# ---------------------------------------------------------------------------
# host stage kinds: pure, deterministic reduces
# ---------------------------------------------------------------------------


def _spec(d):
    d.setdefault("after", ["up"])
    return StageSpec({"name": d.pop("name", "host"), **d})


def test_filter_stage_predicates():
    rows = [(0, "short"), (1, "a much longer output"), (2, "x ok y")]
    contains = _spec({"kind": "filter",
                      "predicate": {"type": "contains", "value": "ok"}})
    assert run_host_stage_kind(contains, rows) == ["x ok y"]
    minlen = _spec({"kind": "filter",
                    "predicate": {"type": "min_length", "value": 7}})
    assert run_host_stage_kind(minlen, rows) == ["a much longer output"]
    keep_all = _spec({"kind": "filter"})  # not_error: errors pre-dropped
    assert run_host_stage_kind(keep_all, rows) == [o for _, o in rows]


def test_pair_stage_round_robin_and_cap():
    rows = [(0, "p"), (1, "q"), (3, "r")]
    spec = _spec({"kind": "pair"})
    pairs = [json.loads(p) for p in run_host_stage_kind(spec, rows)]
    assert pairs == [
        {"a": "p", "b": "q", "a_row": 0, "b_row": 1},
        {"a": "p", "b": "r", "a_row": 0, "b_row": 3},
        {"a": "q", "b": "r", "a_row": 1, "b_row": 3},
    ]
    capped = _spec({"kind": "pair", "max_pairs": 2})
    assert len(run_host_stage_kind(capped, rows)) == 2


def test_elo_stage_deterministic_and_tolerant():
    outputs = [
        (0, json.dumps({"ranking": ["a", "b"]})),
        (1, json.dumps(["a", "b"])),       # bare-array form accepted
        (2, "not json at all"),            # LM noise: skipped, not fatal
        (3, json.dumps({"ranking": []})),  # empty ranking: skipped
    ]
    assert _parse_rankings([o for _, o in outputs]) == [
        ["a", "b"], ["a", "b"],
    ]
    spec = _spec({"kind": "elo"})
    rows = [json.loads(r) for r in run_host_stage_kind(spec, outputs)]
    assert [r["player"] for r in rows] == ["a", "b"]
    assert rows[0]["elo"] > rows[1]["elo"]
    # resume recomputes host stages: byte-identical on a second run
    assert run_host_stage_kind(spec, outputs) == run_host_stage_kind(
        spec, outputs
    )


# ---------------------------------------------------------------------------
# wire + SDK surfaces: structured 400, never a half-created job
# ---------------------------------------------------------------------------

_BAD_STAGES = [
    {"name": "a", "kind": "map", "after": ["ghost"]},
]


def test_http_invalid_graph_is_structured_400(live_engine):
    eng, url, _home = live_engine
    before = {j["job_id"] for j in eng.list_jobs()}
    req = urllib.request.Request(
        url + "/batch-inference",
        data=json.dumps(
            {"model": "tiny-dense", "inputs": ["x"], "stages": _BAD_STAGES}
        ).encode(),
        headers={
            "Content-Type": "application/json",
            "Authorization": "Bearer test-key",
        },
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=60)
    assert e.value.code == 400
    body = json.loads(e.value.read().decode())
    assert body["error"]["code"] == "INVALID_GRAPH"
    assert body["error"]["reason"] == "dangling_edge"
    assert "ghost" in body["error"]["message"]
    # validation ran BEFORE any record existed
    assert {j["job_id"] for j in eng.list_jobs()} == before


def test_sdk_run_graph_invalid_graph_typed_raise(live_engine, monkeypatch):
    engine, _url, home = live_engine
    monkeypatch.setenv("SUTRO_HOME", home)
    from sutro_tpu.sdk import Sutro

    so = Sutro(api_key="test-key")
    so._engine = engine
    with pytest.raises(InvalidGraph) as e:
        so.run_graph(
            ["x"],
            stages=[
                {"name": "a", "kind": "map", "after": ["b"]},
                {"name": "b", "kind": "map", "after": ["a"]},
            ],
            model="tiny-dense",
            stay_attached=False,
        )
    assert e.value.reason == "cycle"


# ---------------------------------------------------------------------------
# acceptance: one DAG job == the client-side job sequence, bit for bit
# ---------------------------------------------------------------------------

_SP_GEN = "You are a terse poet."
_SP_SCORE = "You are a strict grader."


def test_graph_chain_bit_identical_to_client_sequence(
    live_engine, monkeypatch
):
    """generate -> score -> rank as ONE job: results bit-identical at
    temperature 0 to three sequential client-side jobs, per-stage spans
    prove streaming admission (score's first result lands before gen
    finishes), and the shared system prompt pays prefix-store savings."""
    eng, _url, _home = live_engine
    # feed every row as it lands so inter-stage streaming is observable
    # at this tiny row count (default cadence is 16). n deliberately
    # NOT a multiple of decode_batch_size=4: admission drains jobs in
    # seq order, so gen's final short batch leaves free slots that fed
    # score rows claim while gen is still decoding — making the
    # no-barrier overlap visible in completion times, not just feeds
    monkeypatch.setenv("SUTRO_STAGE_FEED_EVERY", "1")
    n = 10
    inputs = [f"poem topic {i}" for i in range(n)]
    jid = _submit(
        eng, inputs,
        stages=[
            {"name": "gen", "kind": "map", "system_prompt": _SP_GEN,
             "sampling_params": {"max_new_tokens": 16}},
            {"name": "score", "kind": "map", "after": ["gen"],
             "system_prompt": _SP_SCORE,
             "prompt_template": "score this: {input}",
             "sampling_params": {"max_new_tokens": 8}},
            {"name": "rank", "kind": "map", "after": ["score"],
             "prompt_template": "rank: {input}",
             "sampling_params": {"max_new_tokens": 4}},
        ],
    )
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED

    # --- the client-side equivalent: three jobs, two round-trips ---
    j1 = _submit(eng, inputs, max_new=16, system_prompt=_SP_GEN)
    assert _wait_terminal(eng, j1) == JobStatus.SUCCEEDED
    out1 = eng.job_results(j1)["outputs"]
    j2 = _submit(
        eng, [f"score this: {o}" for o in out1], max_new=8,
        system_prompt=_SP_SCORE,
    )
    assert _wait_terminal(eng, j2) == JobStatus.SUCCEEDED
    out2 = eng.job_results(j2)["outputs"]
    j3 = _submit(eng, [f"rank: {o}" for o in out2], max_new=4)
    assert _wait_terminal(eng, j3) == JobStatus.SUCCEEDED
    out3 = eng.job_results(j3)["outputs"]

    res = eng.job_results(jid)
    assert res["outputs"] == out3          # the sink IS the job result
    assert "errors" not in res
    # intermediate stages are addressable jobs in their own right
    assert eng.job_results(stage_job_id(jid, "gen"))["outputs"] == out1
    assert eng.job_results(stage_job_id(jid, "score"))["outputs"] == out2

    # durable per-stage rollup on the parent record
    state = eng.jobs.get(jid).stages_state
    assert set(state) == {"gen", "score", "rank"}
    for name, entry in state.items():
        assert entry["status"] == "succeeded", name
        assert entry["rows_done"] == n
        assert entry["quarantined"] == 0

    from sutro_tpu import telemetry

    spans = telemetry.job(jid).attrs["stages"]
    # streaming admission observable (acceptance criterion): each
    # downstream stage produced its FIRST row before its upstream
    # produced its LAST — no full-stage barrier anywhere in the chain
    assert spans["score"]["first_result_s"] < spans["gen"]["done_s"]
    assert spans["rank"]["first_result_s"] < spans["score"]["done_s"]
    # shared context rode the radix prefix store across rows/stages
    prefix = telemetry.job(jid).attrs.get("prefix") or {}
    assert prefix.get("saved_tokens", 0) > 0


def test_graph_quarantine_propagates_per_stage(live_engine, monkeypatch):
    """Row failure domains across stages: a row poisoned in gen is
    quarantined THERE, skipped (no LM call) downstream with the drop
    recorded per stage in the parent failure_log, and every other row
    is bit-identical to the clean run."""
    eng, _url, _home = live_engine
    monkeypatch.setenv("SUTRO_STAGE_FEED_EVERY", "1")
    n = 8
    inputs = [f"quarantine row {i}" for i in range(n)]
    stages = [
        {"name": "gen", "kind": "map",
         "sampling_params": {"max_new_tokens": 8}},
        {"name": "score", "kind": "map", "after": ["gen"],
         "prompt_template": "score this: {input}",
         "sampling_params": {"max_new_tokens": 4}},
    ]
    ref_jid = _submit(eng, inputs, stages=stages)
    assert _wait_terminal(eng, ref_jid) == JobStatus.SUCCEEDED
    ref = eng.job_results(ref_jid)["outputs"]

    # poison row 3 inside the gen stage only (job= matches the nested
    # stage job id, so the score stage and plain jobs are untouched)
    faults.configure("row.decode:error:rows=3,job=stages/gen")
    try:
        jid = _submit(eng, inputs, stages=stages)
        assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    finally:
        faults.clear()
    res = eng.job_results(jid)
    assert res["outputs"][3] is None
    assert res["errors"][3]
    for i in range(n):
        if i != 3:
            assert res["outputs"][i] == ref[i], f"row {i} diverged"
    state = eng.jobs.get(jid).stages_state
    assert state["gen"]["quarantined"] == 1
    assert state["score"]["quarantined"] == 1  # the propagated placeholder
    log = eng.jobs.get(jid).failure_log or []
    skips = [e for e in log if e["event"] == "stage_row_skipped"]
    assert [(e["stage"], e["source_stage"], e["row_id"]) for e in skips] == [
        ("score", "gen", 3)
    ]


def test_graph_dry_run_prices_whole_dag(live_engine):
    """dry_run on a DAG charges every stage up front: the estimate is
    strictly above the same submit without the downstream stage."""
    eng, _url, _home = live_engine
    inputs = [f"price row {i}" for i in range(10)]
    plain = _submit(eng, inputs, dry_run=True)
    assert _wait_terminal(eng, plain) == JobStatus.SUCCEEDED
    graph = _submit(
        eng, inputs, dry_run=True,
        stages=[
            {"name": "gen", "kind": "map"},
            {"name": "score", "kind": "map", "after": ["gen"],
             "prompt_template": "score this: {input}"},
        ],
    )
    assert _wait_terminal(eng, graph) == JobStatus.SUCCEEDED
    plain_est = eng.jobs.get(plain).cost_estimate
    graph_est = eng.jobs.get(graph).cost_estimate
    assert graph_est > plain_est > 0


# ---------------------------------------------------------------------------
# wire frames: the per-stage NDJSON progress record
# ---------------------------------------------------------------------------


def test_stage_progress_frame_roundtrip():
    from sutro_tpu.engine.stageframes import (
        parse_stage_progress,
        rollup_counts,
        stage_progress_frame,
    )

    roll = {
        "gen": {"status": "running", "kind": "map", "rows_done": 3,
                "rows_total": 8, "quarantined": 1},
    }
    frame = stage_progress_frame(roll)
    assert frame["update_type"] == "stages"  # old readers skip, not die
    assert parse_stage_progress(json.loads(json.dumps(frame))) == roll
    assert parse_stage_progress({"update_type": "progress"}) is None
    counts = rollup_counts(roll["gen"])
    assert counts["rows_done"] == 3 and counts["quarantined"] == 1
