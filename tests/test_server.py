"""Engine HTTP daemon (server.py): the SDK's remote backend against a live
in-process server — detach/attach across "processes", results, datasets,
cancellation, functions (wire contract SURVEY §3.6)."""

import numpy as np
import pytest

from sutro_tpu.engine.api import LocalEngine
from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.interfaces import JobStatus
from sutro_tpu.server import start_server_thread


@pytest.fixture(scope="module")
def served(tmp_path_factory, monkeypatch_module):
    """A live daemon over a tiny CPU engine + an SDK bound to it."""
    home = tmp_path_factory.mktemp("serve-home")
    monkeypatch_module.setenv("SUTRO_HOME", str(home))
    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", max_new_tokens=8,
    )
    engine = LocalEngine(ecfg)
    server, thread, url = start_server_thread(engine)
    from sutro_tpu.sdk import Sutro

    sdk = Sutro(api_key="test-key", base_url=url, backend="remote")
    sdk.set_serving_base_url(url)  # functions/run rides the serving host
    yield sdk, engine, url
    server.shutdown()


def test_auth_and_quotas(served):
    sdk, _, _ = served
    assert sdk.try_authentication()["authenticated"] is True
    quotas = sdk.get_quotas()
    assert quotas and all("row_quota" in q for q in quotas)


def test_infer_detach_results_roundtrip(served):
    sdk, engine, _ = served
    job_id = sdk.infer(
        ["hello", "world", "again"], model="tiny-dense", stay_attached=False
    )
    assert isinstance(job_id, str) and job_id.startswith("job-")
    df = sdk.await_job_completion(job_id, timeout=300)
    assert df is not None and len(df) == 3
    # a *different* client (fresh SDK) attaches to the same job
    from sutro_tpu.sdk import Sutro

    other = Sutro(api_key="k2", base_url=served[2], backend="remote")
    assert other.get_job_status(job_id) == JobStatus.SUCCEEDED.value
    df2 = other.get_job_results(job_id, disable_cache=True)
    assert list(df2["inference_result"]) == list(df["inference_result"])


def test_stream_progress_over_http(served):
    sdk, _, _ = served
    job_id = sdk.infer(["stream me"], model="tiny-dense", stay_attached=False)
    updates = list(sdk._iter_progress(job_id))
    assert any(u.get("update_type") == "progress" for u in updates)
    sdk.await_job_completion(job_id, timeout=300, obtain_results=False)


def test_job_record_and_list(served):
    sdk, _, _ = served
    jobs = sdk.list_jobs()
    assert jobs
    rec = sdk._fetch_job(jobs[0]["job_id"])
    assert "status" in rec and "num_rows" in rec


def test_cancel_queued_job(served):
    sdk, engine, _ = served
    # pile up work so the next job sits in the queue long enough to cancel
    blocker = sdk.infer(
        ["b"] * 4, model="tiny-dense", stay_attached=False
    )
    victim = sdk.infer(["v"] * 4, model="tiny-dense", stay_attached=False)
    out = sdk.cancel_job(victim)
    assert out["status"] in (
        JobStatus.CANCELLED.value, JobStatus.CANCELLING.value,
        JobStatus.SUCCEEDED.value,  # raced to completion: acceptable
    )
    sdk.await_job_completion(blocker, timeout=300, obtain_results=False)


def test_datasets_over_http(served, tmp_path):
    sdk, _, _ = served
    dataset_id = sdk.create_dataset()
    assert dataset_id.startswith("dataset-")
    src = tmp_path / "rows.csv"
    src.write_text("text\nalpha\nbeta\n")
    sdk.upload_to_dataset(dataset_id, [str(src)])
    assert sdk.list_dataset_files(dataset_id) == ["rows.csv"]
    listed = sdk.list_datasets()
    assert any(d["dataset_id"] == dataset_id for d in listed)
    out = sdk.download_from_dataset(
        dataset_id, output_path=str(tmp_path / "dl")
    )
    assert (tmp_path / "dl" / "rows.csv").read_text() == src.read_text()
    assert out and out[0].endswith("rows.csv")
    # dataset as inference input through the daemon
    job_id = sdk.infer(dataset_id, model="tiny-dense", column="text",
                       stay_attached=False)
    df = sdk.await_job_completion(job_id, timeout=300)
    assert len(df) == 2


def test_functions_run_over_http(served):
    sdk, _, _ = served
    out = sdk.run_function(name="tiny-dense", input_data={"q": "hi"})
    assert "response" in out and out["run_id"].startswith("job-")
    assert out["usage"]["input_tokens"] > 0


def test_unknown_endpoint_404(served):
    sdk, _, _ = served
    resp = sdk.do_request("get", "no-such-endpoint")
    assert resp.status_code == 404
