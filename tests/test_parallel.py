"""Multi-device sharding tests on the 8-way virtual CPU mesh (conftest sets
xla_force_host_platform_device_count=8 — SURVEY §4's multi-device CI
strategy). Verifies TP/EP/DP shardings produce the same results as
single-device execution."""

import jax
import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS
from sutro_tpu.parallel.mesh import make_mesh, mesh_shape
from sutro_tpu.parallel.sharding import param_shardings, shard_params


def _ecfg(**kw):
    base = dict(
        kv_page_size=8, max_pages_per_seq=8, decode_batch_size=4,
        max_model_len=64, use_pallas=False, param_dtype="float32",
    )
    base.update(kw)
    return EngineConfig(**base)


def test_mesh_construction(eight_devices):
    mesh = make_mesh(2, 2, 2, eight_devices)
    assert mesh_shape(mesh) == (2, 1, 1, 2, 2)
    with pytest.raises(ValueError, match="exceeds"):
        make_mesh(4, 4, 4, eight_devices)
    with pytest.raises(ValueError, match="exceeds"):
        make_mesh(2, 2, 2, eight_devices, sp=2)
    with pytest.raises(ValueError, match="exceeds"):
        make_mesh(2, 2, 2, eight_devices, pp=2)


def test_param_shardings_cover_all_leaves(eight_devices):
    from sutro_tpu.models import transformer

    mesh = make_mesh(1, 2, 4, eight_devices)
    for name in ("tiny-moe", "tiny-oss"):
        cfg = MODEL_CONFIGS[name]
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        sh = param_shardings(params, mesh)
        flat_p = jax.tree_util.tree_leaves(params)
        flat_s = jax.tree_util.tree_leaves(
            sh, is_leaf=lambda x: hasattr(x, "spec")
        )
        assert len(flat_p) == len(flat_s)


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
def test_tp_matches_single_device_generation(eight_devices):
    """Greedy generation must be identical under TP+EP sharding."""
    cfg = MODEL_CONFIGS["tiny-moe"]
    prompt = np.arange(11, dtype=np.int32) % 200

    def run(mesh):
        runner = ModelRunner(cfg, _ecfg(), mesh=mesh)
        table = np.zeros((8,), np.int32)
        table[:4] = [1, 2, 3, 4]
        logits = runner.prefill(prompt, table)
        tok = int(np.argmax(logits))
        out = [tok]
        pos = len(prompt)
        for _ in range(4):
            toks, _ = runner.decode_step(
                np.array([tok, 0, 0, 0], np.int32),
                np.array([pos, 0, 0, 0], np.int32),
                np.stack([table] + [np.zeros_like(table)] * 3),
                jax.random.PRNGKey(0),
                np.zeros(4, np.float32),
                np.ones(4, np.float32),
            )
            tok = int(toks[0])
            out.append(tok)
            pos += 1
        return out

    single = run(None)
    sharded = run(make_mesh(1, 2, 2, eight_devices[:4]))
    assert single == sharded


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
def test_dp_ep_tp_full_mesh_step(eight_devices):
    """A full 2x2x2 mesh executes a prefill+decode step without error and
    params actually land sharded."""
    cfg = MODEL_CONFIGS["tiny-moe"]
    mesh = make_mesh(2, 2, 2, eight_devices)
    runner = ModelRunner(cfg, _ecfg(), mesh=mesh)
    wq = runner.params["layers"]["wq"]
    assert len(wq.sharding.device_set) == 8
    table = np.zeros((8,), np.int32)
    table[:2] = [1, 2]
    logits = runner.prefill(np.arange(5, dtype=np.int32), table)
    assert logits.shape == (cfg.vocab_size,)
    assert np.isfinite(np.asarray(logits)).all()


def test_shard_params_helper(eight_devices):
    from sutro_tpu.models import transformer

    mesh = make_mesh(1, 1, 8, eight_devices)
    cfg = MODEL_CONFIGS["tiny-dense"]  # NHD=128 divides by 8
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    sharded = shard_params(params, mesh)
    assert len(sharded["layers"]["wq"].sharding.device_set) == 8
    # norms replicated
    assert sharded["layers"]["attn_norm"].sharding.is_fully_replicated


# ---------------------------------------------------------------------------
# Explicit expert parallelism (ops/moe_ep.py)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
@pytest.mark.parametrize("dp,ep,tp", [(2, 2, 2), (1, 4, 2), (1, 2, 1)])
@pytest.mark.parametrize("with_bias", [False, True])
def test_moe_ep_matches_reference(eight_devices, dp, ep, tp, with_bias):
    """The shard_map EP path (local grouped GEMMs + one psum) must
    reproduce the single-device MoE exactly — no token drops, biases
    and gpt-oss activation included."""
    import jax.numpy as jnp

    from sutro_tpu.ops.moe import moe_mlp
    from sutro_tpu.ops.moe_ep import moe_mlp_ep

    rng = np.random.default_rng(3)
    B, T, H, F, E, K = 2, 3, 16, 32, 4, 2
    act = "swiglu_oss" if with_bias else "silu"
    f32 = lambda *s: jnp.asarray(rng.standard_normal(s), jnp.float32)  # noqa: E731
    x = f32(B, T, H)
    router = f32(H, E)
    wg, wu = f32(E, H, F), f32(E, H, F)
    wd = f32(E, F, H)
    kw = dict(top_k=K, activation=act)
    if with_bias:
        kw.update(
            router_b=f32(E),
            bias_gate=f32(E, F) * 0.1,
            bias_up=f32(E, F) * 0.1,
            bias_down=f32(E, H) * 0.1,
        )

    want = moe_mlp(x, router, wg, wu, wd, method="dense", **kw)
    mesh = make_mesh(dp, ep, tp, eight_devices)
    got = jax.jit(
        lambda *a: moe_mlp_ep(*a, mesh=mesh, **kw)
    )(x, router, wg, wu, wd)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_moe_ep_odd_batch_replicates(eight_devices):
    """B not divisible by dp falls back to replicated tokens (still
    exact)."""
    import jax.numpy as jnp

    from sutro_tpu.ops.moe import moe_mlp
    from sutro_tpu.ops.moe_ep import moe_mlp_ep

    rng = np.random.default_rng(5)
    B, T, H, F, E, K = 3, 2, 8, 16, 4, 2
    f32 = lambda *s: jnp.asarray(rng.standard_normal(s), jnp.float32)  # noqa: E731
    x, router = f32(B, T, H), f32(H, E)
    wg, wu, wd = f32(E, H, F), f32(E, H, F), f32(E, F, H)
    mesh = make_mesh(2, 2, 2, eight_devices)
    want = moe_mlp(x, router, wg, wu, wd, top_k=K, method="dense")
    got = jax.jit(
        lambda *a: moe_mlp_ep(*a, mesh=mesh, top_k=K)
    )(x, router, wg, wu, wd)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_moe_ep_weight_residency(eight_devices):
    """With the sharding rules applied, each device holds exactly
    1/(ep*tp) of the expert weights — the reason this path exists
    (no GSPMD all-gather of expert weights)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(1, 4, 2, eight_devices)
    E, H, F = 8, 16, 64
    w = jnp.ones((E, H, F), jnp.float32)
    w = jax.device_put(
        w, NamedSharding(mesh, P("expert", None, "model"))
    )
    shard = w.addressable_shards[0].data
    assert shard.shape == (E // 4, H, F // 2)


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
@pytest.mark.parametrize("sp,pp", [(2, 1), (1, 2)])
def test_moe_ep_gspmd_fallback_under_sp_pp(eight_devices, sp, pp):
    """VERDICT r3 weak #6: under sp/pp the explicit shard_map EP path
    falls back to GSPMD MoE (runner.ep_mesh is None — shard_map nesting
    is unsupported). The fallback COMBINATION must still generate
    greedy tokens identical to single-device; its perf remains
    chip-gated (PARITY.md), but correctness is pinned here."""
    from sutro_tpu.ops.shard_compat import HAS_NEW_SHARD_MAP

    if pp > 1 and not HAS_NEW_SHARD_MAP:
        pytest.skip(
            "pp through the jitted runner needs partial-auto shard_map "
            "support (XLA:CPU rejects PartitionId on legacy jax)"
        )
    cfg = MODEL_CONFIGS["tiny-moe"]
    prompt = np.arange(11, dtype=np.int32) % 200

    def run(mesh):
        runner = ModelRunner(cfg, _ecfg(), mesh=mesh)
        if mesh is not None:
            assert runner.ep_mesh is None, (
                "explicit EP must sit out under sp/pp"
            )
        table = np.zeros((8,), np.int32)
        table[:4] = [1, 2, 3, 4]
        logits = runner.prefill(prompt, table)
        tok = int(np.argmax(logits))
        out = [tok]
        pos = len(prompt)
        for _ in range(3):
            toks, _ = runner.decode_step(
                np.array([tok, 0, 0, 0], np.int32),
                np.array([pos, 0, 0, 0], np.int32),
                np.stack([table] + [np.zeros_like(table)] * 3),
                jax.random.PRNGKey(0),
                np.zeros(4, np.float32),
                np.ones(4, np.float32),
            )
            tok = int(toks[0])
            out.append(tok)
            pos += 1
        return out

    single = run(None)
    sharded = run(
        make_mesh(1, 2, 2, eight_devices, sp=sp, pp=pp)
    )
    assert single == sharded
