"""Round-4 feature composition: shared-prefix KV caching + cross-job
co-batching + piggybacked chunked prefill + n-gram speculative decoding
+ int8 KV cache in ONE engine session. Each feature is pinned exact in
isolation by its own test file; this asserts the COMPOSITION:

- fp leg: with full-precision KV, the composed co-batched session must
  produce outputs bit-identical to solo runs with prefix cache,
  speculation, and piggyback all DISABLED — the three features are
  exactness-preserving and must stay so when stacked.
- int8 leg: with kv_quantize="int8" the comparison baseline must share
  the same KV READ PATTERN (same config, solo): chunked/prefix prefill
  re-reads earlier K/V from quantized pages where a whole-prompt
  prefill attends over exact in-flight K/V, so cross-pattern token
  equality is not a contract under quantization — co-batching, however,
  must still be a pure scheduling change (exact vs same-config solo).

Plus invariants: no leaked pages (incl. the shared prefix's) and the
prefix cache actually saving prefill tokens in both legs.
"""

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.engine.scheduler import (
    ContinuousBatcher,
    GenRequest,
    JobCtx,
)
from sutro_tpu.models.configs import MODEL_CONFIGS

PREFIX = (
    "system: classify the following review as positive or negative. "
    "review: "
)
A_SUFFIXES = [
    "great product works great",
    "terrible broke on day one",
    "great product came late but works",
    # long suffix: exceeds prefill_chunk=16 so its prefill rides the
    # chunked path, which the piggyback interleaves with live decode
    "the quality is ok but the packaging was damaged and the seller "
    "never answered my messages about a replacement unit",
    "love it love it love it",
    "not what the picture showed",
]
B_TEXTS = ["quick check a", "quick check b", "quick check c"]


def _ecfg(**kw):
    base = dict(
        kv_page_size=8,
        max_pages_per_seq=32,
        max_model_len=256,
        decode_batch_size=4,
        use_pallas=False,
        param_dtype="float32",
        activation_dtype="float32",
        spec_ngram_draft=6,
        decode_multi_step=4,
        decode_lookahead=2,
        prefill_chunk=16,
    )
    base.update(kw)
    return EngineConfig(**base)


def _reqs(tok, texts):
    return [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(tok.encode(t), np.int32),
            max_new_tokens=10,
            temperature=0.0,
        )
        for i, t in enumerate(texts)
    ]


def _solo(ecfg, tok, texts):
    b = ContinuousBatcher(
        ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg),
        stop_ids=tok.stop_ids(),
    )
    res = {}
    assert (
        b.run(
            _reqs(tok, texts),
            on_result=lambda r: res.__setitem__(r.row_id, r),
        )
        == "completed"
    )
    return {i: r.token_ids for i, r in res.items()}


def _cobatch(ecfg, tok):
    a_texts = [PREFIX + s for s in A_SUFFIXES]
    b = ContinuousBatcher(
        ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg),
        stop_ids=tok.stop_ids(),
    )
    free0 = b.free_page_count
    got_a, got_b, done = {}, {}, []
    state = b.run_multi(
        [
            JobCtx(
                job_id="A",
                pending=_reqs(tok, a_texts),
                on_result=lambda r: got_a.__setitem__(r.row_id, r),
                priority=1,
                seq=0,
            ),
            JobCtx(
                job_id="B",
                pending=_reqs(tok, B_TEXTS),
                on_result=lambda r: got_b.__setitem__(r.row_id, r),
                priority=0,
                seq=1,
            ),
        ],
        on_job_done=lambda c, o: done.append((c.job_id, o)),
    )
    assert state == "completed"
    assert dict(done) == {"A": "completed", "B": "completed"}
    assert b.free_page_count == free0, "leaked pages (incl. prefix)"
    # the shared prefix must have saved prefill work
    naive = sum(len(tok.encode(t)) for t in a_texts + B_TEXTS)
    assert b.prefill_tokens < naive, (b.prefill_tokens, naive)
    return (
        {i: r.token_ids for i, r in got_a.items()},
        {i: r.token_ids for i, r in got_b.items()},
    )


def test_composed_fp_exact_vs_plain(byte_tok):
    """fp leg: the full composition == solo with every
    exactness-preserving feature off."""
    tok = byte_tok
    a_texts = [PREFIX + s for s in A_SUFFIXES]
    on_a, on_b = _cobatch(_ecfg(), tok)
    plain = _ecfg(
        prefix_cache=False, spec_ngram_draft=0, prefill_chunk=512
    )
    assert on_a == _solo(plain, tok, a_texts)
    assert on_b == _solo(plain, tok, B_TEXTS)


@pytest.mark.slow  # second full composed-stack run differing from the
# fp leg only in kv_quantize; int8 KV exactness is pinned fast by
# test_kv_int8.py and the fp composition leg stays tier-1
def test_composed_int8_exact_vs_same_config_solo(byte_tok):
    """int8 leg: co-batching is a pure scheduling change — exact vs
    solo under the same composed config and KV read pattern."""
    tok = byte_tok
    a_texts = [PREFIX + s for s in A_SUFFIXES]
    ecfg = _ecfg(kv_quantize="int8")
    on_a, on_b = _cobatch(ecfg, tok)
    assert on_a == _solo(ecfg, tok, a_texts)
    assert on_b == _solo(ecfg, tok, B_TEXTS)
