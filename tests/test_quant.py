"""Int8 weight-only quantization (ops/quant.py): round-trip accuracy,
end-to-end generation, param-size reduction, and TP/EP-sharded execution
of quantized pytrees."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models import transformer
from sutro_tpu.models.configs import MODEL_CONFIGS
from sutro_tpu.ops import quant


def test_quantize_weight_roundtrip():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((4, 64, 32)), jnp.float32)
    q = quant.quantize_weight(w)
    assert q["qw"].dtype == jnp.int8
    assert q["scale"].shape == (4, 1, 32)
    deq = quant.materialize(q, jnp.float32)
    # per-channel int8: worst-case error is scale/2 per element
    max_scale = float(q["scale"].max())
    assert float(jnp.abs(deq - w).max()) <= max_scale * 0.5 + 1e-6


def test_quantize_params_selects_projections():
    cfg = MODEL_CONFIGS["tiny-moe"]
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    fp_bytes = quant.params_bytes(params)
    qparams = quant.quantize_params(params)
    assert quant.is_quantized(qparams["layers"]["wq"])
    assert quant.is_quantized(qparams["layers"]["we_gate"])
    assert not quant.is_quantized(qparams["layers"]["attn_norm"])
    assert not isinstance(qparams["embed"], dict)
    q_bytes = quant.params_bytes(qparams)
    assert q_bytes < 0.5 * fp_bytes  # f32 -> int8 on the projection bulk


def _ecfg(**kw):
    base = dict(
        kv_page_size=8, max_pages_per_seq=8, decode_batch_size=4,
        max_model_len=64, use_pallas=False, param_dtype="float32",
    )
    base.update(kw)
    return EngineConfig(**base)


@pytest.mark.parametrize("model", ["tiny-dense", "tiny-moe"])
def test_quantized_generation_tracks_fp(model):
    """Greedy generation with int8 weights must run end-to-end and stay
    close to the fp logits (same argmax on a random model is too strict;
    we check logit correlation instead)."""
    cfg = MODEL_CONFIGS[model]
    prompt = ((np.arange(13, dtype=np.int32) * 3) % 199).astype(np.int32)
    table = np.zeros((8,), np.int32)
    table[:4] = [1, 2, 3, 4]

    fp = ModelRunner(cfg, _ecfg())
    q = ModelRunner(cfg, _ecfg(quantize="int8"))
    lf = fp.prefill(prompt, table)
    lq = q.prefill(prompt, table)
    corr = np.corrcoef(lf, lq)[0, 1]
    assert corr > 0.99, corr
    # decode step executes with the quantized tree
    toks, _ = q.decode_step(
        np.array([int(np.argmax(lq)), 0, 0, 0], np.int32),
        np.array([len(prompt), 0, 0, 0], np.int32),
        np.stack([table] + [np.zeros_like(table)] * 3),
        jax.random.PRNGKey(0),
        np.zeros(4, np.float32), np.ones(4, np.float32),
    )
    assert 0 <= int(toks[0]) < cfg.vocab_size


def test_quantized_sharded_tp_ep(eight_devices):
    """Quantized pytrees shard under TP+EP: qw/scale inherit the weight's
    rule with size-1 scale dims unsharded."""
    from sutro_tpu.parallel.mesh import make_mesh

    cfg = MODEL_CONFIGS["tiny-moe"]
    mesh = make_mesh(1, 2, 2, eight_devices[:4])
    runner = ModelRunner(cfg, _ecfg(quantize="int8"), mesh=mesh)
    qw = runner.params["layers"]["wq"]["qw"]
    assert len(qw.sharding.device_set) == 4
    table = np.zeros((8,), np.int32)
    table[:2] = [1, 2]
    logits = runner.prefill(np.arange(5, dtype=np.int32), table)
    assert np.isfinite(logits).all()


def test_unknown_quantize_mode_rejected():
    with pytest.raises(ValueError, match="quantize"):
        ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg(quantize="fp4"))
