"""Constrained decoding: schema compiler, NFA semantics, token masks,
C++/Python parity."""

import json

import numpy as np
import pytest
from pydantic import BaseModel

from sutro_tpu.common import normalize_output_schema
from sutro_tpu.engine.constrain import (
    TokenTable,
    compile_schema,
    schema_constraint_factory,
)
from sutro_tpu.engine.constrain.fsm import MaskCache
from sutro_tpu.engine.tokenizer import ByteTokenizer


def accepts(nfa, text: str) -> bool:
    states = nfa.initial()
    for b in text.encode():
        states = nfa.step(states, b)
        if not states:
            return False
    return nfa.is_accepting(states)


@pytest.mark.parametrize(
    "schema,good,bad",
    [
        (
            {"type": "object", "properties": {"x": {"type": "integer"}},
             "required": ["x"]},
            ['{"x":0}', '{"x":-17}', '{"x":123456}'],
            ['{"x":01}', '{"x":1.5}', '{}', '{"x": 1}', '{"y":1}'],
        ),
        (
            {"type": "object", "properties": {"s": {"type": "string"}},
             "required": ["s"]},
            ['{"s":""}', '{"s":"hi"}', '{"s":"q\\"uote"}', '{"s":"\\u00e9"}'],
            ['{"s":5}', '{"s":"unterminated}', '{"s":"bad\\q"}'],
        ),
        (
            {"type": "object",
             "properties": {"t": {"type": "array", "items": {"type": "boolean"}}},
             "required": ["t"]},
            ['{"t":[]}', '{"t":[true]}', '{"t":[true,false,true]}'],
            ['{"t":[true,]}', '{"t":[1]}', '{"t":'],
        ),
        (
            {"type": "object",
             "properties": {
                 "a": {"type": "number"},
                 "b": {"enum": ["x", "y"]},
             },
             "required": ["b"]},
            ['{"a":1.5,"b":"x"}', '{"b":"y"}', '{"a":-2e3,"b":"x"}'],
            ['{"b":"z"}', '{"a":1.5}', '{"b":"x","a":1}'],  # fixed key order
        ),
    ],
)
def test_schema_acceptance(schema, good, bad):
    nfa = compile_schema(schema)
    for g in good:
        json.loads(g)  # sanity: must be valid JSON
        assert accepts(nfa, g), f"should accept {g}"
    for bstr in bad:
        assert not accepts(nfa, bstr), f"should reject {bstr}"


def test_pydantic_schema_with_enum_and_optional():
    from enum import Enum

    class Color(str, Enum):
        red = "red"
        blue = "blue"

    class M(BaseModel):
        color: Color
        note: str = "d"  # optional (has default => not required)

    nfa = compile_schema(normalize_output_schema(M))
    assert accepts(nfa, '{"color":"red","note":"hi"}')
    assert accepts(nfa, '{"color":"blue"}')
    assert not accepts(nfa, '{"color":"green"}')


def test_nested_object_and_anyof():
    schema = {
        "type": "object",
        "properties": {
            "sub": {
                "type": "object",
                "properties": {"x": {"type": "integer"}},
                "required": ["x"],
            },
            "opt": {"anyOf": [{"type": "integer"}, {"type": "null"}]},
        },
        "required": ["sub"],
    }
    nfa = compile_schema(schema)
    assert accepts(nfa, '{"sub":{"x":1}}')
    assert accepts(nfa, '{"sub":{"x":1},"opt":null}')
    assert accepts(nfa, '{"sub":{"x":1},"opt":42}')
    assert not accepts(nfa, '{"sub":{},"opt":null}')


def test_string_length_bounds():
    schema = {
        "type": "object",
        "properties": {"s": {"type": "string", "minLength": 2, "maxLength": 4}},
        "required": ["s"],
    }
    nfa = compile_schema(schema)
    assert not accepts(nfa, '{"s":"a"}')
    assert accepts(nfa, '{"s":"ab"}')
    assert accepts(nfa, '{"s":"abcd"}')
    assert not accepts(nfa, '{"s":"abcde"}')


def test_token_fsm_forces_valid_json():
    tok = ByteTokenizer()
    schema = {
        "type": "object",
        "properties": {"k": {"enum": ["a", "b"]}},
        "required": ["k"],
    }
    fac = schema_constraint_factory(schema, tok)
    fsm = fac()
    # walk by always taking the lexicographically-smallest allowed token
    out = bytearray()
    for _ in range(64):
        if fsm.is_complete():
            break
        mask = fsm.allowed_tokens()
        tid = int(np.argmax(mask))
        fsm.advance(tid)
        out += tok.token_bytes(tid)
        if fsm.is_complete():
            break
    parsed = json.loads(out.decode())
    assert parsed["k"] in ("a", "b")


def test_mask_allows_stop_only_at_accept():
    tok = ByteTokenizer()
    schema = {"type": "object", "properties": {"n": {"type": "integer"}},
              "required": ["n"]}
    fac = schema_constraint_factory(schema, tok)
    fsm = fac()
    assert not fsm.allowed_tokens()[tok.eos_id]
    for ch in b'{"n":7':
        fsm.advance(ch)
    # '7' could continue (more digits) or close; eos not yet allowed
    assert not fsm.allowed_tokens()[tok.eos_id]
    fsm.advance(ord("}"))
    assert fsm.is_complete()
    assert fsm.allowed_tokens()[tok.eos_id]


def _assert_cpp_py_parity(schema, text: str, expect_accept=False):
    """Walk ``text`` byte-wise asserting the C++ and Python maskers
    agree at every state (shared harness for every parity case)."""
    pytest.importorskip("ctypes")
    from sutro_tpu.engine.constrain.cpp import CppMasker

    tok = ByteTokenizer()
    nfa = compile_schema(schema)
    table = TokenTable(tok)
    try:
        cpp = CppMasker(nfa, table)
    except Exception:
        pytest.skip("native toolchain unavailable")
    py = MaskCache(nfa, table)
    py._cpp = None
    states = nfa.initial()
    for ch in text.encode():
        pm, pd = py._compute(states)
        cm, cd = cpp.mask(states)
        np.testing.assert_array_equal(pm, cm)
        np.testing.assert_array_equal(pd, cd)
        states = nfa.step(states, ch)
        assert states, chr(ch)
    if expect_accept:
        assert nfa.is_accepting(states)


def test_cpp_python_mask_parity():
    _assert_cpp_py_parity(
        {
            "type": "object",
            "properties": {
                "s": {"type": "string"},
                "v": {"type": "number"},
                "e": {"enum": ["aa", "ab", "b"]},
            },
            "required": ["s", "v", "e"],
        },
        '{"s":"x\\n","v":-1.5e2,"e":"ab"}',
    )


def test_budget_aware_closure_always_completes():
    """With a token budget too small for free-running string content, the
    FSM must steer to closing bytes so the emitted JSON is complete
    (verify-session regression: mid-string cuts at the length cap)."""
    import json

    from sutro_tpu.engine.constrain.fsm import schema_constraint_factory

    tok = ByteTokenizer()
    schema = {
        "type": "object",
        "properties": {"label": {"type": "string"}},
        "required": ["label"],
    }
    nested = {
        "type": "array",
        "items": {
            "type": "object",
            "properties": {"label": {"type": "string"}},
            "required": ["label"],
        },
    }
    rng = np.random.default_rng(0)
    for sch, check in (
        (schema, lambda o: "label" in o),
        (nested, lambda o: isinstance(o, list)),
    ):
        factory = schema_constraint_factory(sch, tok)
        for budget in (14, 20, 40):
            fsm = factory()
            out = bytearray()
            remaining = budget
            while remaining > 0 and not fsm.is_complete():
                mask = fsm.allowed_tokens(remaining=remaining)
                ids = np.nonzero(mask)[0]
                assert len(ids), "mask must never be empty"
                # adversarial: pick a random allowed token (worst-case model)
                tid = int(rng.choice(ids))
                fsm.advance(tid)
                out.extend(tok.token_bytes(tid))
                remaining -= 1
            obj = json.loads(out.decode("utf-8", errors="strict"))
            assert check(obj), (sch, budget, out)


def test_distance_to_accept():
    from sutro_tpu.engine.constrain.schema import compile_schema as cs

    nfa = cs({"enum": ["ab"]})  # JSON: "ab" -> 4 bytes: " a b "
    d0 = nfa.dist_to_accept(nfa.initial())
    assert d0 == 4


def test_schema_min_tokens_raises_generation_cap(tiny_ecfg, tmp_path, monkeypatch):
    """A max_new_tokens below the schema's shortest accepting output must
    not break the schema guarantee: the engine raises the row cap to the
    FSM's min_tokens so constrained rows still emit complete JSON."""
    import dataclasses
    import json
    import time

    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.interfaces import JobStatus

    ecfg = dataclasses.replace(
        tiny_ecfg, max_pages_per_seq=32, max_model_len=256
    )
    eng = LocalEngine(ecfg)
    jid = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": ["x"],
            "sampling_params": {"max_new_tokens": 4},  # << schema minimum
            "output_schema": {
                "type": "object",
                "properties": {
                    "label": {"type": "string", "enum": ["aa", "bb"]}
                },
                "required": ["label"],
            },
        }
    )
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if JobStatus(eng.job_status(jid)).is_terminal():
            break
        time.sleep(0.05)
    assert eng.job_status(jid) == "SUCCEEDED"
    out = eng.job_results(jid)["outputs"][0]
    parsed = json.loads(out)  # complete JSON despite the 4-token cap
    assert parsed["label"] in ("aa", "bb")


def test_speculative_constrained_matches_masked(tiny_ecfg, byte_tok):
    """Greedy schema-constrained generation must produce IDENTICAL
    outputs whether every step is masked (decode_multi_step=1) or fused
    speculative windows verify-and-commit (decode_multi_step=8): for
    greedy rows, the unmasked argmax is accepted only when it equals the
    masked argmax, and rejections fall back to one masked step."""
    import dataclasses
    import json

    from sutro_tpu.engine.constrain import schema_constraint_factory
    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
    from sutro_tpu.models.configs import MODEL_CONFIGS

    schema = {
        "type": "object",
        "properties": {
            "note": {"type": "string", "maxLength": 20},
            "label": {"type": "string", "enum": ["alpha", "beta"]},
        },
        "required": ["note", "label"],
    }

    def run(multi):
        ecfg = dataclasses.replace(
            tiny_ecfg, decode_multi_step=multi, max_pages_per_seq=32,
            max_model_len=256,
        )
        runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
        factory = schema_constraint_factory(schema, byte_tok)
        reqs = [
            GenRequest(
                row_id=i,
                prompt_ids=np.array(byte_tok.encode(t), np.int32),
                max_new_tokens=80,
                temperature=0.0,
                constraint=factory(),
            )
            for i, t in enumerate(["first row", "second", "third one"])
        ]
        b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
        res = {}
        b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
        return {
            i: (tuple(r.token_ids), r.finish_reason)
            for i, r in res.items()
        }

    masked = run(1)
    spec = run(8)
    assert masked == spec
    # and every output is complete, schema-valid JSON
    for toks, _reason in masked.values():
        parsed = json.loads(byte_tok.decode(list(toks)))
        assert parsed["label"] in ("alpha", "beta")


# ---------------------------------------------------------------------------
# Integer minimum/maximum (interval automaton) + string pattern (regex)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "lo,hi",
    [(0, 10), (1, 5), (7, 7), (-5, 5), (-30, -7), (17, 40163), (None, 12),
     (3, None), (None, -4), (-9, None), (0, None), (None, 0)],
)
def test_integer_bounds_exact(lo, hi):
    """The digit-interval automaton accepts exactly the integers in
    range — brute-force checked against int comparison."""
    schema = {"type": "integer"}
    if lo is not None:
        schema["minimum"] = lo
    if hi is not None:
        schema["maximum"] = hi
    nfa = compile_schema(schema)
    for v in list(range(-60, 61)) + [1234, -1234, 40162, 40163, 40164, 99999]:
        want = (lo is None or v >= lo) and (hi is None or v <= hi)
        assert accepts(nfa, str(v)) == want, (v, lo, hi)
    # canonical form only: no leading zeros / plus signs ever
    assert not accepts(nfa, "007")
    assert not accepts(nfa, "+3")


def test_integer_exclusive_bounds():
    nfa = compile_schema(
        {"type": "integer", "exclusiveMinimum": 2, "exclusiveMaximum": 6}
    )
    for v in range(-3, 10):
        assert accepts(nfa, str(v)) == (3 <= v <= 5), v


@pytest.mark.parametrize(
    "pattern,good,bad",
    [
        (r"^[a-z]+$", ["abc", "z"], ["", "Abc", "ab1"]),
        (r"^\d{3}-\d{4}$", ["555-0199"], ["5550199", "55-0199", "555-019"]),
        (r"^(yes|no)$", ["yes", "no"], ["maybe", "yesno", ""]),
        # unanchored (JSON Schema semantics): substring match
        (r"cat", ["cat", "concatenate", "cat!"], ["dog", "ca t"]),
        (r"^[A-Z][a-z]*( [A-Z][a-z]*)*$", ["Hello World", "A"], ["hello", "A  B"]),
        (r"^v\d+\.\d+\.\d+$", ["v1.20.3"], ["v1.2", "1.2.3"]),
        (r"^[^0-9]*$", ["abc", ""], ["a1"]),
        (r"^a{2,4}$", ["aa", "aaaa"], ["a", "aaaaa"]),
        # class escapes: known literals map, punctuation stays literal
        (r"^[a\-z]+$", ["a", "-", "z", "a-z"], ["b", "m"]),
        (r"^[\t]$", ["\t"], [" ", "t"]),
        # escaped range-high endpoint maps (\t-\n = 0x09-0x0A; wider
        # ranges through 0x0B fall back — \v has no JSON short escape)
        (r"^[\t-\n]$", ["\t", "\n"], [" ", "t", "n", "\r"]),
    ],
)
def test_string_pattern_enforced(pattern, good, bad):
    nfa = compile_schema(
        {
            "type": "object",
            "properties": {"s": {"type": "string", "pattern": pattern}},
            "required": ["s"],
        }
    )
    for s in good:
        assert accepts(nfa, json.dumps({"s": s}, separators=(",", ":"))), s
    for s in bad:
        assert not accepts(nfa, json.dumps({"s": s}, separators=(",", ":"))), s


def test_unsupported_pattern_falls_back_with_warning():
    """Exotic constructs keep the job alive: the string is type-checked
    but the pattern is not enforced (documented fallback)."""
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema(
            {"type": "string", "pattern": r"^(?=lookahead)x$"}
        )
        assert any("not enforced" in str(x.message) for x in w)
    assert accepts(nfa, '"anything"')


@pytest.mark.parametrize(
    "pattern",
    [
        r"^[\x41]$",        # hex escape in class (would wrongly match "x"/"4"/"1")
        r"^[\x20-\x7E]+$",  # printable-ASCII idiom — hex range
        r"^[a-\x]$",        # exotic escape as range-high endpoint
        "^[\\u0041]$",      # unicode escape in class
        r"^[\1]$",          # backref-looking digit escape in class
    ],
)
def test_class_escape_exotic_falls_back(pattern):
    """Unrecognized escapes inside character classes must raise
    UnsupportedPattern (not silently degrade to the escape letter's
    literal — advisor round-2 medium), which routes the whole pattern
    into the documented warn-and-fallback path."""
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema({"type": "string", "pattern": pattern})
        assert any("not enforced" in str(x.message) for x in w), pattern
    # fallback accepts any string — crucially "x" is no longer wrongly
    # privileged over "A" by a mis-compiled class
    assert accepts(nfa, '"A"')
    assert accepts(nfa, '"x"')


def test_pattern_masks_drive_valid_generation():
    """End-to-end with the token FSM: masked sampling over a byte
    vocabulary can only produce strings matching the pattern."""
    schema = {
        "type": "object",
        "properties": {"id": {"type": "string", "pattern": r"^[A-Z]{2}\d{2}$"}},
        "required": ["id"],
    }
    tok = ByteTokenizer()
    factory = schema_constraint_factory(schema, tok)
    fsm = factory()
    rng = np.random.default_rng(0)
    out = bytearray()
    for _ in range(64):
        if fsm.is_complete():
            break
        ids = np.flatnonzero(fsm.allowed_tokens())
        assert len(ids), "dead state"
        t = int(rng.choice(ids))
        fsm.advance(t)
        out += tok.token_bytes(t)
    obj = json.loads(out.decode())
    import re

    assert re.fullmatch(r"[A-Z]{2}\d{2}", obj["id"])


def test_integer_bounds_edge_semantics():
    """Fractional bounds round inward; draft-4 boolean and draft-2020
    numeric exclusive forms intersect with minimum/maximum."""
    # fractional: minimum 2.5 -> 3 is the smallest valid integer
    nfa = compile_schema({"type": "integer", "minimum": 2.5})
    assert not accepts(nfa, "2") and accepts(nfa, "3")
    nfa = compile_schema({"type": "integer", "maximum": -0.5})
    assert not accepts(nfa, "0") and accepts(nfa, "-1")
    # draft-2020: both keywords apply independently
    nfa = compile_schema(
        {"type": "integer", "minimum": 10, "exclusiveMinimum": 2}
    )
    assert not accepts(nfa, "3") and not accepts(nfa, "9")
    assert accepts(nfa, "10")
    # draft-4 boolean form
    nfa = compile_schema(
        {"type": "integer", "minimum": 10, "exclusiveMinimum": True,
         "maximum": 12}
    )
    assert not accepts(nfa, "10") and accepts(nfa, "11")
    # exclusiveMinimum -2.5: v > -2.5 -> -2 is valid
    nfa = compile_schema({"type": "integer", "exclusiveMinimum": -2.5})
    assert accepts(nfa, "-2") and not accepts(nfa, "-3")


def test_malformed_and_oversized_patterns_fall_back():
    """Malformed braces and unbounded repetition caps degrade to the
    unconstrained string (warning), never crash or blow up memory."""
    import warnings

    for pat in ["a{b}", "x{}", "a{2,x}", "^a{200000,}$", "a{-1}"]:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            nfa = compile_schema({"type": "string", "pattern": pat})
            assert any("not enforced" in str(x.message) for x in w), pat
        assert accepts(nfa, '"whatever"'), pat


@pytest.mark.parametrize(
    "fmt,good,bad",
    [
        ("uuid", ["123e4567-e89b-12d3-a456-426614174000"],
         ["123e4567e89b12d3a456426614174000", "123E4567-e89b-12d3-a456-426614174000", "xyz"]),
        ("date", ["2026-07-30", "1999-12-01"],
         ["2026-13-01", "2026-00-10", "2026-01-32", "26-07-30"]),
        ("date-time", ["2026-07-30T23:59:59Z", "2026-07-30T00:00:00+05:30",
                       "2026-07-30T12:00:00.123"],
         ["2026-07-30 12:00:00", "2026-07-30T24:00:00Z"]),
        ("time", ["23:59:59", "00:00:00Z", "12:30:45.5+05:30"],
         ["24:00:00", "12:60:00", "1:00:00", "12:00"]),
        ("email", ["a@b.co", "first.last+tag@example.org"],
         ["no-at-sign", "@x.com", "a@b", "a@b."]),
        ("ipv4", ["0.0.0.0", "255.255.255.255", "192.168.1.7"],
         ["256.1.1.1", "1.2.3", "01.2.3.4", "1.2.3.4.5"]),
    ],
)
def test_string_format_enforced(fmt, good, bad):
    nfa = compile_schema({"type": "string", "format": fmt})
    for s in good:
        assert accepts(nfa, json.dumps(s)), (fmt, s)
    for s in bad:
        assert not accepts(nfa, json.dumps(s)), (fmt, s)


def test_unknown_format_is_annotation_only():
    nfa = compile_schema({"type": "string", "format": "hostname"})
    assert accepts(nfa, '"anything at all"')


def test_format_with_length_bounds_defers_to_lengths():
    """minLength/maxLength are validator-enforced; format is annotation.
    When both appear the length bounds win, so generated values never
    fail the user's own validation."""
    nfa = compile_schema(
        {"type": "string", "format": "uuid", "maxLength": 10}
    )
    assert accepts(nfa, '"short"')          # within maxLength
    assert not accepts(nfa, '"12345678901"')  # 11 chars > maxLength


def test_unsupported_pattern_falls_back_to_format():
    """A pattern outside the regex subset degrades to the format grammar
    (closer than an unconstrained string) when one is available."""
    import warnings

    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        nfa = compile_schema(
            {"type": "string", "pattern": r"(?=x)a", "format": "ipv4"}
        )
    assert accepts(nfa, '"10.0.0.1"')
    assert not accepts(nfa, '"not an ip"')


@pytest.mark.parametrize(
    "lo,hi",
    [("0", "10"), ("1.5", "3.5"), ("0.25", "0.75"), ("2", "2"),
     ("-5.5", "5.5"), ("-30.2", "-7.85"), ("17", "40163.125"),
     (None, "12.5"), ("3.25", None), (None, "-4.5"), ("-0.5", None)],
)
def test_number_bounds_exact(lo, hi):
    """The decimal interval automaton accepts exactly the in-range
    plain decimals — brute-force checked against Decimal comparison."""
    import decimal

    schema = {"type": "number"}
    if lo is not None:
        schema["minimum"] = float(lo)
    if hi is not None:
        schema["maximum"] = float(hi)
    nfa = compile_schema(schema)
    dlo = None if lo is None else decimal.Decimal(lo)
    dhi = None if hi is None else decimal.Decimal(hi)

    cands = set()
    for base in [-31, -30.2, -8, -7.85, -7.8, -5.5, -4.5, -4.49, -1,
                 -0.75, -0.5, -0.25, 0, 0.24, 0.25, 0.5, 0.75, 0.76,
                 1, 1.4, 1.5, 2, 2.5, 3.5, 3.51, 5.5, 9, 10, 10.5, 12.5,
                 12.51, 17, 40163, 40163.125, 40163.13, 99999]:
        cands.add(str(decimal.Decimal(str(base))))
    for s in sorted(cands):
        v = decimal.Decimal(s)
        want = (dlo is None or v >= dlo) and (dhi is None or v <= dhi)
        assert accepts(nfa, s) == want, (s, lo, hi)
    # canonical form only
    assert not accepts(nfa, "01.5")
    assert not accepts(nfa, "1.")
    assert not accepts(nfa, "+2")
    # exponent form: accepted ONLY inside the safe box, so acceptance
    # implies the value is in range (safety direction of the subset)
    import itertools

    for m, e in itertools.product(["1", "2", "9.5"], range(-3, 4)):
        v = decimal.Decimal(m) * decimal.Decimal(10) ** e
        ok = (dlo is None or v >= dlo) and (dhi is None or v <= dhi)
        if accepts(nfa, f"{m}e{e}"):
            assert ok, (m, e, lo, hi)
    # trailing zeros are fine when the value is in range
    mid = dlo if dlo is not None else dhi
    if mid is not None:
        s = str(mid)
        if "." in s:
            assert accepts(nfa, s + "0") == (
                (dlo is None or mid >= dlo) and (dhi is None or mid <= dhi)
            )


def test_number_exclusive_bounds_are_subset():
    """Exclusive real bounds: the compiled language must EXCLUDE the
    boundary and stay within the open interval."""
    nfa = compile_schema(
        {"type": "number", "exclusiveMinimum": 1.5, "exclusiveMaximum": 4}
    )
    assert not accepts(nfa, "1.5")
    assert not accepts(nfa, "4")
    assert accepts(nfa, "2")
    assert accepts(nfa, "3.999")
    assert not accepts(nfa, "1.4")
    assert not accepts(nfa, "4.1")


def test_number_exclusive_bounds_arbitrary_depth():
    """Strict real bounds admit values arbitrarily close to the
    boundary but never the boundary itself (at any trailing-zero
    depth)."""
    nfa = compile_schema(
        {"type": "number", "exclusiveMinimum": 1.5, "exclusiveMaximum": 4}
    )
    for good in ["1.500001", "1.51", "3.9999999", "2", "3.5"]:
        assert accepts(nfa, good), good
    for bad in ["1.5", "1.50", "1.5000", "4", "4.0", "4.000", "1.49",
                "4.0001"]:
        assert not accepts(nfa, bad), bad


def test_number_negative_strict_zero():
    """maximum 0 strict => only negative values; "-0" variants equal
    zero and must be rejected."""
    nfa = compile_schema({"type": "number", "exclusiveMaximum": 0})
    for good in ["-0.001", "-1", "-99.5"]:
        assert accepts(nfa, good), good
    for bad in ["0", "0.0", "-0", "-0.0", "-0.000", "0.001"]:
        assert not accepts(nfa, bad), bad


def test_number_exponent_form_safe_box():
    """Bounded numbers admit canonical scientific form inside the
    exponent "safe box" (every mantissa in-range), so wide bounds don't
    force 300-digit positional output; boundary-adjacent decades stay
    positional-only (VERDICT r3 missing #7)."""
    # [5, 500]: safe exponents are exactly E=1 (10^1 >= 5, 10^2 <= 500)
    nfa = compile_schema({"type": "number", "minimum": 5, "maximum": 500})
    for good in ["1e1", "5e1", "9.99e1"]:
        assert accepts(nfa, good), good
    # in-bounds but outside the box (some mantissa at E=2 would exceed
    # 500) — positional still covers these values
    assert not accepts(nfa, "1e2")
    assert accepts(nfa, "100")
    for bad in ["1e0", "1e3", "4.9e0"]:  # out of bounds entirely
        assert not accepts(nfa, bad), bad

    # wide upper bound: exponent form reaches the top decades
    nfa = compile_schema({"type": "number", "minimum": 0, "maximum": 1e30})
    for good in ["1e5", "9.9e29", "2.5e10"]:
        assert accepts(nfa, good), good
    assert not accepts(nfa, "1e30")  # boundary decade: positional only
    assert accepts(nfa, "1" + "0" * 30)
    assert not accepts(nfa, "2e30")

    # negative side mirrors on magnitudes
    nfa = compile_schema(
        {"type": "number", "minimum": -1000, "maximum": -10}
    )
    for good in ["-1e1", "-9.9e2", "-2e2"]:
        assert accepts(nfa, good), good
    for bad in ["1e1", "-1e0", "-1e3", "-2e3"]:
        assert not accepts(nfa, bad), bad

    # strict bound at a power of ten excludes that exponent's floor
    nfa = compile_schema({"type": "number", "exclusiveMinimum": 100})
    assert accepts(nfa, "1e3")
    assert not accepts(nfa, "1e2")  # == 100 at m=1: excluded
    assert accepts(nfa, "100.5")

    # unbounded-above side: any exponent >= the safe floor
    nfa = compile_schema({"type": "number", "minimum": 10})
    for good in ["1e1", "3e25", "1e100"]:
        assert accepts(nfa, good), good
    assert not accepts(nfa, "1e0")


def test_number_bounds_edge_cases():
    """Negative-zero bounds compile (sign-strip regression) and
    astronomically wide bounds stay cheap (O(width) construction)."""
    import time

    nfa = compile_schema({"type": "number", "minimum": -0.0})
    assert accepts(nfa, "0") and accepts(nfa, "7.5")
    assert not accepts(nfa, "-1")

    t0 = time.monotonic()
    nfa = compile_schema({"type": "number", "minimum": 0,
                          "maximum": 1.7e308})
    dt = time.monotonic() - t0
    assert dt < 1.0, f"wide-bound compile took {dt:.2f}s"
    assert accepts(nfa, "12345.678")
    assert accepts(nfa, "9" * 300)
    assert not accepts(nfa, "-1")


@pytest.mark.parametrize(
    "schema,k,lo,hi",
    [
        ({"type": "integer", "multipleOf": 7}, 7, None, None),
        ({"type": "integer", "multipleOf": 5, "minimum": 3,
          "maximum": 100}, 5, 3, 100),
        ({"type": "integer", "multipleOf": 12, "minimum": -40,
          "maximum": 40}, 12, -40, 40),
        ({"type": "integer", "multipleOf": 9, "minimum": 17}, 9, 17, None),
        ({"type": "integer", "multipleOf": 4, "maximum": -6}, 4, None, -6),
    ],
)
def test_integer_multiple_of(schema, k, lo, hi):
    """multipleOf composes exactly with bounds via the remainder-
    tracking product automaton."""
    nfa = compile_schema(schema)
    for v in list(range(-130, 131)) + [252, 999, 1008, -1008]:
        want = (
            v % k == 0
            and (lo is None or v >= lo)
            and (hi is None or v <= hi)
        )
        assert accepts(nfa, str(v)) == want, (v, schema)
    assert not accepts(nfa, "014")


def test_multiple_of_empty_range_raises():
    with pytest.raises(ValueError, match="no multiple"):
        compile_schema(
            {"type": "integer", "multipleOf": 50, "minimum": 3,
             "maximum": 40}
        )


def test_fractional_multiple_of_warns_and_ignores():
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema({"type": "integer", "multipleOf": 0.5})
        assert any("not enforced" in str(x.message) for x in w)
    assert accepts(nfa, "3")


def test_unique_items_enum_array():
    """uniqueItems + small enum items: repeats are impossible by
    construction; size bounds respected."""
    schema = {
        "type": "array",
        "items": {"enum": ["a", "b", "c"]},
        "uniqueItems": True,
        "minItems": 1,
        "maxItems": 2,
    }
    nfa = compile_schema(schema)
    enc = lambda a: json.dumps(a, separators=(",", ":"))  # noqa: E731
    for good in [["a"], ["c"], ["a", "b"], ["c", "a"]]:
        assert accepts(nfa, enc(good)), good
    for bad in [[], ["a", "a"], ["a", "b", "c"], ["d"], ["a", "d"]]:
        assert not accepts(nfa, enc(bad)), bad


def test_unique_items_large_pool_warns():
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema(
            {
                "type": "array",
                "items": {"enum": list("abcdefgh")},
                "uniqueItems": True,
            }
        )
        assert any("uniqueItems" in str(x.message) for x in w)
    assert accepts(nfa, '["a","a"]')  # unchecked fallback


def test_unique_items_dedupes_enum_values():
    """Positional duplicates in the enum pool must not defeat the
    uniqueness guarantee."""
    nfa = compile_schema(
        {"type": "array", "items": {"enum": ["a", "a", "b"]},
         "uniqueItems": True, "minItems": 1}
    )
    assert accepts(nfa, '["a","b"]')
    assert not accepts(nfa, '["a","a"]')


# ---------------------------------------------------------------------------
# allOf intersection merge + additionalProperties
# ---------------------------------------------------------------------------


def test_allof_integer_bounds_brute_force():
    """Conjoined bounds + multipleOf from separate branches accept
    exactly their intersection — checked against int comparison."""
    nfa = compile_schema(
        {
            "allOf": [
                {"type": "integer", "minimum": -4},
                {"maximum": 10},
                {"multipleOf": 2},
            ]
        }
    )
    for v in range(-30, 31):
        want = -4 <= v <= 10 and v % 2 == 0
        assert accepts(nfa, str(v)) == want, v


def test_allof_merges_object_branches():
    """Properties and required sets union across branches; per-property
    schemas intersect recursively; emission keeps first-seen key order."""
    nfa = compile_schema(
        {
            "allOf": [
                {
                    "type": "object",
                    "properties": {"a": {"type": "integer", "minimum": 0}},
                    "required": ["a"],
                },
                {
                    "type": "object",
                    "properties": {
                        "a": {"maximum": 5},
                        "b": {"enum": ["x", "y"]},
                    },
                    "required": ["b"],
                },
            ]
        }
    )
    assert accepts(nfa, '{"a":3,"b":"x"}')
    assert accepts(nfa, '{"a":0,"b":"y"}')
    assert not accepts(nfa, '{"a":6,"b":"x"}')   # a > merged maximum
    assert not accepts(nfa, '{"a":-1,"b":"x"}')  # a < minimum
    assert not accepts(nfa, '{"a":3}')           # b required via union
    assert not accepts(nfa, '{"b":"x","a":3}')   # canonical key order


def test_allof_enum_intersection_and_lcm():
    nfa = compile_schema(
        {"allOf": [{"enum": [1, 2, 3, "x"]}, {"enum": [2, "x", 9]}]}
    )
    for text, want in [("2", True), ('"x"', True), ("1", False),
                       ("3", False), ("9", False)]:
        assert accepts(nfa, text) == want, text
    nfa = compile_schema(
        {"type": "integer", "allOf": [{"multipleOf": 4}, {"multipleOf": 6}],
         "minimum": 0, "maximum": 60}
    )
    for v in range(0, 61):
        assert accepts(nfa, str(v)) == (v % 12 == 0), v


def test_allof_type_intersection_number_integer():
    nfa = compile_schema(
        {"allOf": [{"type": "number"}, {"type": "integer"}]}
    )
    assert accepts(nfa, "7")
    assert not accepts(nfa, "7.5")


def test_allof_anyof_distribution():
    """allOf(anyOf(A,B), C) == anyOf(allOf(A,C), allOf(B,C)) — exact."""
    nfa = compile_schema(
        {
            "allOf": [
                {"anyOf": [{"minimum": 0}, {"maximum": -10}]},
                {"type": "integer", "maximum": 5},
            ]
        }
    )
    for v in range(-30, 31):
        want = (0 <= v <= 5) or (v <= -10)
        assert accepts(nfa, str(v)) == want, v


def test_allof_string_length_conjunction():
    nfa = compile_schema(
        {
            "allOf": [
                {"type": "string", "minLength": 2},
                {"maxLength": 4},
            ]
        }
    )
    for s, want in [("a", False), ("ab", True), ("abcd", True),
                    ("abcde", False)]:
        assert accepts(nfa, json.dumps(s)) == want, s


def test_pattern_length_bounds():
    """The bounds analyzer runs the real pattern compiler against a
    counting builder — spot-check it against known languages."""
    from sutro_tpu.engine.constrain.regex import (
        UnsupportedPattern,
        pattern_length_bounds,
    )

    assert pattern_length_bounds("^abc$") == (3, 3)
    assert pattern_length_bounds("^[a-z]{2,5}$") == (2, 5)
    assert pattern_length_bounds("^a+$") == (1, None)
    assert pattern_length_bounds("^a?(bc|defg)$") == (2, 5)
    assert pattern_length_bounds(r'^\d{4}-\d{2}$') == (7, 7)
    # unanchored ends wrap with star(string_char): unbounded above
    assert pattern_length_bounds("abc") == (3, None)
    assert pattern_length_bounds("^ab") == (2, None)
    with pytest.raises(UnsupportedPattern):
        pattern_length_bounds("^a(?=b)$")  # lookahead: outside subset


def test_allof_pattern_with_provable_length_bounds():
    """pattern + length bounds from different conjuncts: bounds the
    pattern provably satisfies are dropped as redundant; the pattern
    compiles and its language is emitted."""
    nfa = compile_schema(
        {
            "allOf": [
                {"type": "string", "pattern": "^[a-z]{3}$"},
                {"minLength": 2, "maxLength": 5},
            ]
        }
    )
    assert accepts(nfa, json.dumps("abc"))
    assert not accepts(nfa, json.dumps("ab"))
    assert not accepts(nfa, json.dumps("abcd"))


def test_allof_pattern_vs_length_bounds_hard_fails():
    """A pattern that cannot be proven to satisfy a length conjunct
    hard-fails (the merge's no-silent-widening contract) instead of
    letting compile_node drop the bounds."""
    with pytest.raises(ValueError, match="pattern"):
        compile_schema(
            {
                "allOf": [
                    {"type": "string", "pattern": "^a+$"},
                    {"maxLength": 4},
                ]
            }
        )


def test_allof_pattern_bounds_skipped_under_enum():
    """A merged enum/const makes the pattern-vs-length check moot:
    compile_node prefers the enum and the merge filters members against
    pattern AND bounds exactly — the schema must still compile."""
    nfa = compile_schema(
        {
            "allOf": [
                {"enum": ["aa", "aaaaaa"]},
                {"type": "string", "pattern": "^a+$"},
                {"maxLength": 4},
            ]
        }
    )
    assert accepts(nfa, json.dumps("aa"))
    assert not accepts(nfa, json.dumps("aaaaaa"))  # violates maxLength
    assert not accepts(nfa, json.dumps("bb"))


def test_allof_unsupported_pattern_keeps_length_bounds():
    """A pattern outside the regex subset inside allOf must NOT
    hard-fail against length conjuncts: compile_node's fallback
    enforces the bounds and warns the pattern is unenforced — exactly
    the non-allOf behavior, with no widening."""
    import warnings as _w

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        nfa = compile_schema(
            {
                "allOf": [
                    {"type": "string", "pattern": "^a(?=b)$"},
                    {"maxLength": 4},
                ]
            }
        )
    assert any("not enforced" in str(r.message) for r in rec)
    assert accepts(nfa, json.dumps("abcd"))
    assert not accepts(nfa, json.dumps("abcde"))  # bounds enforced


def test_direct_pattern_with_unprovable_bounds_warns():
    """Directly-authored pattern + bounds keeps the documented
    pattern-wins precedence but now warns when the bounds are not
    provably satisfied (they were silently dropped before)."""
    import warnings as _w

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        nfa = compile_schema(
            {"type": "string", "pattern": "^a+$", "maxLength": 4}
        )
    assert any("precedence" in str(r.message) for r in rec)
    assert accepts(nfa, json.dumps("aaaaaa"))  # pattern wins

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        compile_schema(
            {"type": "string", "pattern": "^a{1,3}$", "maxLength": 4}
        )
    assert not any("precedence" in str(r.message) for r in rec)


@pytest.mark.parametrize(
    "schema,msg",
    [
        ({"allOf": [{"type": "string"}, {"type": "integer"}]}, "type"),
        ({"allOf": [{"enum": [1]}, {"enum": [2]}]}, "enum"),
        ({"allOf": [{"const": 1}, {"const": 2}]}, "const"),
        (
            {"allOf": [{"type": "string", "pattern": "^a+$"},
                       {"pattern": "^b+$"}]},
            "pattern",
        ),
        (
            {"allOf": [{"oneOf": [{"type": "integer"}]},
                       {"minimum": 3}]},
            "oneOf",
        ),
        (
            {"allOf": [{"multipleOf": 2}, {"multipleOf": 0.5}]},
            "multipleOf",
        ),
    ],
)
def test_allof_unsupported_intersections_hard_fail(schema, msg):
    """Inexpressible conjunctions raise with a clear message instead of
    silently widening the language (subset discipline)."""
    with pytest.raises(ValueError, match=msg):
        compile_schema(schema)


def test_allof_pydantic_ref_with_siblings_still_works():
    """Pydantic's single-element allOf around a $ref plus annotation
    siblings (the pre-existing fast path) keeps working."""
    from enum import Enum

    from pydantic import Field

    class Color(str, Enum):
        red = "red"
        blue = "blue"

    class M(BaseModel):
        color: Color = Field(description="paint")

    nfa = compile_schema(normalize_output_schema(M))
    assert accepts(nfa, '{"color":"red"}')
    assert not accepts(nfa, '{"color":"green"}')


def test_additional_properties_false_closed_by_construction():
    """Declared-property objects never emit extra keys, so
    additionalProperties: false holds structurally."""
    nfa = compile_schema(
        {
            "type": "object",
            "properties": {"a": {"type": "integer"}},
            "required": ["a"],
            "additionalProperties": False,
        }
    )
    assert accepts(nfa, '{"a":1}')
    assert not accepts(nfa, '{"a":1,"z":2}')
    assert not accepts(nfa, '{"z":2,"a":1}')


def test_freeform_map_additional_properties_schema():
    """Property-less object with a value schema (Pydantic Dict[str, T])
    compiles to a free-form map instead of the empty object."""
    nfa = compile_schema(
        {"type": "object", "additionalProperties": {"type": "integer"}}
    )
    assert accepts(nfa, "{}")
    assert accepts(nfa, '{"k":1}')
    assert accepts(nfa, '{"k":1,"other":-2}')
    assert not accepts(nfa, '{"k":"s"}')
    assert not accepts(nfa, '{"k":1,}')
    bounded = compile_schema(
        {
            "type": "object",
            "additionalProperties": {"type": "boolean"},
            "minProperties": 1,
            "maxProperties": 2,
        }
    )
    assert not accepts(bounded, "{}")
    assert accepts(bounded, '{"k":true}')
    assert accepts(bounded, '{"k":true,"j":false}')
    assert not accepts(bounded, '{"a":true,"b":false,"c":true}')


def test_freeform_map_generation_completes():
    """Token-FSM drive over a byte vocabulary: masked sampling on a
    free-form map terminates with parseable, schema-valid JSON."""
    schema = {
        "type": "object",
        "additionalProperties": {"type": "integer"},
        "maxProperties": 2,
    }
    tok = ByteTokenizer()
    fsm = schema_constraint_factory(schema, tok)()
    rng = np.random.default_rng(3)
    out = bytearray()
    for _ in range(80):
        if fsm.is_complete():
            break
        ids = np.flatnonzero(fsm.allowed_tokens(remaining=80 - len(out)))
        assert len(ids), "dead state"
        t = int(rng.choice(ids))
        fsm.advance(t)
        out += tok.token_bytes(t)
    obj = json.loads(out.decode())
    assert all(isinstance(v, int) for v in obj.values())


def test_freeform_map_max_properties_above_16_enforced():
    """maxProperties is exact at any size (no silent star fallback)."""
    nfa = compile_schema(
        {"type": "object", "additionalProperties": {"type": "integer"},
         "maxProperties": 17}
    )
    ok = "{" + ",".join(f'"k{i}":1' for i in range(17)) + "}"
    too_many = "{" + ",".join(f'"k{i}":1' for i in range(18)) + "}"
    assert accepts(nfa, ok)
    assert not accepts(nfa, too_many)
    with pytest.raises(ValueError, match="minProperties"):
        compile_schema(
            {"type": "object", "additionalProperties": {},
             "minProperties": 20, "maxProperties": 18}
        )


def test_allof_enum_const_filtered_by_conjunct_bounds():
    """enum/const members violating a sibling conjunct's bounds are
    dropped (or the schema hard-fails as unsatisfiable) — the merge must
    never widen past the user's own validation."""
    nfa = compile_schema({"allOf": [{"enum": [1, 20]}, {"minimum": 10}]})
    assert accepts(nfa, "20")
    assert not accepts(nfa, "1")
    with pytest.raises(ValueError, match="const"):
        compile_schema({"allOf": [{"const": 5}, {"minimum": 10}]})
    nfa = compile_schema(
        {"allOf": [{"enum": ["a", "bb", "ccc"]},
                   {"type": "string", "minLength": 2, "maxLength": 2}]}
    )
    assert accepts(nfa, '"bb"')
    assert not accepts(nfa, '"a"')
    assert not accepts(nfa, '"ccc"')
    nfa = compile_schema(
        {"allOf": [{"enum": ["ab", "zz", 3]},
                   {"type": "string", "pattern": "^a"}]}
    )
    assert accepts(nfa, '"ab"')
    assert not accepts(nfa, '"zz"')
    assert not accepts(nfa, "3")  # type-filtered too


def test_allof_preserves_implicit_all_required():
    """A branch without an explicit required list keeps the compiler's
    all-properties-required default through the merge."""
    nfa = compile_schema(
        {
            "allOf": [
                {"type": "object", "properties": {"a": {"type": "integer"}}},
                {"type": "object",
                 "properties": {"b": {"type": "string"}},
                 "required": ["b"]},
            ]
        }
    )
    assert accepts(nfa, '{"a":1,"b":"x"}')
    assert not accepts(nfa, '{"b":"x"}')  # a implicitly required
    assert not accepts(nfa, '{"a":1}')


def test_allof_lone_oneof_with_annotation_siblings():
    """Annotation-only siblings (description etc.) must not make a lone
    oneOf conjunct 'inexpressible'."""
    nfa = compile_schema(
        {"allOf": [{"oneOf": [{"type": "integer"}]}],
         "description": "annotated"}
    )
    assert accepts(nfa, "7")


def test_allof_nested_anyof_does_not_leak():
    """A single-branch (or nested) anyOf conjunct must still intersect
    with its siblings instead of leaving an 'anyOf' key that makes
    compile_node drop them."""
    nfa = compile_schema(
        {
            "allOf": [
                {"anyOf": [{"anyOf": [{"type": "integer"},
                                      {"type": "string"}]}]},
                {"minimum": 3},
            ]
        }
    )
    assert accepts(nfa, "5")
    assert not accepts(nfa, "1")   # minimum survives the distribution
    assert accepts(nfa, '"ok"')    # string branch unaffected by minimum


def test_allof_composite_enum_filtered():
    """Array/object enum members are validated against conjunct
    composite constraints (recursively), not just scalar ones."""
    nfa = compile_schema(
        {"allOf": [{"enum": [[1], [1, 2, 3]]},
                   {"type": "array", "maxItems": 2}]}
    )
    assert accepts(nfa, "[1]")
    assert not accepts(nfa, "[1,2,3]")
    nfa = compile_schema(
        {"allOf": [{"enum": [{"a": 1}, {"a": 99}]},
                   {"type": "object",
                    "properties": {"a": {"maximum": 10}}}]}
    )
    assert accepts(nfa, '{"a":1}')
    assert not accepts(nfa, '{"a":99}')


def test_class_escaped_underscore_still_literal():
    """[\\_] — underscore is the one word-set member ECMA keeps a
    literal escape; must not fall back."""
    nfa = compile_schema(
        {"type": "object",
         "properties": {"s": {"type": "string", "pattern": r"^[\_a]+$"}},
         "required": ["s"]}
    )
    assert accepts(nfa, '{"s":"_a_"}')
    assert not accepts(nfa, '{"s":"b"}')


def test_allof_prunes_unsatisfiable_anyof_branches():
    """Optional-narrowing: allOf(anyOf(int, null), int&minimum) must
    keep the satisfiable branch, not fail the compile."""
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema(
            {"allOf": [{"anyOf": [{"type": "integer"}, {"type": "null"}]},
                       {"type": "integer", "minimum": 0}]}
        )
        assert any("pruned" in str(x.message) for x in w)
    assert accepts(nfa, "3")
    assert not accepts(nfa, "-1")
    assert not accepts(nfa, "null")  # null branch correctly pruned
    with pytest.raises(ValueError, match="every distributed"):
        compile_schema(
            {"allOf": [{"anyOf": [{"type": "null"}, {"type": "boolean"}]},
                       {"type": "integer"}]}
        )


def test_allof_draft4_boolean_not_conflated_with_numeric():
    """True == 1 in Python; draft-4 boolean exclusive bounds normalize
    to the numeric form per conjunct, so (>5) ∧ (>1) merges to >5 —
    neither conflated with the number 1 nor re-attached to a bound
    tightened by a different conjunct."""
    nfa = compile_schema(
        {"type": "integer",
         "allOf": [{"minimum": 5, "exclusiveMinimum": True},
                   {"exclusiveMinimum": 1}]}
    )
    assert not accepts(nfa, "5")  # strict: 5 excluded
    assert accepts(nfa, "6")
    assert not accepts(nfa, "2")


def test_allof_draft4_flag_does_not_reattach_to_tightened_bound():
    """(>3) ∧ (>=5) must keep 5: the boolean flag from one conjunct may
    not make a DIFFERENT conjunct's minimum exclusive."""
    nfa = compile_schema(
        {"type": "integer",
         "allOf": [{"minimum": 3, "exclusiveMinimum": True},
                   {"minimum": 5}]}
    )
    assert accepts(nfa, "5")
    assert not accepts(nfa, "4")
    # same shape with an enum at the boundary value
    nfa = compile_schema(
        {"allOf": [{"minimum": 3, "exclusiveMinimum": True},
                   {"enum": [5], "minimum": 5}]}
    )
    assert accepts(nfa, "5")


def test_allof_integral_float_multipleof():
    nfa = compile_schema(
        {"allOf": [{"enum": [2, 3]}, {"multipleOf": 2.0}]}
    )
    assert accepts(nfa, "2")
    assert not accepts(nfa, "3")
    nfa = compile_schema(
        {"type": "integer", "minimum": 0, "maximum": 24,
         "allOf": [{"multipleOf": 4.0}, {"multipleOf": 6}]}
    )
    for v in range(0, 25):
        assert accepts(nfa, str(v)) == (v % 12 == 0), v


def test_allof_anyof_branch_object_keeps_implicit_required():
    """An object branch arriving through anyOf expansion keeps the
    all-properties-required default."""
    nfa = compile_schema(
        {"allOf": [
            {"anyOf": [{"type": "object",
                        "properties": {"a": {"type": "integer"}}}]},
            {"type": "object",
             "properties": {"b": {"type": "string"}},
             "required": ["b"]},
        ]}
    )
    assert accepts(nfa, '{"a":1,"b":"x"}')
    assert not accepts(nfa, '{"b":"x"}')


def test_allof_additional_properties_closure_across_conjuncts():
    """A conjunct's additionalProperties: false closes over ITS declared
    properties: a required extra from another conjunct is unsatisfiable;
    an optional extra is dropped (narrowing, never emitted)."""
    with pytest.raises(ValueError, match="additionalProperties"):
        compile_schema(
            {"allOf": [
                {"type": "object",
                 "properties": {"a": {"type": "integer"}},
                 "additionalProperties": False},
                {"type": "object",
                 "properties": {"b": {"type": "string"}},
                 "required": ["b"]},
            ]}
        )
    nfa = compile_schema(
        {"allOf": [
            {"type": "object",
             "properties": {"a": {"type": "integer"}},
             "additionalProperties": False},
            {"type": "object",
             "properties": {"b": {"type": "string"}},
             "required": []},
        ]}
    )
    assert accepts(nfa, '{"a":1}')
    assert not accepts(nfa, '{"a":1,"b":"x"}')  # b dropped by closure


def test_allof_map_value_schema_applies_to_merged_properties():
    """A map conjunct's value schema must constrain properties declared
    only by other conjuncts — string ∧ integer is unsatisfiable."""
    with pytest.raises(ValueError):
        compile_schema(
            {"allOf": [
                {"type": "object",
                 "additionalProperties": {"type": "integer"}},
                {"type": "object",
                 "properties": {"a": {"type": "string"}},
                 "required": ["a"]},
            ]}
        )
    nfa = compile_schema(
        {"allOf": [
            {"type": "object",
             "additionalProperties": {"minimum": 0}},
            {"type": "object",
             "properties": {"a": {"type": "integer", "maximum": 9}},
             "required": ["a"]},
        ]}
    )
    assert accepts(nfa, '{"a":5}')
    assert not accepts(nfa, '{"a":-3}')  # map conjunct's minimum applies


def test_allof_property_const_true_vs_1_not_conflated():
    with pytest.raises(ValueError, match="const"):
        compile_schema(
            {"allOf": [
                {"type": "object", "properties": {"a": {"const": True}},
                 "required": ["a"]},
                {"type": "object", "properties": {"a": {"const": 1}},
                 "required": ["a"]},
            ]}
        )


def test_allof_enum_dict_key_order_insensitive():
    """JSON-equal dict members with different key order intersect (no
    spurious empty-enum failure); the kept member emits in its own
    declared key order."""
    nfa = compile_schema(
        {"allOf": [{"enum": [{"a": 1, "b": 2}, 7]},
                   {"enum": [{"b": 2, "a": 1}]}]}
    )
    assert accepts(nfa, '{"b":2,"a":1}')
    assert not accepts(nfa, "7")


def test_allof_required_without_property_schema_hard_fails():
    with pytest.raises(ValueError, match="required"):
        compile_schema(
            {"allOf": [
                {"type": "object",
                 "properties": {"a": {"type": "integer"}},
                 "required": ["a"]},
                {"required": ["b"]},
            ]}
        )


def test_allof_fractional_multipleof_filters_enum():
    nfa = compile_schema(
        {"allOf": [{"enum": [1, 1.3]}, {"multipleOf": 0.5}]}
    )
    assert accepts(nfa, "1")
    assert not accepts(nfa, "1.3")


def test_freeform_map_honors_required_keys():
    nfa = compile_schema(
        {"type": "object", "additionalProperties": {"type": "integer"},
         "required": ["k"]}
    )
    assert not accepts(nfa, "{}")
    assert accepts(nfa, '{"k":1}')
    assert accepts(nfa, '{"k":1,"extra":2}')
    nfa = compile_schema(
        {"type": "object", "additionalProperties": {"type": "integer"},
         "required": ["k"], "maxProperties": 2}
    )
    assert accepts(nfa, '{"k":1,"x":2}')
    assert not accepts(nfa, '{"k":1,"x":2,"y":3}')
    with pytest.raises(ValueError, match="maxProperties"):
        compile_schema(
            {"type": "object", "additionalProperties": {},
             "required": ["a", "b"], "maxProperties": 1}
        )


# ---------------------------------------------------------------------------
# recursive schemas (self-referential Pydantic models)
# ---------------------------------------------------------------------------


def test_recursive_model_bounded_unrolling():
    """List['Node'] recursion compiles (no RecursionError): nesting
    accepted to MAX_REF_DEPTH, the cutoff closes child arrays to []."""
    from typing import List as TList

    class Node(BaseModel):
        name: str
        children: TList["Node"] = []

    nfa = compile_schema(normalize_output_schema(Node))
    assert accepts(nfa, '{"name":"a","children":[]}')
    assert accepts(
        nfa, '{"name":"a","children":[{"name":"b","children":[]}]}'
    )
    deep = '{"name":"a","children":[]}'
    for nm in ("b", "c", "d"):
        deep = (
            '{"name":"%s","children":[%s]}' % (nm, deep)
        )
    assert accepts(nfa, deep)  # depth == MAX_REF_DEPTH unrolls


def test_recursive_optional_keeps_null_arm():
    from typing import Optional as TOpt

    class Cell(BaseModel):
        v: int
        nxt: TOpt["Cell"] = None

    nfa = compile_schema(normalize_output_schema(Cell))
    assert accepts(nfa, '{"v":1}')
    assert accepts(nfa, '{"v":1,"nxt":{"v":2,"nxt":null}}')
    assert accepts(nfa, '{"v":1,"nxt":{"v":2,"nxt":{"v":3}}}')


def test_required_unbounded_recursion_hard_fails():
    """A required self-reference with no finite alternative cannot be
    finitely unrolled — clear ValueError, never a RecursionError."""
    with pytest.raises(ValueError, match="recursive"):
        compile_schema(
            {
                "$defs": {
                    "A": {
                        "type": "object",
                        "properties": {"next": {"$ref": "#/$defs/A"}},
                        "required": ["next"],
                    }
                },
                "$ref": "#/$defs/A",
            }
        )


def test_mutual_recursion_compiles_or_fails_cleanly():
    """A <-> B mutual recursion through an optional arm terminates."""
    schema = {
        "$defs": {
            "A": {
                "type": "object",
                "properties": {
                    "name": {"type": "string"},
                    "b": {"anyOf": [{"$ref": "#/$defs/B"},
                                    {"type": "null"}]},
                },
                "required": ["name"],
            },
            "B": {
                "type": "object",
                "properties": {
                    "a": {"anyOf": [{"$ref": "#/$defs/A"},
                                    {"type": "null"}]},
                },
                "required": ["a"],
            },
        },
        "$ref": "#/$defs/A",
    }
    nfa = compile_schema(schema)
    assert accepts(nfa, '{"name":"x"}')
    assert accepts(nfa, '{"name":"x","b":{"a":null}}')
    assert accepts(nfa, '{"name":"x","b":{"a":{"name":"y"}}}')


def test_recursive_ref_in_allof_wrapper():
    """Pydantic's Field()-metadata shape wraps the recursive ref in a
    single-element allOf — the depth counter must see through it."""
    schema = {
        "$defs": {
            "A": {
                "type": "object",
                "properties": {
                    "name": {"type": "string"},
                    "child": {
                        "anyOf": [
                            {"allOf": [{"$ref": "#/$defs/A"}],
                             "title": "Child"},
                            {"type": "null"},
                        ]
                    },
                },
                "required": ["name"],
            }
        },
        "$ref": "#/$defs/A",
    }
    nfa = compile_schema(schema)
    assert accepts(nfa, '{"name":"x"}')
    assert accepts(nfa, '{"name":"x","child":{"name":"y"}}')


def test_recursive_freeform_map_values():
    """Recursion through additionalProperties closes the map at the
    depth limit instead of RecursionError."""
    schema = {
        "$defs": {
            "A": {"type": "object",
                  "additionalProperties": {"$ref": "#/$defs/A"}}
        },
        "$ref": "#/$defs/A",
    }
    nfa = compile_schema(schema)
    assert accepts(nfa, "{}")
    assert accepts(nfa, '{"k":{}}')
    assert accepts(nfa, '{"k":{"j":{}}}')


def test_cpp_python_mask_parity_round3_features():
    """Native masker parity over the round-3 schema features (allOf
    merge, free-form map, recursion unrolling) — the NFA is the
    interchange format, so every new compile feature must flow through
    the C++ core bit-identically."""
    _assert_cpp_py_parity(
        {
            "$defs": {
                "N": {
                    "type": "object",
                    "properties": {
                        "v": {"allOf": [{"type": "integer", "minimum": 0},
                                        {"maximum": 20}]},
                        "kids": {"type": "array",
                                 "items": {"$ref": "#/$defs/N"}},
                        "tags": {"type": "object",
                                 "additionalProperties":
                                     {"type": "boolean"},
                                 "maxProperties": 2},
                    },
                    "required": ["v"],
                }
            },
            "$ref": "#/$defs/N",
        },
        '{"v":7,"kids":[{"v":20,"tags":{"a":true}}],"tags":{}}',
        expect_accept=True,
    )


@pytest.mark.parametrize(
    "schema",
    [
        # pure alias cycle: a def that IS a ref back to itself
        {"$defs": {"A": {"$ref": "#/$defs/A"}}, "$ref": "#/$defs/A"},
        # mutual alias cycle
        {"$defs": {"A": {"$ref": "#/$defs/B"},
                   "B": {"$ref": "#/$defs/A"}},
         "$ref": "#/$defs/A"},
        # cycle living entirely at allOf/anyOf level (bypasses
        # compile_node's per-node ref counter)
        {"$defs": {"U": {"anyOf": [{"allOf": [{"$ref": "#/$defs/U"}]},
                                   {"type": "null"}]}},
         "allOf": [{"$ref": "#/$defs/U"}]},
    ],
)
def test_ref_cycles_clear_error_never_recursionerror(schema):
    with pytest.raises(ValueError):
        compile_schema(schema)
