"""Constrained decoding: schema compiler, NFA semantics, token masks,
C++/Python parity."""

import json

import numpy as np
import pytest
from pydantic import BaseModel

from sutro_tpu.common import normalize_output_schema
from sutro_tpu.engine.constrain import (
    TokenTable,
    compile_schema,
    schema_constraint_factory,
)
from sutro_tpu.engine.constrain.fsm import MaskCache
from sutro_tpu.engine.tokenizer import ByteTokenizer


def accepts(nfa, text: str) -> bool:
    states = nfa.initial()
    for b in text.encode():
        states = nfa.step(states, b)
        if not states:
            return False
    return nfa.is_accepting(states)


@pytest.mark.parametrize(
    "schema,good,bad",
    [
        (
            {"type": "object", "properties": {"x": {"type": "integer"}},
             "required": ["x"]},
            ['{"x":0}', '{"x":-17}', '{"x":123456}'],
            ['{"x":01}', '{"x":1.5}', '{}', '{"x": 1}', '{"y":1}'],
        ),
        (
            {"type": "object", "properties": {"s": {"type": "string"}},
             "required": ["s"]},
            ['{"s":""}', '{"s":"hi"}', '{"s":"q\\"uote"}', '{"s":"\\u00e9"}'],
            ['{"s":5}', '{"s":"unterminated}', '{"s":"bad\\q"}'],
        ),
        (
            {"type": "object",
             "properties": {"t": {"type": "array", "items": {"type": "boolean"}}},
             "required": ["t"]},
            ['{"t":[]}', '{"t":[true]}', '{"t":[true,false,true]}'],
            ['{"t":[true,]}', '{"t":[1]}', '{"t":'],
        ),
        (
            {"type": "object",
             "properties": {
                 "a": {"type": "number"},
                 "b": {"enum": ["x", "y"]},
             },
             "required": ["b"]},
            ['{"a":1.5,"b":"x"}', '{"b":"y"}', '{"a":-2e3,"b":"x"}'],
            ['{"b":"z"}', '{"a":1.5}', '{"b":"x","a":1}'],  # fixed key order
        ),
    ],
)
def test_schema_acceptance(schema, good, bad):
    nfa = compile_schema(schema)
    for g in good:
        json.loads(g)  # sanity: must be valid JSON
        assert accepts(nfa, g), f"should accept {g}"
    for bstr in bad:
        assert not accepts(nfa, bstr), f"should reject {bstr}"


def test_pydantic_schema_with_enum_and_optional():
    from enum import Enum

    class Color(str, Enum):
        red = "red"
        blue = "blue"

    class M(BaseModel):
        color: Color
        note: str = "d"  # optional (has default => not required)

    nfa = compile_schema(normalize_output_schema(M))
    assert accepts(nfa, '{"color":"red","note":"hi"}')
    assert accepts(nfa, '{"color":"blue"}')
    assert not accepts(nfa, '{"color":"green"}')


def test_nested_object_and_anyof():
    schema = {
        "type": "object",
        "properties": {
            "sub": {
                "type": "object",
                "properties": {"x": {"type": "integer"}},
                "required": ["x"],
            },
            "opt": {"anyOf": [{"type": "integer"}, {"type": "null"}]},
        },
        "required": ["sub"],
    }
    nfa = compile_schema(schema)
    assert accepts(nfa, '{"sub":{"x":1}}')
    assert accepts(nfa, '{"sub":{"x":1},"opt":null}')
    assert accepts(nfa, '{"sub":{"x":1},"opt":42}')
    assert not accepts(nfa, '{"sub":{},"opt":null}')


def test_string_length_bounds():
    schema = {
        "type": "object",
        "properties": {"s": {"type": "string", "minLength": 2, "maxLength": 4}},
        "required": ["s"],
    }
    nfa = compile_schema(schema)
    assert not accepts(nfa, '{"s":"a"}')
    assert accepts(nfa, '{"s":"ab"}')
    assert accepts(nfa, '{"s":"abcd"}')
    assert not accepts(nfa, '{"s":"abcde"}')


def test_token_fsm_forces_valid_json():
    tok = ByteTokenizer()
    schema = {
        "type": "object",
        "properties": {"k": {"enum": ["a", "b"]}},
        "required": ["k"],
    }
    fac = schema_constraint_factory(schema, tok)
    fsm = fac()
    # walk by always taking the lexicographically-smallest allowed token
    out = bytearray()
    for _ in range(64):
        if fsm.is_complete():
            break
        mask = fsm.allowed_tokens()
        tid = int(np.argmax(mask))
        fsm.advance(tid)
        out += tok.token_bytes(tid)
        if fsm.is_complete():
            break
    parsed = json.loads(out.decode())
    assert parsed["k"] in ("a", "b")


def test_mask_allows_stop_only_at_accept():
    tok = ByteTokenizer()
    schema = {"type": "object", "properties": {"n": {"type": "integer"}},
              "required": ["n"]}
    fac = schema_constraint_factory(schema, tok)
    fsm = fac()
    assert not fsm.allowed_tokens()[tok.eos_id]
    for ch in b'{"n":7':
        fsm.advance(ch)
    # '7' could continue (more digits) or close; eos not yet allowed
    assert not fsm.allowed_tokens()[tok.eos_id]
    fsm.advance(ord("}"))
    assert fsm.is_complete()
    assert fsm.allowed_tokens()[tok.eos_id]


def test_cpp_python_mask_parity():
    pytest.importorskip("ctypes")
    from sutro_tpu.engine.constrain.cpp import CppMasker

    tok = ByteTokenizer()
    schema = {
        "type": "object",
        "properties": {
            "s": {"type": "string"},
            "v": {"type": "number"},
            "e": {"enum": ["aa", "ab", "b"]},
        },
        "required": ["s", "v", "e"],
    }
    nfa = compile_schema(schema)
    table = TokenTable(tok)
    try:
        cpp = CppMasker(nfa, table)
    except Exception:
        pytest.skip("native toolchain unavailable")
    py = MaskCache(nfa, table)
    py._cpp = None
    states = nfa.initial()
    for ch in '{"s":"x\\n","v":-1.5e2,"e":"ab"}'.encode():
        pm, pd = py._compute(states)
        cm, cd = cpp.mask(states)
        np.testing.assert_array_equal(pm, cm)
        np.testing.assert_array_equal(pd, cd)
        states = nfa.step(states, ch)
        assert states


def test_budget_aware_closure_always_completes():
    """With a token budget too small for free-running string content, the
    FSM must steer to closing bytes so the emitted JSON is complete
    (verify-session regression: mid-string cuts at the length cap)."""
    import json

    from sutro_tpu.engine.constrain.fsm import schema_constraint_factory

    tok = ByteTokenizer()
    schema = {
        "type": "object",
        "properties": {"label": {"type": "string"}},
        "required": ["label"],
    }
    nested = {
        "type": "array",
        "items": {
            "type": "object",
            "properties": {"label": {"type": "string"}},
            "required": ["label"],
        },
    }
    rng = np.random.default_rng(0)
    for sch, check in (
        (schema, lambda o: "label" in o),
        (nested, lambda o: isinstance(o, list)),
    ):
        factory = schema_constraint_factory(sch, tok)
        for budget in (14, 20, 40):
            fsm = factory()
            out = bytearray()
            remaining = budget
            while remaining > 0 and not fsm.is_complete():
                mask = fsm.allowed_tokens(remaining=remaining)
                ids = np.nonzero(mask)[0]
                assert len(ids), "mask must never be empty"
                # adversarial: pick a random allowed token (worst-case model)
                tid = int(rng.choice(ids))
                fsm.advance(tid)
                out.extend(tok.token_bytes(tid))
                remaining -= 1
            obj = json.loads(out.decode("utf-8", errors="strict"))
            assert check(obj), (sch, budget, out)


def test_distance_to_accept():
    from sutro_tpu.engine.constrain.schema import compile_schema as cs

    nfa = cs({"enum": ["ab"]})  # JSON: "ab" -> 4 bytes: " a b "
    d0 = nfa.dist_to_accept(nfa.initial())
    assert d0 == 4


def test_schema_min_tokens_raises_generation_cap(tiny_ecfg, tmp_path, monkeypatch):
    """A max_new_tokens below the schema's shortest accepting output must
    not break the schema guarantee: the engine raises the row cap to the
    FSM's min_tokens so constrained rows still emit complete JSON."""
    import dataclasses
    import json
    import time

    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.interfaces import JobStatus

    ecfg = dataclasses.replace(
        tiny_ecfg, max_pages_per_seq=32, max_model_len=256
    )
    eng = LocalEngine(ecfg)
    jid = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": ["x"],
            "sampling_params": {"max_new_tokens": 4},  # << schema minimum
            "output_schema": {
                "type": "object",
                "properties": {
                    "label": {"type": "string", "enum": ["aa", "bb"]}
                },
                "required": ["label"],
            },
        }
    )
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if JobStatus(eng.job_status(jid)).is_terminal():
            break
        time.sleep(0.05)
    assert eng.job_status(jid) == "SUCCEEDED"
    out = eng.job_results(jid)["outputs"][0]
    parsed = json.loads(out)  # complete JSON despite the 4-token cap
    assert parsed["label"] in ("aa", "bb")


def test_speculative_constrained_matches_masked(tiny_ecfg, byte_tok):
    """Greedy schema-constrained generation must produce IDENTICAL
    outputs whether every step is masked (decode_multi_step=1) or fused
    speculative windows verify-and-commit (decode_multi_step=8): for
    greedy rows, the unmasked argmax is accepted only when it equals the
    masked argmax, and rejections fall back to one masked step."""
    import dataclasses
    import json

    from sutro_tpu.engine.constrain import schema_constraint_factory
    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
    from sutro_tpu.models.configs import MODEL_CONFIGS

    schema = {
        "type": "object",
        "properties": {
            "note": {"type": "string", "maxLength": 20},
            "label": {"type": "string", "enum": ["alpha", "beta"]},
        },
        "required": ["note", "label"],
    }

    def run(multi):
        ecfg = dataclasses.replace(
            tiny_ecfg, decode_multi_step=multi, max_pages_per_seq=32,
            max_model_len=256,
        )
        runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
        factory = schema_constraint_factory(schema, byte_tok)
        reqs = [
            GenRequest(
                row_id=i,
                prompt_ids=np.array(byte_tok.encode(t), np.int32),
                max_new_tokens=80,
                temperature=0.0,
                constraint=factory(),
            )
            for i, t in enumerate(["first row", "second", "third one"])
        ]
        b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
        res = {}
        b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
        return {
            i: (tuple(r.token_ids), r.finish_reason)
            for i, r in res.items()
        }

    masked = run(1)
    spec = run(8)
    assert masked == spec
    # and every output is complete, schema-valid JSON
    for toks, _reason in masked.values():
        parsed = json.loads(byte_tok.decode(list(toks)))
        assert parsed["label"] in ("alpha", "beta")


# ---------------------------------------------------------------------------
# Integer minimum/maximum (interval automaton) + string pattern (regex)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "lo,hi",
    [(0, 10), (1, 5), (7, 7), (-5, 5), (-30, -7), (17, 40163), (None, 12),
     (3, None), (None, -4), (-9, None), (0, None), (None, 0)],
)
def test_integer_bounds_exact(lo, hi):
    """The digit-interval automaton accepts exactly the integers in
    range — brute-force checked against int comparison."""
    schema = {"type": "integer"}
    if lo is not None:
        schema["minimum"] = lo
    if hi is not None:
        schema["maximum"] = hi
    nfa = compile_schema(schema)
    for v in list(range(-60, 61)) + [1234, -1234, 40162, 40163, 40164, 99999]:
        want = (lo is None or v >= lo) and (hi is None or v <= hi)
        assert accepts(nfa, str(v)) == want, (v, lo, hi)
    # canonical form only: no leading zeros / plus signs ever
    assert not accepts(nfa, "007")
    assert not accepts(nfa, "+3")


def test_integer_exclusive_bounds():
    nfa = compile_schema(
        {"type": "integer", "exclusiveMinimum": 2, "exclusiveMaximum": 6}
    )
    for v in range(-3, 10):
        assert accepts(nfa, str(v)) == (3 <= v <= 5), v


@pytest.mark.parametrize(
    "pattern,good,bad",
    [
        (r"^[a-z]+$", ["abc", "z"], ["", "Abc", "ab1"]),
        (r"^\d{3}-\d{4}$", ["555-0199"], ["5550199", "55-0199", "555-019"]),
        (r"^(yes|no)$", ["yes", "no"], ["maybe", "yesno", ""]),
        # unanchored (JSON Schema semantics): substring match
        (r"cat", ["cat", "concatenate", "cat!"], ["dog", "ca t"]),
        (r"^[A-Z][a-z]*( [A-Z][a-z]*)*$", ["Hello World", "A"], ["hello", "A  B"]),
        (r"^v\d+\.\d+\.\d+$", ["v1.20.3"], ["v1.2", "1.2.3"]),
        (r"^[^0-9]*$", ["abc", ""], ["a1"]),
        (r"^a{2,4}$", ["aa", "aaaa"], ["a", "aaaaa"]),
        # class escapes: known literals map, punctuation stays literal
        (r"^[a\-z]+$", ["a", "-", "z", "a-z"], ["b", "m"]),
        (r"^[\t]$", ["\t"], [" ", "t"]),
        # escaped range-high endpoint maps (\t-\n = 0x09-0x0A; wider
        # ranges through 0x0B fall back — \v has no JSON short escape)
        (r"^[\t-\n]$", ["\t", "\n"], [" ", "t", "n", "\r"]),
    ],
)
def test_string_pattern_enforced(pattern, good, bad):
    nfa = compile_schema(
        {
            "type": "object",
            "properties": {"s": {"type": "string", "pattern": pattern}},
            "required": ["s"],
        }
    )
    for s in good:
        assert accepts(nfa, json.dumps({"s": s}, separators=(",", ":"))), s
    for s in bad:
        assert not accepts(nfa, json.dumps({"s": s}, separators=(",", ":"))), s


def test_unsupported_pattern_falls_back_with_warning():
    """Exotic constructs keep the job alive: the string is type-checked
    but the pattern is not enforced (documented fallback)."""
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema(
            {"type": "string", "pattern": r"^(?=lookahead)x$"}
        )
        assert any("not enforced" in str(x.message) for x in w)
    assert accepts(nfa, '"anything"')


@pytest.mark.parametrize(
    "pattern",
    [
        r"^[\x41]$",        # hex escape in class (would wrongly match "x"/"4"/"1")
        r"^[\x20-\x7E]+$",  # printable-ASCII idiom — hex range
        r"^[a-\x]$",        # exotic escape as range-high endpoint
        "^[\\u0041]$",      # unicode escape in class
        r"^[\1]$",          # backref-looking digit escape in class
    ],
)
def test_class_escape_exotic_falls_back(pattern):
    """Unrecognized escapes inside character classes must raise
    UnsupportedPattern (not silently degrade to the escape letter's
    literal — advisor round-2 medium), which routes the whole pattern
    into the documented warn-and-fallback path."""
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema({"type": "string", "pattern": pattern})
        assert any("not enforced" in str(x.message) for x in w), pattern
    # fallback accepts any string — crucially "x" is no longer wrongly
    # privileged over "A" by a mis-compiled class
    assert accepts(nfa, '"A"')
    assert accepts(nfa, '"x"')


def test_pattern_masks_drive_valid_generation():
    """End-to-end with the token FSM: masked sampling over a byte
    vocabulary can only produce strings matching the pattern."""
    schema = {
        "type": "object",
        "properties": {"id": {"type": "string", "pattern": r"^[A-Z]{2}\d{2}$"}},
        "required": ["id"],
    }
    tok = ByteTokenizer()
    factory = schema_constraint_factory(schema, tok)
    fsm = factory()
    rng = np.random.default_rng(0)
    out = bytearray()
    for _ in range(64):
        if fsm.is_complete():
            break
        ids = np.flatnonzero(fsm.allowed_tokens())
        assert len(ids), "dead state"
        t = int(rng.choice(ids))
        fsm.advance(t)
        out += tok.token_bytes(t)
    obj = json.loads(out.decode())
    import re

    assert re.fullmatch(r"[A-Z]{2}\d{2}", obj["id"])


def test_integer_bounds_edge_semantics():
    """Fractional bounds round inward; draft-4 boolean and draft-2020
    numeric exclusive forms intersect with minimum/maximum."""
    # fractional: minimum 2.5 -> 3 is the smallest valid integer
    nfa = compile_schema({"type": "integer", "minimum": 2.5})
    assert not accepts(nfa, "2") and accepts(nfa, "3")
    nfa = compile_schema({"type": "integer", "maximum": -0.5})
    assert not accepts(nfa, "0") and accepts(nfa, "-1")
    # draft-2020: both keywords apply independently
    nfa = compile_schema(
        {"type": "integer", "minimum": 10, "exclusiveMinimum": 2}
    )
    assert not accepts(nfa, "3") and not accepts(nfa, "9")
    assert accepts(nfa, "10")
    # draft-4 boolean form
    nfa = compile_schema(
        {"type": "integer", "minimum": 10, "exclusiveMinimum": True,
         "maximum": 12}
    )
    assert not accepts(nfa, "10") and accepts(nfa, "11")
    # exclusiveMinimum -2.5: v > -2.5 -> -2 is valid
    nfa = compile_schema({"type": "integer", "exclusiveMinimum": -2.5})
    assert accepts(nfa, "-2") and not accepts(nfa, "-3")


def test_malformed_and_oversized_patterns_fall_back():
    """Malformed braces and unbounded repetition caps degrade to the
    unconstrained string (warning), never crash or blow up memory."""
    import warnings

    for pat in ["a{b}", "x{}", "a{2,x}", "^a{200000,}$", "a{-1}"]:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            nfa = compile_schema({"type": "string", "pattern": pat})
            assert any("not enforced" in str(x.message) for x in w), pat
        assert accepts(nfa, '"whatever"'), pat


@pytest.mark.parametrize(
    "fmt,good,bad",
    [
        ("uuid", ["123e4567-e89b-12d3-a456-426614174000"],
         ["123e4567e89b12d3a456426614174000", "123E4567-e89b-12d3-a456-426614174000", "xyz"]),
        ("date", ["2026-07-30", "1999-12-01"],
         ["2026-13-01", "2026-00-10", "2026-01-32", "26-07-30"]),
        ("date-time", ["2026-07-30T23:59:59Z", "2026-07-30T00:00:00+05:30",
                       "2026-07-30T12:00:00.123"],
         ["2026-07-30 12:00:00", "2026-07-30T24:00:00Z"]),
        ("time", ["23:59:59", "00:00:00Z", "12:30:45.5+05:30"],
         ["24:00:00", "12:60:00", "1:00:00", "12:00"]),
        ("email", ["a@b.co", "first.last+tag@example.org"],
         ["no-at-sign", "@x.com", "a@b", "a@b."]),
        ("ipv4", ["0.0.0.0", "255.255.255.255", "192.168.1.7"],
         ["256.1.1.1", "1.2.3", "01.2.3.4", "1.2.3.4.5"]),
    ],
)
def test_string_format_enforced(fmt, good, bad):
    nfa = compile_schema({"type": "string", "format": fmt})
    for s in good:
        assert accepts(nfa, json.dumps(s)), (fmt, s)
    for s in bad:
        assert not accepts(nfa, json.dumps(s)), (fmt, s)


def test_unknown_format_is_annotation_only():
    nfa = compile_schema({"type": "string", "format": "hostname"})
    assert accepts(nfa, '"anything at all"')


def test_format_with_length_bounds_defers_to_lengths():
    """minLength/maxLength are validator-enforced; format is annotation.
    When both appear the length bounds win, so generated values never
    fail the user's own validation."""
    nfa = compile_schema(
        {"type": "string", "format": "uuid", "maxLength": 10}
    )
    assert accepts(nfa, '"short"')          # within maxLength
    assert not accepts(nfa, '"12345678901"')  # 11 chars > maxLength


def test_unsupported_pattern_falls_back_to_format():
    """A pattern outside the regex subset degrades to the format grammar
    (closer than an unconstrained string) when one is available."""
    import warnings

    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        nfa = compile_schema(
            {"type": "string", "pattern": r"(?=x)a", "format": "ipv4"}
        )
    assert accepts(nfa, '"10.0.0.1"')
    assert not accepts(nfa, '"not an ip"')


@pytest.mark.parametrize(
    "lo,hi",
    [("0", "10"), ("1.5", "3.5"), ("0.25", "0.75"), ("2", "2"),
     ("-5.5", "5.5"), ("-30.2", "-7.85"), ("17", "40163.125"),
     (None, "12.5"), ("3.25", None), (None, "-4.5"), ("-0.5", None)],
)
def test_number_bounds_exact(lo, hi):
    """The decimal interval automaton accepts exactly the in-range
    plain decimals — brute-force checked against Decimal comparison."""
    import decimal

    schema = {"type": "number"}
    if lo is not None:
        schema["minimum"] = float(lo)
    if hi is not None:
        schema["maximum"] = float(hi)
    nfa = compile_schema(schema)
    dlo = None if lo is None else decimal.Decimal(lo)
    dhi = None if hi is None else decimal.Decimal(hi)

    cands = set()
    for base in [-31, -30.2, -8, -7.85, -7.8, -5.5, -4.5, -4.49, -1,
                 -0.75, -0.5, -0.25, 0, 0.24, 0.25, 0.5, 0.75, 0.76,
                 1, 1.4, 1.5, 2, 2.5, 3.5, 3.51, 5.5, 9, 10, 10.5, 12.5,
                 12.51, 17, 40163, 40163.125, 40163.13, 99999]:
        cands.add(str(decimal.Decimal(str(base))))
    for s in sorted(cands):
        v = decimal.Decimal(s)
        want = (dlo is None or v >= dlo) and (dhi is None or v <= dhi)
        assert accepts(nfa, s) == want, (s, lo, hi)
    # canonical form only
    assert not accepts(nfa, "01.5")
    assert not accepts(nfa, "1.")
    assert not accepts(nfa, "+2")
    assert not accepts(nfa, "2e0")  # no exponent form under bounds
    # trailing zeros are fine when the value is in range
    mid = dlo if dlo is not None else dhi
    if mid is not None:
        s = str(mid)
        if "." in s:
            assert accepts(nfa, s + "0") == (
                (dlo is None or mid >= dlo) and (dhi is None or mid <= dhi)
            )


def test_number_exclusive_bounds_are_subset():
    """Exclusive real bounds: the compiled language must EXCLUDE the
    boundary and stay within the open interval."""
    nfa = compile_schema(
        {"type": "number", "exclusiveMinimum": 1.5, "exclusiveMaximum": 4}
    )
    assert not accepts(nfa, "1.5")
    assert not accepts(nfa, "4")
    assert accepts(nfa, "2")
    assert accepts(nfa, "3.999")
    assert not accepts(nfa, "1.4")
    assert not accepts(nfa, "4.1")


def test_number_exclusive_bounds_arbitrary_depth():
    """Strict real bounds admit values arbitrarily close to the
    boundary but never the boundary itself (at any trailing-zero
    depth)."""
    nfa = compile_schema(
        {"type": "number", "exclusiveMinimum": 1.5, "exclusiveMaximum": 4}
    )
    for good in ["1.500001", "1.51", "3.9999999", "2", "3.5"]:
        assert accepts(nfa, good), good
    for bad in ["1.5", "1.50", "1.5000", "4", "4.0", "4.000", "1.49",
                "4.0001"]:
        assert not accepts(nfa, bad), bad


def test_number_negative_strict_zero():
    """maximum 0 strict => only negative values; "-0" variants equal
    zero and must be rejected."""
    nfa = compile_schema({"type": "number", "exclusiveMaximum": 0})
    for good in ["-0.001", "-1", "-99.5"]:
        assert accepts(nfa, good), good
    for bad in ["0", "0.0", "-0", "-0.0", "-0.000", "0.001"]:
        assert not accepts(nfa, bad), bad


def test_number_bounds_edge_cases():
    """Negative-zero bounds compile (sign-strip regression) and
    astronomically wide bounds stay cheap (O(width) construction)."""
    import time

    nfa = compile_schema({"type": "number", "minimum": -0.0})
    assert accepts(nfa, "0") and accepts(nfa, "7.5")
    assert not accepts(nfa, "-1")

    t0 = time.monotonic()
    nfa = compile_schema({"type": "number", "minimum": 0,
                          "maximum": 1.7e308})
    dt = time.monotonic() - t0
    assert dt < 1.0, f"wide-bound compile took {dt:.2f}s"
    assert accepts(nfa, "12345.678")
    assert accepts(nfa, "9" * 300)
    assert not accepts(nfa, "-1")


@pytest.mark.parametrize(
    "schema,k,lo,hi",
    [
        ({"type": "integer", "multipleOf": 7}, 7, None, None),
        ({"type": "integer", "multipleOf": 5, "minimum": 3,
          "maximum": 100}, 5, 3, 100),
        ({"type": "integer", "multipleOf": 12, "minimum": -40,
          "maximum": 40}, 12, -40, 40),
        ({"type": "integer", "multipleOf": 9, "minimum": 17}, 9, 17, None),
        ({"type": "integer", "multipleOf": 4, "maximum": -6}, 4, None, -6),
    ],
)
def test_integer_multiple_of(schema, k, lo, hi):
    """multipleOf composes exactly with bounds via the remainder-
    tracking product automaton."""
    nfa = compile_schema(schema)
    for v in list(range(-130, 131)) + [252, 999, 1008, -1008]:
        want = (
            v % k == 0
            and (lo is None or v >= lo)
            and (hi is None or v <= hi)
        )
        assert accepts(nfa, str(v)) == want, (v, schema)
    assert not accepts(nfa, "014")


def test_multiple_of_empty_range_raises():
    with pytest.raises(ValueError, match="no multiple"):
        compile_schema(
            {"type": "integer", "multipleOf": 50, "minimum": 3,
             "maximum": 40}
        )


def test_fractional_multiple_of_warns_and_ignores():
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema({"type": "integer", "multipleOf": 0.5})
        assert any("not enforced" in str(x.message) for x in w)
    assert accepts(nfa, "3")


def test_unique_items_enum_array():
    """uniqueItems + small enum items: repeats are impossible by
    construction; size bounds respected."""
    schema = {
        "type": "array",
        "items": {"enum": ["a", "b", "c"]},
        "uniqueItems": True,
        "minItems": 1,
        "maxItems": 2,
    }
    nfa = compile_schema(schema)
    enc = lambda a: json.dumps(a, separators=(",", ":"))  # noqa: E731
    for good in [["a"], ["c"], ["a", "b"], ["c", "a"]]:
        assert accepts(nfa, enc(good)), good
    for bad in [[], ["a", "a"], ["a", "b", "c"], ["d"], ["a", "d"]]:
        assert not accepts(nfa, enc(bad)), bad


def test_unique_items_large_pool_warns():
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nfa = compile_schema(
            {
                "type": "array",
                "items": {"enum": list("abcdefgh")},
                "uniqueItems": True,
            }
        )
        assert any("uniqueItems" in str(x.message) for x in w)
    assert accepts(nfa, '["a","a"]')  # unchecked fallback


def test_unique_items_dedupes_enum_values():
    """Positional duplicates in the enum pool must not defeat the
    uniqueness guarantee."""
    nfa = compile_schema(
        {"type": "array", "items": {"enum": ["a", "a", "b"]},
         "uniqueItems": True, "minItems": 1}
    )
    assert accepts(nfa, '["a","b"]')
    assert not accepts(nfa, '["a","a"]')
