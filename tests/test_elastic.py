"""Elastic dp fleet chaos suite (FAILURES.md "Elastic fleet").

The chaos gate for the elastic membership protocol: worker crash, hang,
mid-frame drop, SIGTERM preemption drain, and late join on a 256-row
multi-worker job must all end with the round COMPLETED, zero lost rows,
and a merged result set bit-identical to a fault-free run (first result
wins; duplicates dropped by row id before the merge). Runs the
coordinator/worker in-process on threads — the same channel-level
harness as tests/test_dphost.py and the dp scenarios in test_chaos.py —
so every scenario finishes in seconds.

Also covers the protocol-degradation contract (old worker with elastic
coordinator and vice versa run fixed-world rounds unchanged), the
coordinator-crash resume path (restart replays only missing rows), the
EngineConfig channel knobs, and serve_resume_round's bounded bind
retry.
"""

import os
import signal
import socket
import threading
import time

import pytest

from sutro_tpu.engine import faults
from sutro_tpu.engine import dphost
from sutro_tpu.engine.dphost import (
    DPWorld,
    fleet_view,
    run_dp_coordinator,
    run_dp_worker,
    serve_resume_round,
    shard_requests,
)

from tests.conftest import free_low_port as _free_port

N_ROWS = 256


@pytest.fixture(autouse=True)
def _clean_channel_state():
    """Every scenario starts with no fault plan, no sticky drain flag,
    and the EngineConfig channel overrides reset."""
    yield
    faults.clear()
    dphost._DRAIN.clear()
    dphost._CHANNEL_CFG.update({"stall_timeout": None, "heartbeat": None})


def _worlds(port, world):
    return [
        DPWorld(rank=r, world=world, host="127.0.0.1", port=port)
        for r in range(world)
    ]


def _reqs(n=N_ROWS):
    import numpy as np

    from sutro_tpu.engine.scheduler import GenRequest

    return [
        GenRequest(row_id=i, prompt_ids=np.array([1, 2], np.int32))
        for i in range(n)
    ]


def _res(row_id):
    from sutro_tpu.engine.scheduler import GenResult

    # per-row-distinct content so "bit-identical merge" is a real claim
    return GenResult(
        row_id=row_id, token_ids=[row_id % 11, 7],
        cumulative_logprob=0.0, finish_reason="stop", input_tokens=2,
    )


def _shard_fn(ran=None, per_row=None):
    """Trivial deterministic shard runner. ``ran`` collects executed row
    ids; ``per_row(row_id)`` runs before each row (sleep / drain
    hooks)."""

    def fn(shard, on_result, on_progress, should_cancel):
        for q in shard:
            if should_cancel():
                return "cancelled"
            if per_row is not None:
                per_row(q.row_id)
            if ran is not None:
                ran.append(q.row_id)
            on_result(_res(q.row_id))
        return "completed"

    return fn


class _Merge:
    """Coordinator-side merge recorder: counts on_result invocations
    per row so duplicate merges (a steal race both sides winning) are
    detected, not absorbed."""

    def __init__(self):
        self.lock = threading.Lock()
        self.counts = {}
        self.results = {}

    def __call__(self, res):
        with self.lock:
            self.counts[res.row_id] = self.counts.get(res.row_id, 0) + 1
            self.results[res.row_id] = list(res.token_ids)

    def assert_complete_no_dups(self, n=N_ROWS):
        assert set(self.results) == set(range(n)), (
            f"lost rows: {sorted(set(range(n)) - set(self.results))[:16]}"
        )
        dups = {r: c for r, c in self.counts.items() if c != 1}
        assert not dups, f"duplicate merges reached on_result: {dups}"
        # bit-identical to a fault-free run: content is row-determined
        for rid, toks in self.results.items():
            assert toks == [rid % 11, 7]


class _Events:
    def __init__(self):
        self.lock = threading.Lock()
        self.items = []

    def __call__(self, ev):
        with self.lock:
            self.items.append(dict(ev))

    def of(self, kind):
        with self.lock:
            return [e for e in self.items if e.get("event") == kind]


def _spawn_worker(world, shard_fn, pool, *, elastic=True, drain=None,
                  outcomes=None, name=None):
    key = name or f"rank{world.rank}"

    def main():
        try:
            out = run_dp_worker(
                world, shard_fn, pool, elastic=elastic, drain=drain,
            )
        except Exception as e:  # noqa: BLE001 — injected faults re-raise
            out = f"raised:{type(e).__name__}"
        if outcomes is not None:
            outcomes[key] = out

    t = threading.Thread(target=main, daemon=True, name=f"dpw-{key}")
    t.start()
    return t


# ---------------------------------------------------------------------------
# the chaos gate: crash / hang / torn frame / preempt / late join
# ---------------------------------------------------------------------------


def test_elastic_clean_round_three_workers():
    """Baseline: a 256-row job across a coordinator + 3 elastic workers
    completes with every row merged exactly once, and the fleet view
    reports the round."""
    port = _free_port()
    cw, w1, w2, w3 = _worlds(port, 4)
    reqs = _reqs()
    merge, events, outcomes = _Merge(), _Events(), {}
    threads = [
        _spawn_worker(w, _shard_fn(), reqs, outcomes=outcomes)
        for w in (w1, w2, w3)
    ]
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 4),
        on_result=merge, on_row_event=events,
        requests=reqs, job_id="job-clean",
    )
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()
    assert outcome == "completed"
    assert all(v == "completed" for v in outcomes.values()), outcomes
    merge.assert_complete_no_dups()
    joins = events.of("dp_worker_joined")
    assert {e["rank"] for e in joins} == {1, 2, 3}
    snap = fleet_view("job-clean")
    assert snap is not None and snap["elastic"]
    assert snap["rows"]["done"] == N_ROWS
    assert snap["rows"]["pending"] == 0


def test_elastic_worker_crash_after_join_requeues_and_completes():
    """A worker that dies right after admission (join churn) loses its
    whole assignment; the coordinator requeues those rows onto the
    surviving idle rank and the round still completes — zero lost rows,
    no duplicate merges, the requeue on the failure_log."""
    faults.configure("dphost.join:crash:times=1")
    port = _free_port()
    cw, w1, w2 = _worlds(port, 3)
    reqs = _reqs()
    merge, events, outcomes = _Merge(), _Events(), {}
    threads = [
        _spawn_worker(w, _shard_fn(), reqs, outcomes=outcomes)
        for w in (w1, w2)
    ]
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 3),
        on_result=merge, on_row_event=events, requests=reqs,
    )
    for t in threads:
        t.join(timeout=120)
    assert outcome == "completed"
    merge.assert_complete_no_dups()
    req_evts = events.of("dp_rows_requeued")
    assert req_evts, "crash produced no dp_rows_requeued event"
    assert sum(e["rows"] for e in req_evts) >= 1
    assert sorted(outcomes.values()).count("completed") == 1


def test_elastic_worker_hang_stalled_rows_requeued(monkeypatch):
    """A worker that goes TRULY silent mid-round (no heartbeat, no
    results — a wedged process, simulated with a raw socket that
    handshakes and then says nothing) is declared stalled by the
    watchdog; an elastic round requeues its rows and completes instead
    of failing."""
    monkeypatch.setenv("SUTRO_DP_STALL_TIMEOUT", "1")
    # healthy ranks must beat the 1s stall bound even while parked idle
    monkeypatch.setenv("SUTRO_DP_HEARTBEAT", "0.2")
    port = _free_port()
    cw, _w1, w2 = _worlds(port, 3)
    reqs = _reqs()
    merge, events, outcomes = _Merge(), _Events(), {}
    hung = threading.Event()

    def hung_rank1():
        deadline = time.monotonic() + 60
        sock = None
        while sock is None:
            try:
                sock = socket.create_connection(
                    ("127.0.0.1", port), timeout=10.0
                )
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        dphost._send(
            sock, {"t": "hello", "rank": 1, "job": "", "elastic": 1}
        )
        next(dphost._recv_lines(sock), None)  # resume reply
        hung.set()
        time.sleep(60)  # wedged: no results, no heartbeat
        sock.close()

    threading.Thread(target=hung_rank1, daemon=True).start()
    t = _spawn_worker(w2, _shard_fn(), reqs, outcomes=outcomes)
    t0 = time.monotonic()
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 3),
        on_result=merge, on_row_event=events, requests=reqs,
    )
    assert outcome == "completed"
    assert time.monotonic() - t0 < 60  # stall bound, not accept bound
    merge.assert_complete_no_dups()
    assert hung.is_set()
    assert any(
        e.get("reason") == "stall" for e in events.of("dp_rows_requeued")
    ), events.items
    t.join(timeout=120)
    assert outcomes.get("rank2") == "completed"


def test_elastic_mid_frame_drop_requeues_torn_row():
    """A connection torn MID-FRAME (injected socket drop during a result
    send) must not lose the row: the coordinator requeues the dead
    rank's remainder and the merge stays bit-identical."""
    faults.configure("dphost.send:drop:nth=5,times=1")
    port = _free_port()
    cw, w1, w2 = _worlds(port, 3)
    reqs = _reqs()
    merge, events, outcomes = _Merge(), _Events(), {}
    threads = [
        _spawn_worker(w, _shard_fn(), reqs, outcomes=outcomes)
        for w in (w1, w2)
    ]
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 3),
        on_result=merge, on_row_event=events, requests=reqs,
    )
    for t in threads:
        t.join(timeout=120)
    assert outcome == "completed"
    merge.assert_complete_no_dups()
    assert events.of("dp_rows_requeued")


def test_elastic_preempt_drain_via_fault_site(monkeypatch):
    """The dphost.preempt fault site: a worker drains mid-shard —
    finishes the in-flight row, hands unfinished ids back in a drain
    frame, returns "drained" — and the round completes without it.
    With the requeue limit at 0, ANY counted requeue would fail the
    round, proving a graceful drain is not held against the rows."""
    monkeypatch.setenv("SUTRO_DP_REQUEUE_LIMIT", "0")
    faults.configure("dphost.preempt:error:nth=10,times=1")
    port = _free_port()
    cw, w1, w2 = _worlds(port, 3)
    reqs = _reqs()
    merge, events, outcomes = _Merge(), _Events(), {}
    threads = [
        _spawn_worker(w, _shard_fn(), reqs, outcomes=outcomes)
        for w in (w1, w2)
    ]
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 3),
        on_result=merge, on_row_event=events, requests=reqs,
    )
    for t in threads:
        t.join(timeout=120)
    assert outcome == "completed"
    merge.assert_complete_no_dups()
    drains = events.of("dp_preempt_drain")
    assert len(drains) == 1, events.items
    assert sorted(outcomes.values()) == ["completed", "drained"]


def test_elastic_sigterm_drains_main_thread_worker():
    """SIGTERM on an elastic worker running on the MAIN thread is the
    spot-preemption notice: the installed handler requests a drain, the
    worker returns "drained", and the previous handler is restored."""
    port = _free_port()
    cw, w1, w2 = _worlds(port, 3)
    reqs = _reqs()
    merge, events, outcomes = _Merge(), _Events(), {}
    coord_out = {}

    def coord_main():
        coord_out["v"] = run_dp_coordinator(
            cw, _shard_fn(), shard_requests(reqs, 0, 3),
            on_result=merge, on_row_event=events, requests=reqs,
        )

    ct = threading.Thread(target=coord_main, daemon=True)
    ct.start()
    _spawn_worker(w2, _shard_fn(), reqs, outcomes=outcomes)

    fired = threading.Event()

    def preempt(row_id):
        # the "cloud" preempts this host a few rows into its shard —
        # but only once rank 2 has joined: _DRAIN is process-global,
        # and a rank 2 still in its connect loop would drain without
        # ever connecting, parking its stride until the join grace
        if row_id > 10 and not fired.is_set():
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                with merge.lock:
                    if any(r % 3 == 2 for r in merge.results):
                        break
                time.sleep(0.01)
            fired.set()
            os.kill(os.getpid(), signal.SIGTERM)

    prev_handler = signal.getsignal(signal.SIGTERM)
    out = run_dp_worker(
        w1, _shard_fn(per_row=preempt), reqs, elastic=True,
    )
    assert out == "drained"
    assert signal.getsignal(signal.SIGTERM) == prev_handler
    ct.join(timeout=120)
    assert not ct.is_alive()
    assert coord_out["v"] == "completed"
    merge.assert_complete_no_dups()
    assert events.of("dp_preempt_drain")


def test_elastic_late_joiner_absorbs_requeued_rows():
    """A rank joining OUTSIDE the fixed world (rank id >= world) is
    admitted with a fresh rank and an empty assignment, then absorbs
    rows the round needs re-run — here, the stride of a worker that
    died right after joining."""
    plan = faults.configure("dphost.join:crash:times=1")
    port = _free_port()
    cw, w1 = _worlds(port, 2)
    late = DPWorld(rank=7, world=2, host="127.0.0.1", port=port)
    reqs = _reqs()
    merge, outcomes = _Merge(), {}
    # Two races to pin down: the crash clause must hit w1 (not `late`),
    # and `late` must be admitted before the tiny job completes — the
    # coordinator absorbs a dead worker's requeued rows itself in well
    # under a second on an idle box, and under CPU load `late` can lose
    # that race entirely. So: hold `late`'s spawn until the clause has
    # fired, and hold the coordinator's own rows until the late join is
    # observed.
    late_joined = threading.Event()
    events = _Events()

    def on_evt(ev):
        events(ev)
        if ev.get("event") == "dp_worker_joined" and ev.get("late_join"):
            late_joined.set()

    threads = [
        _spawn_worker(w1, _shard_fn(), reqs, outcomes=outcomes),
    ]

    def _admit_late():
        deadline = time.monotonic() + 60
        while plan.specs[0].fires < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        threads.append(
            _spawn_worker(late, _shard_fn(), reqs, outcomes=outcomes,
                          name="late")
        )

    gate = threading.Thread(target=_admit_late, daemon=True)
    gate.start()
    outcome = run_dp_coordinator(
        cw, _shard_fn(per_row=lambda _rid: late_joined.wait(timeout=60)),
        shard_requests(reqs, 0, 2),
        on_result=merge, on_row_event=on_evt,
        requests=reqs, job_id="job-late",
    )
    gate.join(timeout=90)
    for t in list(threads):
        t.join(timeout=120)
    assert outcome == "completed"
    merge.assert_complete_no_dups()
    joins = events.of("dp_worker_joined")
    assert any(e["late_join"] for e in joins), joins
    # the late joiner was assigned a fresh rank beyond the fixed world
    assert any(e["rank"] >= 2 for e in joins)


def test_elastic_steal_race_first_result_wins():
    """Work stealing: with nothing pending and an idle rank parked, the
    straggler's tail half is dual-assigned (forced here by the
    dphost.steal site instead of waiting out SUTRO_DP_STEAL_AFTER).
    Both ranks may stream the same rows — exactly one copy reaches the
    merge."""
    faults.configure("dphost.steal:error:times=1")
    port = _free_port()
    cw, w1, w2 = _worlds(port, 3)
    reqs = _reqs(36)  # straggler sleeps per row; keep the tail short
    merge, events, outcomes = _Merge(), _Events(), {}

    def slow(row_id):
        time.sleep(0.08)

    def gate(row_id):
        # don't let rank 2 park idle before the straggler has even
        # joined: the forced-steal fault is times=1, and firing it
        # with no admitted victim would waste the charge
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            with merge.lock:
                if any(r % 3 == 1 for r in merge.results):
                    return
            time.sleep(0.01)

    threads = [
        _spawn_worker(
            w1, _shard_fn(per_row=slow), reqs, outcomes=outcomes,
            name="straggler",
        ),
        _spawn_worker(
            w2, _shard_fn(per_row=gate), reqs, outcomes=outcomes,
        ),
    ]
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 3),
        on_result=merge, on_row_event=events,
        requests=reqs, job_id="job-steal",
    )
    for t in threads:
        t.join(timeout=120)
    assert outcome == "completed"
    merge.assert_complete_no_dups(36)
    steals = events.of("dp_rows_stolen")
    assert len(steals) == 1, events.items
    assert steals[0]["victim"] == 1 and steals[0]["thief"] == 2
    snap = fleet_view("job-steal")
    assert snap["counters"]["stolen_rows"] == steals[0]["rows"]


def test_elastic_all_workers_die_rank0_claims_everything():
    """The zero-lost-rows backstop: every worker dies right after
    joining, no idle rank is ever parked, and rank 0 claims and runs
    the orphaned rows itself."""
    faults.configure("dphost.join:crash:times=2")
    port = _free_port()
    cw, w1, w2 = _worlds(port, 3)
    reqs = _reqs()
    merge, events, outcomes = _Merge(), _Events(), {}
    local_ran = []
    threads = [
        _spawn_worker(w, _shard_fn(), reqs, outcomes=outcomes)
        for w in (w1, w2)
    ]
    outcome = run_dp_coordinator(
        cw, _shard_fn(ran=local_ran), shard_requests(reqs, 0, 3),
        on_result=merge, on_row_event=events, requests=reqs,
    )
    for t in threads:
        t.join(timeout=120)
    assert outcome == "completed"
    merge.assert_complete_no_dups()
    # rank 0 ran more than its own stride (it picked up orphans)
    assert len(local_ran) > len(shard_requests(reqs, 0, 3))
    assert all(v.startswith("raised:") for v in outcomes.values())


def test_elastic_never_connected_rank_released_after_join_grace(
    monkeypatch,
):
    """A reserved stride whose rank never connects stops blocking the
    round after SUTRO_DP_JOIN_GRACE: the rows requeue (not counted
    against the limit) and the round completes without it."""
    monkeypatch.setenv("SUTRO_DP_JOIN_GRACE", "1.5")
    port = _free_port()
    cw, w1, _w2 = _worlds(port, 3)  # rank 2 never shows up
    reqs = _reqs(64)
    merge, events, outcomes = _Merge(), _Events(), {}
    t = _spawn_worker(w1, _shard_fn(), reqs, outcomes=outcomes)
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 3),
        on_result=merge, on_row_event=events, requests=reqs,
    )
    t.join(timeout=120)
    assert outcome == "completed"
    merge.assert_complete_no_dups(64)
    assert any(
        e.get("reason") == "never_connected_within_join_grace"
        for e in events.of("dp_rows_requeued")
    ), events.items


# ---------------------------------------------------------------------------
# protocol degradation: old peers on either side
# ---------------------------------------------------------------------------


def test_old_worker_with_elastic_coordinator_runs_fixed_stride():
    """A v1 worker (no elastic hello) against an elastic coordinator is
    a fixed-stride member: it runs exactly its stride and the round
    completes unchanged."""
    port = _free_port()
    cw, w1 = _worlds(port, 2)
    reqs = _reqs(64)
    merge, events = _Merge(), _Events()
    ran = []
    t = _spawn_worker(
        w1, _shard_fn(ran=ran), shard_requests(reqs, 1, 2),
        elastic=False, name="v1",
    )
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 2),
        on_result=merge, on_row_event=events, requests=reqs,
    )
    t.join(timeout=120)
    assert outcome == "completed"
    merge.assert_complete_no_dups(64)
    assert sorted(ran) == [i for i in range(64) if i % 2 == 1]
    joins = events.of("dp_worker_joined")
    assert joins and joins[0]["elastic"] is False


def test_elastic_worker_with_old_coordinator_degrades_to_stride():
    """An elastic worker whose resume reply carries no assignment (old
    coordinator) falls back to its fixed stride over the pool — the
    pre-elastic round, byte for byte."""
    port = _free_port()
    cw, w1 = _worlds(port, 2)
    reqs = _reqs(64)
    merge = _Merge()
    ran = []
    outcomes = {}
    t = _spawn_worker(
        w1, _shard_fn(ran=ran), reqs, elastic=True, outcomes=outcomes,
    )
    # requests=None -> the coordinator runs the fixed-world (v1) round
    outcome = run_dp_coordinator(
        cw, _shard_fn(), shard_requests(reqs, 0, 2), on_result=merge,
    )
    t.join(timeout=120)
    assert outcome == "completed"
    assert outcomes["rank1"] == "completed"
    merge.assert_complete_no_dups(64)
    assert sorted(ran) == [i for i in range(64) if i % 2 == 1]


# ---------------------------------------------------------------------------
# coordinator crash mid-round: restart + resume replays only missing rows
# ---------------------------------------------------------------------------


def test_coordinator_crash_mid_round_resume_replays_only_missing():
    """Rank 0 dies mid-round (its local shard raises); the workers see
    EOF and stop. A restarted coordinator resumes with the merged set:
    workers re-run ONLY rows that never merged, and the final result
    set is bit-identical to a fault-free run."""
    reqs = _reqs(96)
    merge = _Merge()

    port = _free_port()
    cw, w1, w2 = _worlds(port, 3)
    outcomes = {}

    def dawdle(row_id):
        # keep round-1 workers slow enough that the crash lands while
        # every stride still has unmerged rows — otherwise round 2 has
        # nothing for the workers to replay and finishes before they
        # can even connect
        time.sleep(0.02)

    def crashing_local(shard, on_result, on_progress, should_cancel):
        for q in shard[:10]:
            on_result(_res(q.row_id))
        # die only once BOTH workers have merged rows — a worker still
        # in its connect loop when the listener closes would spin out
        # its whole accept deadline
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with merge.lock:
                if {r % 3 for r in merge.results} >= {1, 2}:
                    break
            time.sleep(0.01)
        raise RuntimeError("rank0 host died")

    threads = [
        _spawn_worker(
            w, _shard_fn(per_row=dawdle), reqs, outcomes=outcomes
        )
        for w in (w1, w2)
    ]
    with pytest.raises(RuntimeError, match="rank0 host died"):
        run_dp_coordinator(
            cw, crashing_local, shard_requests(reqs, 0, 3),
            on_result=merge, requests=reqs,
        )
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    survived = set(merge.results)
    assert survived and survived != set(range(96))
    # the crash left unmerged rows in every stride — round 2 must
    # involve the workers, not just rank 0's leftovers
    assert {r % 3 for r in set(range(96)) - survived} == {0, 1, 2}

    # restart: same pool, done set = whatever merged before the crash
    port2 = _free_port()
    cw2, w1b, w2b = _worlds(port2, 3)
    ran2 = []
    outcomes2 = {}
    threads2 = [
        _spawn_worker(w, _shard_fn(ran=ran2), reqs, outcomes=outcomes2)
        for w in (w1b, w2b)
    ]
    local_ran2 = []
    pending = [q for q in reqs if q.row_id not in survived]
    outcome = run_dp_coordinator(
        cw2, _shard_fn(ran=local_ran2),
        shard_requests(pending, 0, 3),
        on_result=merge, done_rows=set(survived), requests=pending,
    )
    for t in threads2:
        t.join(timeout=60)
        assert not t.is_alive()
    assert outcome == "completed"
    # no already-merged row ran again, anywhere — and the workers did
    # the replaying, not just rank 0
    assert ran2
    assert not (set(ran2) | set(local_ran2)) & survived
    merge.assert_complete_no_dups(96)


# ---------------------------------------------------------------------------
# satellites: config knobs, seeded backoff, resume bind retry, state unit
# ---------------------------------------------------------------------------


def test_engine_config_channel_fields_and_env_precedence(monkeypatch):
    from sutro_tpu.engine.config import EngineConfig

    ecfg = EngineConfig()
    assert ecfg.dp_stall_timeout == 600.0
    assert ecfg.dp_heartbeat == 20.0

    monkeypatch.delenv("SUTRO_DP_STALL_TIMEOUT", raising=False)
    monkeypatch.delenv("SUTRO_DP_HEARTBEAT", raising=False)
    dphost.configure_channel(stall_timeout=5.0, heartbeat=7.0)
    assert dphost._stall_timeout_s() == 5.0
    assert dphost._heartbeat_s() == 7.0
    # env (set and non-empty) overrides the configured value
    monkeypatch.setenv("SUTRO_DP_STALL_TIMEOUT", "9")
    assert dphost._stall_timeout_s() == 9.0
    with pytest.raises(ValueError, match=">= 0"):
        dphost.configure_channel(stall_timeout=-1.0)
    with pytest.raises(ValueError, match=">= 0"):
        dphost.configure_channel(heartbeat=-0.5)


def test_reconnect_delay_seeded_by_fault_plan():
    """Under an active plan the reconnect jitter derives from the plan
    seed: chaos runs replay with identical timing."""
    faults.install(faults.parse_plan("seed=42;row.decode:error:p=0"))
    a = [dphost._reconnect_delay(k, 1) for k in range(4)]
    b = [dphost._reconnect_delay(k, 1) for k in range(4)]
    assert a == b
    faults.install(faults.parse_plan("seed=43;row.decode:error:p=0"))
    c = [dphost._reconnect_delay(k, 1) for k in range(4)]
    assert a != c
    faults.clear()
    for k, v in enumerate(a):
        base = min(0.25 * 2.0 ** k, 5.0)
        assert 0.5 * base <= v < 1.5 * base
    # no plan: still bounded (random jitter)
    d = dphost._reconnect_delay(2, 1)
    assert 0.5 <= d < 1.5


def test_serve_resume_round_port_busy_returns_false(monkeypatch):
    """The busy-port path is a bounded, LOGGED failure now, not a
    silent return: after the bind retries it reports False so the
    caller can record a dp_resume_round_unserved event."""
    monkeypatch.setenv("SUTRO_DP_RESUME_BIND_RETRIES", "2")
    port = _free_port()
    blocker = socket.create_server(("127.0.0.1", port))
    try:
        cw = DPWorld(rank=0, world=2, host="127.0.0.1", port=port)
        t0 = time.monotonic()
        served = serve_resume_round(cw, job_key="k", done_rows={0})
        assert served is False
        assert time.monotonic() - t0 < 10
    finally:
        blocker.close()


def test_requeue_limit_fails_round_resumably():
    """A row that exceeds SUTRO_DP_REQUEUE_LIMIT requeues (it kills
    every host it lands on) turns the round into a resumable failure
    instead of an infinite heal loop."""
    est = dphost._ElasticState.build(
        _reqs(8), set(), shard_requests(_reqs(8), 0, 2),
        DPWorld(rank=0, world=2, host="", port=0),
        steal_after=180.0, join_grace=60.0, requeue_limit=1, now=0.0,
    )
    for _ in range(3):
        rank, rows, _evts = est.admit(1, True)
        assert rows == {1, 3, 5, 7} - est.done
        evts = est.release(1, "worker connection lost")
        assert evts and evts[0]["event"] == "dp_rows_requeued"
        # re-admission drains pending back to the rank
        est.rank_rows[1] = set(est.pending)
        est.pending.clear()
    assert est.fatal is not None
    assert "requeued more than 1" in est.fatal


def test_elastic_state_first_result_wins_and_drain_not_counted():
    est = dphost._ElasticState.build(
        _reqs(8), {0}, shard_requests(_reqs(8), 0, 2),
        DPWorld(rank=0, world=2, host="", port=0),
        steal_after=180.0, join_grace=60.0, requeue_limit=3, now=0.0,
    )
    est.admit(1, True)
    assert est.on_res(1, 1, False) is True
    assert est.on_res(0, 1, False) is False  # duplicate dropped
    assert est.dup_dropped == 1
    # cancelled results merge (later-wins store) but never mark done
    assert est.on_res(1, 3, True) is True
    assert 3 not in est.done
    evts = est.drain(1, [3, 5, 7])
    assert any(e["event"] == "dp_preempt_drain" for e in evts)
    assert est.requeue_count == {}  # drain is not counted
    assert {3, 5, 7} <= est.pending
