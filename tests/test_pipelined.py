"""Pipelined fused-window decode (scheduler lookahead) correctness.

The pipelined path dispatches window k+1 off window k's device-resident
tokens before window k's results reach the host. For greedy decoding the
sampled tokens are rng-independent, so every row's output must be
IDENTICAL to the synchronous (lookahead=1) path — including across slot
reuse (rows finishing mid-pipeline and new rows admitted into their
slots) and constrained rows forcing a mid-job drain.
"""

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
from sutro_tpu.engine.tokenizer import ByteTokenizer
from sutro_tpu.models.configs import MODEL_CONFIGS


def _run(lookahead: int, reqs_fn, batch=2, multi=4, **ecfg_kw):
    mcfg = MODEL_CONFIGS["tiny-dense"]
    kw = dict(
        kv_page_size=8,
        max_pages_per_seq=8,
        decode_batch_size=batch,
        max_model_len=64,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=multi,
        decode_lookahead=lookahead,
    )
    kw.update(ecfg_kw)
    ecfg = EngineConfig(**kw)
    tok = ByteTokenizer(vocab_size=mcfg.vocab_size)
    b = ContinuousBatcher(ModelRunner(mcfg, ecfg), stop_ids=tok.stop_ids())
    res = {}
    status = b.run(reqs_fn(tok), on_result=lambda r: res.__setitem__(r.row_id, r))
    assert status == "completed"
    return res


def _greedy_reqs(tok, texts, max_new):
    return [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(tok.encode(t), np.int32),
            max_new_tokens=mn,
            temperature=0.0,
        )
        for i, (t, mn) in enumerate(zip(texts, max_new))
    ]


def test_pipelined_matches_sync_greedy():
    texts = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot"]
    # staggered budgets force rows to finish mid-pipeline and slots to be
    # reused while windows for the old occupants are still in flight
    max_new = [5, 17, 9, 23, 7, 13]

    def reqs(tok):
        return _greedy_reqs(tok, texts, max_new)

    sync = _run(1, reqs)
    piped = _run(2, reqs)
    assert set(sync) == set(piped) == set(range(len(texts)))
    for i in sync:
        assert piped[i].token_ids == sync[i].token_ids, f"row {i}"
        assert piped[i].finish_reason == sync[i].finish_reason

    deep = _run(3, reqs)
    for i in sync:
        assert deep[i].token_ids == sync[i].token_ids, f"row {i} (depth 3)"


def test_pipelined_capacity_bounded():
    # tiny page budget: capacity stops lookahead dispatches early and the
    # single-step fallback finishes the tails — outputs must still match
    texts = ["k", "longer prompt here", "mid"]
    max_new = [30, 30, 30]

    def reqs(tok):
        return _greedy_reqs(tok, texts, max_new)

    sync = _run(1, reqs, batch=2, multi=8, max_pages_per_seq=6,
                max_model_len=48)
    piped = _run(2, reqs, batch=2, multi=8, max_pages_per_seq=6,
                 max_model_len=48)
    for i in sync:
        assert piped[i].token_ids == sync[i].token_ids, f"row {i}"


class _PrefixConstraint:
    """Requires the first two tokens to be 65, then anything; complete
    after 4 tokens. Exercises the speculative-window/drain interplay."""

    def __init__(self, vocab):
        self.vocab = vocab
        self.n = 0

    def allowed_tokens(self, remaining=None):
        m = np.ones((self.vocab,), bool)
        if self.n < 2:
            m[:] = False
            m[65] = True
        return m

    def advance(self, token_id):
        self.n += 1

    def is_complete(self):
        return self.n >= 4


def test_pipelined_drains_for_constrained_rows():
    # unconstrained rows start a pipeline; a constrained row arriving in
    # a later admission forces a drain, then the speculative/masked path
    # runs — everything must still complete with correct budgets
    def reqs(tok):
        rs = _greedy_reqs(
            tok, ["aaa", "bbb", "ccc", "ddd"], [12, 12, 12, 12]
        )
        rs.append(
            GenRequest(
                row_id=4,
                prompt_ids=np.array(tok.encode("zz"), np.int32),
                max_new_tokens=8,
                temperature=0.0,
                constraint=_PrefixConstraint(tok.vocab_size),
            )
        )
        return rs

    res = _run(2, reqs)
    assert set(res) == set(range(5))
    for i in range(4):
        assert len(res[i].token_ids) <= 12
    r4 = res[4]
    assert r4.token_ids[:2] == [65, 65]
    assert r4.finish_reason in ("schema_complete", "stop", "length")


def test_pipelined_sampled_smoke():
    # non-greedy rows still complete with the right budgets (token
    # equality is not required: rng key order differs by pipelining)
    def reqs(tok):
        return [
            GenRequest(
                row_id=i,
                prompt_ids=np.array(tok.encode(t), np.int32),
                max_new_tokens=10,
                temperature=0.8,
            )
            for i, t in enumerate(["one", "two", "three"])
        ]

    res = _run(2, reqs)
    assert set(res) == {0, 1, 2}
    for r in res.values():
        assert 0 < len(r.token_ids) <= 10
