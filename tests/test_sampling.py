"""Sampling op: greedy, top-k/top-p filters, constrained-vocabulary masks."""

import jax
import jax.numpy as jnp
import numpy as np

from sutro_tpu.ops.sampling import cumulative_logprob, sample


def _logits():
    # row 0: peaked at 3; row 1: flat-ish with max at 0
    return jnp.asarray(
        [[0.0, 1.0, 2.0, 10.0, -1.0], [3.0, 2.9, 2.8, 2.7, 2.6]], jnp.float32
    )


def test_greedy():
    toks = sample(
        _logits(), jax.random.PRNGKey(0), temperature=0.0, top_p=1.0
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_top_k_one_is_greedy():
    toks = sample(
        _logits(),
        jax.random.PRNGKey(7),
        temperature=1.0,
        top_p=1.0,
        top_k=jnp.array([1, 1], jnp.int32),
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_top_p_tiny_is_greedy():
    toks = sample(
        _logits(), jax.random.PRNGKey(3), temperature=1.0, top_p=1e-6
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_per_row_top_k():
    # row 0: k=1 (greedy); row 1: k=0 (disabled) — both valid samples
    toks = sample(
        _logits(),
        jax.random.PRNGKey(5),
        temperature=1.0,
        top_p=1.0,
        top_k=jnp.array([1, 0], jnp.int32),
    )
    t = np.asarray(toks)
    assert t[0] == 3
    assert 0 <= t[1] < 5


def test_allowed_mask_constrains():
    allowed = jnp.asarray(
        [[False, True, False, False, False], [True, True, False, False, False]]
    )
    for seed in range(5):
        toks = sample(
            _logits(),
            jax.random.PRNGKey(seed),
            temperature=1.0,
            top_p=1.0,
            allowed=allowed,
        )
        t = np.asarray(toks)
        assert t[0] == 1
        assert t[1] in (0, 1)


def test_cumulative_logprob_matches_softmax():
    logits = _logits()
    tok = jnp.array([3, 0], jnp.int32)
    lp = np.asarray(cumulative_logprob(logits, tok))
    ref = np.log(
        np.exp(np.asarray(logits))
        / np.exp(np.asarray(logits)).sum(-1, keepdims=True)
    )
    np.testing.assert_allclose(lp, ref[[0, 1], [3, 0]], rtol=1e-4, atol=1e-6)


def test_top_k_above_cap_clamps_not_disables():
    """top_k > NUCLEUS_CAP must clamp to the cap-wide head, not fall back
    to full-vocab sampling (code-review regression)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sutro_tpu.ops.sampling import NUCLEUS_CAP, sample

    V = NUCLEUS_CAP * 4
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((1, V)), jnp.float32)
    head = set(
        np.asarray(jax.lax.top_k(logits, NUCLEUS_CAP)[1][0]).tolist()
    )
    for i in range(20):
        tok = sample(
            logits,
            jax.random.PRNGKey(i),
            temperature=jnp.float32(5.0),  # near-uniform: tail very likely
            top_p=jnp.float32(1.0),
            top_k=jnp.int32(V),  # "keep everything" — clamps to cap
        )
        assert int(tok[0]) in head
