"""Sampling op: greedy, top-k/top-p filters, constrained-vocabulary masks."""

import jax
import jax.numpy as jnp
import numpy as np

from sutro_tpu.ops.sampling import cumulative_logprob, sample


def _logits():
    # row 0: peaked at 3; row 1: flat-ish with max at 0
    return jnp.asarray(
        [[0.0, 1.0, 2.0, 10.0, -1.0], [3.0, 2.9, 2.8, 2.7, 2.6]], jnp.float32
    )


def test_greedy():
    toks = sample(
        _logits(), jax.random.PRNGKey(0), temperature=0.0, top_p=1.0
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_top_k_one_is_greedy():
    toks = sample(
        _logits(),
        jax.random.PRNGKey(7),
        temperature=1.0,
        top_p=1.0,
        top_k=jnp.array([1, 1], jnp.int32),
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_top_p_tiny_is_greedy():
    toks = sample(
        _logits(), jax.random.PRNGKey(3), temperature=1.0, top_p=1e-6
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_per_row_top_k():
    # row 0: k=1 (greedy); row 1: k=0 (disabled) — both valid samples
    toks = sample(
        _logits(),
        jax.random.PRNGKey(5),
        temperature=1.0,
        top_p=1.0,
        top_k=jnp.array([1, 0], jnp.int32),
    )
    t = np.asarray(toks)
    assert t[0] == 3
    assert 0 <= t[1] < 5


def test_allowed_mask_constrains():
    allowed = jnp.asarray(
        [[False, True, False, False, False], [True, True, False, False, False]]
    )
    for seed in range(5):
        toks = sample(
            _logits(),
            jax.random.PRNGKey(seed),
            temperature=1.0,
            top_p=1.0,
            allowed=allowed,
        )
        t = np.asarray(toks)
        assert t[0] == 1
        assert t[1] in (0, 1)


def test_cumulative_logprob_matches_softmax():
    logits = _logits()
    tok = jnp.array([3, 0], jnp.int32)
    lp = np.asarray(cumulative_logprob(logits, tok))
    ref = np.log(
        np.exp(np.asarray(logits))
        / np.exp(np.asarray(logits)).sum(-1, keepdims=True)
    )
    np.testing.assert_allclose(lp, ref[[0, 1], [3, 0]], rtol=1e-4, atol=1e-6)


def test_top_k_above_cap_clamps_not_disables():
    """top_k > NUCLEUS_CAP must clamp to the cap-wide head, not fall back
    to full-vocab sampling (code-review regression)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sutro_tpu.ops.sampling import NUCLEUS_CAP, sample

    V = NUCLEUS_CAP * 4
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((1, V)), jnp.float32)
    head = set(
        np.asarray(jax.lax.top_k(logits, NUCLEUS_CAP)[1][0]).tolist()
    )
    for i in range(20):
        tok = sample(
            logits,
            jax.random.PRNGKey(i),
            temperature=jnp.float32(5.0),  # near-uniform: tail very likely
            top_p=jnp.float32(1.0),
            top_k=jnp.int32(V),  # "keep everything" — clamps to cap
        )
        assert int(tok[0]) in head


def test_apply_penalties_math():
    """Repetition/presence/frequency against a hand-computed reference.
    Repetition scope covers prompt+output (seen_rep); presence and
    frequency derive from the generated-token counts only."""
    import jax.numpy as jnp

    from sutro_tpu.ops.sampling import apply_penalties

    logits = jnp.asarray([[2.0, -1.0, 0.5, 3.0]])
    # token 2 was in the PROMPT only: repetition applies, presence/
    # frequency (generated scope) do not
    seen_rep = jnp.asarray([[True, True, True, False]])
    ids_p = jnp.asarray([[0, 1, -1]], jnp.int32)
    cnt_p = jnp.asarray([[3.0, 1.0, 0.0]])
    out = apply_penalties(
        logits, seen_rep, ids_p, cnt_p,
        presence=jnp.asarray([0.5]),
        frequency=jnp.asarray([0.25]),
        repetition=jnp.asarray([2.0]),
    )
    out = np.asarray(out[0])
    # tok0: 2.0/2 (rep) - 0.5 (presence) - 0.25*3 (freq) = -0.25
    # tok1: -1*2 (rep) - 0.5 - 0.25*1 = -2.75
    # tok2: 0.5/2 (rep only, prompt token) = 0.25
    # tok3: unseen, untouched
    np.testing.assert_allclose(out, [-0.25, -2.75, 0.25, 3.0], atol=1e-6)


def test_repetition_penalty_changes_greedy_choice():
    """Penalized logits flip the greedy argmax away from a seen token."""
    from sutro_tpu.ops.sampling import apply_penalties, sample

    B, V = 2, 16
    logits = np.zeros((B, V), np.float32)
    logits[:, 3] = 5.0   # dominant token
    logits[:, 7] = 4.0   # runner-up
    seen = np.zeros((B, V), bool)
    seen[0, 3] = True    # row 0 already emitted token 3
    ids_p = np.full((B, 4), -1, np.int32)
    cnt_p = np.zeros((B, 4), np.float32)
    ids_p[0, 0] = 3
    cnt_p[0, 0] = 1.0
    pen = apply_penalties(
        jnp.asarray(logits), jnp.asarray(seen),
        jnp.asarray(ids_p), jnp.asarray(cnt_p),
        presence=jnp.zeros(B), frequency=jnp.zeros(B),
        repetition=jnp.full(B, 3.0),
    )
    toks = np.asarray(
        sample(
            pen, jax.random.PRNGKey(0),
            temperature=np.zeros(B, np.float32),
            top_p=np.ones(B, np.float32),
        )
    )
    assert toks[0] == 7   # 5/3 < 4: penalty flips the choice
    assert toks[1] == 3   # row 1 unpenalized
