"""Sampling op: greedy, top-k/top-p filters, constrained-vocabulary masks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sutro_tpu.ops.sampling import apply_penalties, cumulative_logprob, sample


def _logits():
    # row 0: peaked at 3; row 1: flat-ish with max at 0
    return jnp.asarray(
        [[0.0, 1.0, 2.0, 10.0, -1.0], [3.0, 2.9, 2.8, 2.7, 2.6]], jnp.float32
    )


def test_greedy():
    toks = sample(
        _logits(), jax.random.PRNGKey(0), temperature=0.0, top_p=1.0
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_top_k_one_is_greedy():
    toks = sample(
        _logits(),
        jax.random.PRNGKey(7),
        temperature=1.0,
        top_p=1.0,
        top_k=jnp.array([1, 1], jnp.int32),
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_top_p_tiny_is_greedy():
    toks = sample(
        _logits(), jax.random.PRNGKey(3), temperature=1.0, top_p=1e-6
    )
    assert list(np.asarray(toks)) == [3, 0]


def test_per_row_top_k():
    # row 0: k=1 (greedy); row 1: k=0 (disabled) — both valid samples
    toks = sample(
        _logits(),
        jax.random.PRNGKey(5),
        temperature=1.0,
        top_p=1.0,
        top_k=jnp.array([1, 0], jnp.int32),
    )
    t = np.asarray(toks)
    assert t[0] == 3
    assert 0 <= t[1] < 5


def test_allowed_mask_constrains():
    allowed = jnp.asarray(
        [[False, True, False, False, False], [True, True, False, False, False]]
    )
    for seed in range(5):
        toks = sample(
            _logits(),
            jax.random.PRNGKey(seed),
            temperature=1.0,
            top_p=1.0,
            allowed=allowed,
        )
        t = np.asarray(toks)
        assert t[0] == 1
        assert t[1] in (0, 1)


def test_cumulative_logprob_matches_softmax():
    logits = _logits()
    tok = jnp.array([3, 0], jnp.int32)
    lp = np.asarray(cumulative_logprob(logits, tok))
    ref = np.log(
        np.exp(np.asarray(logits))
        / np.exp(np.asarray(logits)).sum(-1, keepdims=True)
    )
    np.testing.assert_allclose(lp, ref[[0, 1], [3, 0]], rtol=1e-4, atol=1e-6)


def test_top_k_above_cap_clamps_not_disables():
    """top_k > NUCLEUS_CAP must clamp to the cap-wide head, not fall back
    to full-vocab sampling (code-review regression)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sutro_tpu.ops.sampling import NUCLEUS_CAP, sample

    V = NUCLEUS_CAP * 4
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((1, V)), jnp.float32)
    head = set(
        np.asarray(jax.lax.top_k(logits, NUCLEUS_CAP)[1][0]).tolist()
    )
    for i in range(20):
        tok = sample(
            logits,
            jax.random.PRNGKey(i),
            temperature=jnp.float32(5.0),  # near-uniform: tail very likely
            top_p=jnp.float32(1.0),
            top_k=jnp.int32(V),  # "keep everything" — clamps to cap
        )
        assert int(tok[0]) in head


def test_apply_penalties_math():
    """Repetition/presence/frequency against a hand-computed reference.
    Repetition scope covers prompt+output (seen_rep); presence and
    frequency derive from the generated-token counts only."""
    import jax.numpy as jnp

    from sutro_tpu.ops.sampling import apply_penalties

    logits = jnp.asarray([[2.0, -1.0, 0.5, 3.0]])
    # token 2 was in the PROMPT only: repetition applies, presence/
    # frequency (generated scope) do not
    seen_rep = jnp.asarray([[True, True, True, False]])
    ids_p = jnp.asarray([[0, 1, -1]], jnp.int32)
    cnt_p = jnp.asarray([[3.0, 1.0, 0.0]])
    out = apply_penalties(
        logits, seen_rep, ids_p, cnt_p,
        presence=jnp.asarray([0.5]),
        frequency=jnp.asarray([0.25]),
        repetition=jnp.asarray([2.0]),
    )
    out = np.asarray(out[0])
    # tok0: 2.0/2 (rep) - 0.5 (presence) - 0.25*3 (freq) = -0.25
    # tok1: -1*2 (rep) - 0.5 - 0.25*1 = -2.75
    # tok2: 0.5/2 (rep only, prompt token) = 0.25
    # tok3: unseen, untouched
    np.testing.assert_allclose(out, [-0.25, -2.75, 0.25, 3.0], atol=1e-6)


def test_repetition_penalty_changes_greedy_choice():
    """Penalized logits flip the greedy argmax away from a seen token."""
    from sutro_tpu.ops.sampling import apply_penalties, sample

    B, V = 2, 16
    logits = np.zeros((B, V), np.float32)
    logits[:, 3] = 5.0   # dominant token
    logits[:, 7] = 4.0   # runner-up
    seen = np.zeros((B, V), bool)
    seen[0, 3] = True    # row 0 already emitted token 3
    ids_p = np.full((B, 4), -1, np.int32)
    cnt_p = np.zeros((B, 4), np.float32)
    ids_p[0, 0] = 3
    cnt_p[0, 0] = 1.0
    pen = apply_penalties(
        jnp.asarray(logits), jnp.asarray(seen),
        jnp.asarray(ids_p), jnp.asarray(cnt_p),
        presence=jnp.zeros(B), frequency=jnp.zeros(B),
        repetition=jnp.full(B, 3.0),
    )
    toks = np.asarray(
        sample(
            pen, jax.random.PRNGKey(0),
            temperature=np.zeros(B, np.float32),
            top_p=np.ones(B, np.float32),
        )
    )
    assert toks[0] == 7   # 5/3 < 4: penalty flips the choice
    assert toks[1] == 3   # row 1 unpenalized


def test_bfloat16_logits_supported():
    """bf16 logits (SUTRO_LOGITS_BF16 head) sample correctly: greedy
    matches f32 for separated logits, masks still bind, and the logprob
    accumulates in f32 (no bf16 drift over the vocab)."""
    B, V = 4, 512
    rng = np.random.default_rng(0)
    logits32 = jnp.asarray(
        rng.normal(0, 2, (B, V)).astype(np.float32)
    )
    # separate the argmax by a margin far above bf16 resolution
    logits32 = logits32.at[jnp.arange(B), jnp.arange(B) + 7].add(10.0)
    logits16 = logits32.astype(jnp.bfloat16)

    g32 = sample(
        logits32, jax.random.PRNGKey(1),
        temperature=np.zeros(B, np.float32),
        top_p=np.ones(B, np.float32),
    )
    g16 = sample(
        logits16, jax.random.PRNGKey(1),
        temperature=np.zeros(B, np.float32),
        top_p=np.ones(B, np.float32),
    )
    np.testing.assert_array_equal(np.asarray(g32), np.asarray(g16))

    # constrained mask binds in bf16 too
    allowed = np.zeros((B, V), bool)
    allowed[:, 11] = True
    t16 = sample(
        logits16, jax.random.PRNGKey(2),
        temperature=np.full(B, 1.0, np.float32),
        top_p=np.ones(B, np.float32),
        allowed=jnp.asarray(allowed),
    )
    assert np.all(np.asarray(t16) == 11)

    # logprob: f32 accumulation keeps bf16 within bf16 input precision
    lp32 = np.asarray(cumulative_logprob(logits32, g32))
    lp16 = np.asarray(cumulative_logprob(logits16, g16))
    np.testing.assert_allclose(lp16, lp32, atol=0.05, rtol=0.02)


@pytest.mark.slow  # 4000-draw statistical leg; the bf16 sampling path
# itself is pinned fast by test_bfloat16_logits_supported
def test_bfloat16_sampled_distribution_close():
    """Stochastic sampling from bf16 logits matches the f32 categorical
    distribution (chi-square-ish tolerance over many draws)."""
    V = 16
    logits = jnp.asarray(
        np.array([np.linspace(0, 3, V)], dtype=np.float32)
    )
    l16 = logits.astype(jnp.bfloat16)
    n = 4000
    counts = np.zeros(V)
    for i in range(n // 50):
        toks = sample(
            jnp.broadcast_to(l16, (50, V)), jax.random.PRNGKey(i),
            temperature=np.ones(50, np.float32),
            top_p=np.ones(50, np.float32),
        )
        for t in np.asarray(toks):
            counts[t] += 1
    p = np.exp(np.asarray(logits[0]))
    p /= p.sum()
    # every high-probability bucket within 30% relative
    big = p > 0.05
    np.testing.assert_allclose(
        counts[big] / n, p[big], rtol=0.3
    )


def test_logits_bf16_flag_plumbs_through_head(monkeypatch):
    """SUTRO_LOGITS_BF16=1 must actually change head_apply's output
    dtype — the other bf16 tests build arrays by hand and would keep
    passing if the env-flag branch regressed."""
    from sutro_tpu.models import transformer
    from sutro_tpu.models.configs import MODEL_CONFIGS

    cfg = MODEL_CONFIGS["tiny-dense"]
    params = transformer.init_params(
        cfg, jax.random.PRNGKey(0), jnp.bfloat16
    )
    h = jnp.zeros((1, 4, cfg.hidden_size), jnp.bfloat16)
    vlen = jnp.full((1,), 4, jnp.int32)

    monkeypatch.delenv("SUTRO_LOGITS_BF16", raising=False)
    out32, _ = transformer.head_apply(cfg, params, h, vlen)
    assert out32.dtype == jnp.float32

    monkeypatch.setenv("SUTRO_LOGITS_BF16", "1")
    out16, _ = transformer.head_apply(cfg, params, h, vlen)
    assert out16.dtype == jnp.bfloat16


def test_apply_penalties_preserves_dtype():
    """bf16 logits stay bf16 through the penalties path (the bandwidth
    saving must not silently evaporate for penalized rows)."""
    B, V = 2, 32
    logits = jnp.zeros((B, V), jnp.bfloat16)
    seen = jnp.zeros((B, V), bool)
    ids_p = jnp.full((B, 4), -1, jnp.int32)
    cnt_p = jnp.zeros((B, 4), jnp.float32)
    out = apply_penalties(
        logits, seen, ids_p, cnt_p,
        presence=jnp.full((B,), 0.5, jnp.float32),
        frequency=jnp.full((B,), 0.5, jnp.float32),
        repetition=jnp.full((B,), 1.2, jnp.float32),
    )
    assert out.dtype == jnp.bfloat16
