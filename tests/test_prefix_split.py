"""Hydragen-style split decode over the shared prefix
(EngineConfig.prefix_split + ops/pallas_paged.prefix_attention_carry +
paged-kernel carry injection).

Op-level parity lives in tests/test_pallas_kernels.py
(test_paged_decode_prefix_carry_injection). Here the FULL engine path
runs with the real Pallas kernels in interpret mode on CPU: prefix
cache detection -> split operands (_split_pfx) -> carry injection in
every decode dispatch — outputs must match the same engine with the
split disabled, and the carry helper must actually have been used."""

import functools

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
from sutro_tpu.models.configs import MODEL_CONFIGS

PREFIX = "system: classify the review. review: "  # 37 chars -> 4 pages
SUFFIXES = ["good stuff", "bad stuff", "meh", "ok product arrived"]


def _force_interpret(monkeypatch):
    """Run the engine's Pallas path on CPU: kernels in interpret mode,
    shape gates opened (tiny test heads fail the TPU-lane gates)."""
    from sutro_tpu.ops import pallas_kv, pallas_paged

    monkeypatch.setattr(
        pallas_paged, "paged_decode_supported", lambda *a: True
    )
    monkeypatch.setattr(
        pallas_paged,
        "paged_decode_attention",
        functools.partial(
            pallas_paged.paged_decode_attention, interpret=True
        ),
    )
    monkeypatch.setattr(
        pallas_kv,
        "kv_write_pallas",
        functools.partial(pallas_kv.kv_write_pallas, interpret=True),
    )
    from sutro_tpu.ops import pallas_flash

    monkeypatch.setattr(
        pallas_flash, "flash_prefill_supported", lambda *a, **k: False
    )


def _run(tok, split: bool, monkeypatch):
    _force_interpret(monkeypatch)
    ecfg = EngineConfig(
        kv_page_size=8,
        max_pages_per_seq=10,
        max_model_len=80,
        decode_batch_size=4,
        use_pallas=True,
        param_dtype="float32",
        activation_dtype="float32",
        decode_multi_step=1,
        decode_lookahead=1,
        prefix_split=split,
    )
    b = ContinuousBatcher(
        ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg),
        stop_ids=tok.stop_ids(),
    )
    res = {}
    out = b.run(
        [
            GenRequest(
                row_id=i,
                prompt_ids=np.array(tok.encode(PREFIX + s), np.int32),
                max_new_tokens=5,
                temperature=0.0,
            )
            for i, s in enumerate(SUFFIXES)
        ],
        on_result=lambda r: res.__setitem__(r.row_id, r),
    )
    assert out == "completed"
    # the job's shared prefix must have been detected (split operands
    # exist only when ctx.prefix does)
    naive = sum(len(tok.encode(PREFIX + s)) for s in SUFFIXES)
    assert b.prefill_tokens < naive
    return {i: r.token_ids for i, r in res.items()}


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
def test_two_prefix_groups_cobatched(byte_tok, monkeypatch):
    """Two templated jobs with DIFFERENT shared prefixes co-batched:
    each gets its own carry group (disjoint member sets combine by
    max/sum/sum), and outputs stay identical to the unsplit kernel."""
    from sutro_tpu.engine.scheduler import JobCtx

    _force_interpret(monkeypatch)
    tok = byte_tok
    PFX2 = "system: extract the named entity. text: "

    def run(split):
        ecfg = EngineConfig(
            kv_page_size=8,
            max_pages_per_seq=10,
            max_model_len=80,
            decode_batch_size=4,
            use_pallas=True,
            param_dtype="float32",
            activation_dtype="float32",
            decode_multi_step=1,
            decode_lookahead=1,
            prefix_split=split,
        )
        b = ContinuousBatcher(
            ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg),
            stop_ids=tok.stop_ids(),
        )

        def reqs(texts, base):
            return [
                GenRequest(
                    row_id=base + i,
                    prompt_ids=np.array(tok.encode(t), np.int32),
                    max_new_tokens=4,
                    temperature=0.0,
                )
                for i, t in enumerate(texts)
            ]

        ga, gb = {}, {}
        st = b.run_multi(
            [
                JobCtx(
                    job_id="A",
                    pending=reqs([PREFIX + s for s in SUFFIXES[:2]], 0),
                    on_result=lambda r: ga.__setitem__(r.row_id, r),
                    priority=1,
                    seq=0,
                ),
                JobCtx(
                    job_id="B",
                    pending=reqs([PFX2 + s for s in ("alpha", "beta")], 100),
                    on_result=lambda r: gb.__setitem__(r.row_id, r),
                    priority=1,
                    seq=1,
                ),
            ],
            on_job_done=lambda c, o: None,
        )
        assert st == "completed"
        return (
            {i: r.token_ids for i, r in ga.items()},
            {i: r.token_ids for i, r in gb.items()},
        )

    on_a, on_b = run(True)
    off_a, off_b = run(False)
    assert on_a == off_a and on_b == off_b


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
def test_engine_split_decode_matches_unsplit(byte_tok, monkeypatch):
    from sutro_tpu.ops import pallas_paged

    calls = []
    real = pallas_paged.prefix_attention_carry

    def record(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(
        pallas_paged, "prefix_attention_carry", record
    )
    on = _run(byte_tok, True, monkeypatch)
    assert calls, "split decode never used the carry helper"
    n_split = len(calls)
    calls.clear()
    off = _run(byte_tok, False, monkeypatch)
    assert not calls, "carry helper ran with prefix_split disabled"
    assert on == off, "split decode changed greedy outputs"
    # the carry is traced once per jit compilation (it sits inside the
    # layer lax.scan, and later dispatches reuse the compiled program),
    # so call COUNT is compilation count — n_split >= 1 is the signal
    assert n_split >= 1


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
def test_engine_split_decode_in_place_kernel(byte_tok, monkeypatch):
    """Same engine path, but with the IN-PLACE prefix-carry kernel
    (page-indexed BlockSpecs over the pool) forced on: the shape gate
    is opened for the tiny test heads and the kernel runs in interpret
    mode — outputs must still match the unsplit engine and the pallas
    carry (not the XLA gather) must have been dispatched."""
    from sutro_tpu.ops import pallas_paged

    calls = []
    real = pallas_paged.prefix_attention_carry_pallas

    def record(*a, **kw):
        calls.append(1)
        kw["interpret"] = True
        return real(*a, **kw)

    monkeypatch.setattr(
        pallas_paged, "prefix_carry_supported", lambda *a, **k: True
    )
    monkeypatch.setattr(
        pallas_paged, "prefix_attention_carry_pallas", record
    )
    on = _run(byte_tok, True, monkeypatch)
    assert calls, "split decode never used the in-place carry kernel"
    calls.clear()
    off = _run(byte_tok, False, monkeypatch)
    assert not calls, "carry kernel ran with prefix_split disabled"
    assert on == off, "in-place split decode changed greedy outputs"
