"""FSM fast-forward ("jump decoding"): scaffold regions where the
schema forces exactly one next token are peeled host-side and committed
through ONE parallel verify forward (runner.verify_greedy) instead of
step-by-step speculative windows that reject their unmasked samples
there. Exactness contract: token_ids and finish_reason identical to
the every-step-masked path (decode_multi_step=1) AND to the
speculative-window path with fast-forward disabled."""

import dataclasses
import json

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.constrain import schema_constraint_factory
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
from sutro_tpu.models.configs import MODEL_CONFIGS

# scaffold-heavy: long const-ish required keys, enum leaves — most of
# the output is FSM-forced
SCHEMA = {
    "type": "object",
    "properties": {
        "classification_result": {
            "type": "string",
            "enum": ["positive", "negative"],
        },
        "confidence_level": {
            "type": "string",
            "enum": ["high", "low"],
        },
    },
    "required": ["classification_result", "confidence_level"],
}


def _run(byte_tok, multi, ff, texts=None, extra_plain=0):
    ecfg = EngineConfig(
        kv_page_size=8,
        max_pages_per_seq=32,
        max_model_len=256,
        decode_batch_size=4,
        use_pallas=False,
        param_dtype="float32",
        activation_dtype="float32",
        decode_multi_step=multi,
        constrain_fastforward=ff,
    )
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
    factory = schema_constraint_factory(SCHEMA, byte_tok)
    texts = texts or ["first row", "second", "third one"]
    reqs = [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(byte_tok.encode(t), np.int32),
            max_new_tokens=80,
            temperature=0.0,
            constraint=factory(),
        )
        for i, t in enumerate(texts)
    ]
    for j in range(extra_plain):  # unconstrained greedy riders
        reqs.append(
            GenRequest(
                row_id=100 + j,
                prompt_ids=np.array(
                    byte_tok.encode(f"plain rider {j}"), np.int32
                ),
                max_new_tokens=12,
                temperature=0.0,
            )
        )
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    res = {}
    assert (
        b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
        == "completed"
    )
    return b, {
        i: (tuple(r.token_ids), r.finish_reason) for i, r in res.items()
    }


def test_fastforward_exact_vs_masked_and_window(byte_tok):
    b_ff, ff = _run(byte_tok, 8, 16)
    assert b_ff.ff_forced > 0, "scaffold schema never fast-forwarded"
    _, masked = _run(byte_tok, 1, 0)
    _, window = _run(byte_tok, 8, 0)
    assert ff == masked
    assert ff == window
    # outputs are complete schema-valid JSON
    for toks, _ in ff.values():
        parsed = json.loads(byte_tok.decode(list(toks)))
        assert parsed["classification_result"] in (
            "positive", "negative",
        )
        assert parsed["confidence_level"] in ("high", "low")


def test_const_schema_needs_zero_windows(byte_tok, monkeypatch):
    """A fully-forced schema (const) commits its entire output through
    fast-forward verifies: ZERO speculative-window dispatches — the
    strongest contrast with the per-row rejection recovery the window
    path needs for the same schema."""
    from sutro_tpu.engine.runner import ModelRunner as MR

    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=32, max_model_len=256,
        decode_batch_size=4, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", decode_multi_step=8,
        constrain_fastforward=16,
    )
    runner = MR(MODEL_CONFIGS["tiny-dense"], ecfg)
    calls = {"window": 0}
    orig = runner.decode_window

    def window(*a, **kw):
        calls["window"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(runner, "decode_window", window)
    factory = schema_constraint_factory(
        {"const": "zqxzqxzqxzqx"}, byte_tok
    )
    b = ContinuousBatcher(
        runner, stop_ids=byte_tok.stop_ids(),
        token_bytes=byte_tok.token_bytes,
    )
    res = {}
    assert (
        b.run(
            [
                GenRequest(
                    row_id=0,
                    prompt_ids=np.array(
                        byte_tok.encode("adv"), np.int32
                    ),
                    max_new_tokens=40,
                    temperature=0.0,
                    constraint=factory(),
                )
            ],
            on_result=lambda r: res.__setitem__(r.row_id, r),
        )
        == "completed"
    )
    out = b"".join(byte_tok.token_bytes(t) for t in res[0].token_ids)
    assert json.loads(out.decode()) == "zqxzqxzqxzqx"
    assert res[0].finish_reason == "schema_complete"
    assert calls["window"] == 0, calls
    assert b.ff_forced >= 10


def test_fastforward_with_unconstrained_riders(byte_tok):
    """Greedy unconstrained rows ride the verify dispatch as
    draft_len-0 plain greedy steps — their outputs must equal a run
    with fast-forward off."""
    b_ff, ff = _run(byte_tok, 8, 16, extra_plain=1)
    assert b_ff.ff_forced > 0
    _, off = _run(byte_tok, 8, 0, extra_plain=1)
    assert ff == off
    assert any(i >= 100 for i in ff)  # the rider completed


def test_fastforward_respects_budget_cap(byte_tok):
    """A tight max_new_tokens still yields complete JSON (the peel
    honors the budget-aware closure masks step by step)."""
    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=32, max_model_len=256,
        decode_batch_size=4, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", decode_multi_step=8,
        constrain_fastforward=16,
    )
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
    factory = schema_constraint_factory(SCHEMA, byte_tok)
    c = factory()
    need = c.min_tokens() if hasattr(c, "min_tokens") else 0
    reqs = [
        GenRequest(
            row_id=0,
            prompt_ids=np.array(byte_tok.encode("x"), np.int32),
            max_new_tokens=max(need, 1),  # engine raises to feasible
            temperature=0.0,
            constraint=factory(),
        )
    ]
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    res = {}
    assert (
        b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
        == "completed"
    )
    parsed = json.loads(byte_tok.decode(list(res[0].token_ids)))
    assert parsed["classification_result"] in ("positive", "negative")


def test_mixed_freetext_scaffold_handoff(byte_tok):
    """A schema with a free-text field then enum scaffold exercises the
    window <-> fast-forward handoff: the window samples the string body
    (and its rejections flag rows), fast-forward commits the scaffold
    (flagged SINGLETON rows are candidates — the peel is their masked
    step). Outputs must equal the every-step-masked path exactly."""
    schema = {
        "type": "object",
        "properties": {
            "note": {"type": "string", "maxLength": 20},
            "label": {"type": "string", "enum": ["alpha", "beta"]},
        },
        "required": ["note", "label"],
    }

    def run(multi, ff):
        ecfg = EngineConfig(
            kv_page_size=8, max_pages_per_seq=32, max_model_len=256,
            decode_batch_size=4, use_pallas=False,
            param_dtype="float32", activation_dtype="float32",
            decode_multi_step=multi, constrain_fastforward=ff,
        )
        runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
        factory = schema_constraint_factory(schema, byte_tok)
        reqs = [
            GenRequest(
                row_id=i,
                prompt_ids=np.array(byte_tok.encode(t), np.int32),
                max_new_tokens=80,
                temperature=0.0,
                constraint=factory(),
            )
            for i, t in enumerate(["first row", "second", "third one"])
        ]
        b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
        res = {}
        assert (
            b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
            == "completed"
        )
        return b, {
            i: (tuple(r.token_ids), r.finish_reason)
            for i, r in res.items()
        }

    b_ff, ff = run(8, 16)
    assert b_ff.ff_forced > 0
    _, masked = run(1, 0)
    assert ff == masked
    for toks, _ in ff.values():
        parsed = json.loads(byte_tok.decode(list(toks)))
        assert parsed["label"] in ("alpha", "beta")


class _MergedTok:
    """Synthetic BPE-style tokenizer: byte ids 0..255 + specials (as
    ByteTokenizer) + MERGED multi-byte tokens for scaffold substrings.
    A forced byte path then admits MANY tokenizations (every prefix
    token is mask-legal), which is exactly the real-vocab regime the
    masked-candidate verification handles token-exactly."""

    def __init__(self, vocab_size):
        from sutro_tpu.engine.tokenizer import ByteTokenizer

        self._bt = ByteTokenizer(vocab_size=vocab_size)
        self.vocab_size = vocab_size
        base = 256 + len(self._bt.SPECIALS)
        self.merged = {
            base + 0: b'{"classification_result"',
            base + 1: b'":"',
            base + 2: b"positive",
            base + 3: b"negative",
            base + 4: b'","confidence_level":"',
            base + 5: b'"}',
            base + 6: b"classific",
            base + 7: b"ation_result",
        }
        self.eos_id = self._bt.eos_id

    def encode(self, text):
        return self._bt.encode(text)

    def decode(self, ids):
        return b"".join(self.token_bytes(t) for t in ids).decode(
            errors="replace"
        )

    def token_bytes(self, tid):
        if tid in self.merged:
            return self.merged[tid]
        return self._bt.token_bytes(tid)

    def stop_ids(self):
        return self._bt.stop_ids()


def test_fastforward_bpe_style_merged_vocab(byte_tok):
    """Under a merged (BPE-style) vocab the forced byte path admits
    every prefix tokenization, so masks are NOT singletons — the
    masked-candidate verification must still produce tokens IDENTICAL
    to the every-step-masked path, while committing multi-token jumps
    (ff_forced > 0)."""
    tok = _MergedTok(MODEL_CONFIGS["tiny-dense"].vocab_size)

    def run(multi, ff):
        ecfg = EngineConfig(
            kv_page_size=8, max_pages_per_seq=32, max_model_len=256,
            decode_batch_size=4, use_pallas=False,
            param_dtype="float32", activation_dtype="float32",
            decode_multi_step=multi, constrain_fastforward=ff,
        )
        runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
        factory = schema_constraint_factory(SCHEMA, tok)
        reqs = [
            GenRequest(
                row_id=i,
                prompt_ids=np.array(tok.encode(t), np.int32),
                max_new_tokens=80,
                temperature=0.0,
                constraint=factory(),
            )
            for i, t in enumerate(["first row", "second", "third one"])
        ]
        b = ContinuousBatcher(runner, stop_ids=tok.stop_ids())
        res = {}
        assert (
            b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
            == "completed"
        )
        return b, {
            i: (tuple(r.token_ids), r.finish_reason)
            for i, r in res.items()
        }

    b_ff, ff = run(8, 16)
    assert b_ff.ff_forced > 0, "merged vocab never fast-forwarded"
    _, masked = run(1, 0)
    assert ff == masked, "BPE-style jump diverged from the masked path"
    for toks, _ in ff.values():
        parsed = json.loads(tok.decode(list(toks)))
        assert parsed["classification_result"] in (
            "positive", "negative",
        )


def test_spec_riders_in_fastforward_dispatch(byte_tok):
    """With n-gram speculation opted in, unconstrained greedy riders
    carry their own drafts inside the fast-forward dispatch (verified
    against the plain greedy outputs) — outputs must stay identical to
    a run with both features off, and both counters must move."""

    def run(ff, spec):
        ecfg = EngineConfig(
            kv_page_size=8, max_pages_per_seq=32, max_model_len=256,
            decode_batch_size=4, use_pallas=False,
            param_dtype="float32", activation_dtype="float32",
            decode_multi_step=8, constrain_fastforward=ff,
            spec_ngram_draft=spec,
        )
        runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
        factory = schema_constraint_factory(SCHEMA, byte_tok)
        reqs = [
            GenRequest(
                row_id=i,
                prompt_ids=np.array(byte_tok.encode(t), np.int32),
                max_new_tokens=60,
                temperature=0.0,
                constraint=factory(),
            )
            for i, t in enumerate(["first row", "second"])
        ]
        # echo-heavy unconstrained riders so n-gram drafts fire
        for j, t in enumerate(
            ["abc abc abc abc abc", "the cat sat on the mat the cat"]
        ):
            reqs.append(
                GenRequest(
                    row_id=100 + j,
                    prompt_ids=np.array(byte_tok.encode(t), np.int32),
                    max_new_tokens=24,
                    temperature=0.0,
                )
            )
        b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
        res = {}
        assert (
            b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
            == "completed"
        )
        return b, {
            i: (tuple(r.token_ids), r.finish_reason)
            for i, r in res.items()
        }

    b_on, on = run(16, 6)
    _, off = run(0, 0)
    assert on == off, "spec riders changed outputs"
    assert b_on.ff_forced > 0
    assert b_on.spec_drafted > 0 and b_on.spec_accepted > 0, (
        "rider drafting never engaged in the shared dispatch"
    )
