"""Tokenizer-honest quota enforcement and embedding-job durability
(partial flush + row-granular resume), per SURVEY §5.3/§7.3."""

import json
import time

import numpy as np

from sutro_tpu.interfaces import JobStatus


def _wait_terminal(eng, job_id, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s = eng.job_status(job_id)
        if JobStatus(s).is_terminal():
            return s
        time.sleep(0.05)
    raise TimeoutError(eng.job_status(job_id))


def test_quota_exact_tokens_reject(tiny_ecfg, tmp_path, monkeypatch):
    """A job whose exact token count exceeds the quota is rejected even
    when a crude chars-based heuristic would have passed it. ByteTokenizer
    is 1 token/byte, so multibyte text makes chars//3 undercount ~3x —
    the old heuristic's failure mode."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    (tmp_path / "quotas.json").write_text(
        json.dumps([{"row_quota": 10, "token_quota": 400}])
    )
    from sutro_tpu.engine.api import LocalEngine

    eng = LocalEngine(tiny_ecfg)
    # 3 rows x ~40 CJK chars = ~120 "chars//3 + 1" tokens (old heuristic:
    # passes 400) but ~360 real byte-tokens + 3*64 max_new = >400
    rows = ["漢字" * 20] * 3
    jid = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": rows,
            "sampling_params": {"max_new_tokens": 64},
        }
    )
    assert _wait_terminal(eng, jid) == "FAILED"
    reason = eng.get_job(jid)["failure_reason"]["message"]
    assert "quota" in reason.lower()


def test_quota_small_job_passes_without_exact_count(
    tiny_ecfg, tmp_path, monkeypatch
):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine

    eng = LocalEngine(tiny_ecfg)
    jid = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": ["ok", "fine"],
            "sampling_params": {"max_new_tokens": 4},
        }
    )
    assert _wait_terminal(eng, jid) == "SUCCEEDED"


def test_embedding_job_resumes_from_partial(
    tiny_ecfg, tmp_path, monkeypatch
):
    """Cancel an embedding job mid-run, then resume: completed rows are
    not recomputed (rows_already_done > 0) and the final result carries
    every row."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine

    eng = LocalEngine(tiny_ecfg)
    n = 64
    jid = eng.submit_batch_inference(
        {"model": "tiny-emb", "inputs": [f"text {i}" for i in range(n)]}
    )
    # wait for some batches to complete, then cancel mid-flight
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if eng.metrics.job(jid).rows_completed > 0:
            break
        time.sleep(0.02)
    eng.cancel_job(jid)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        status = eng.job_status(jid)
        if status in ("CANCELLED", "SUCCEEDED", "FAILED"):
            break
        time.sleep(0.05)
    if status == "SUCCEEDED":  # raced to completion: nothing to resume
        return
    assert status == "CANCELLED"

    out = eng.resume_job(jid)
    assert out["resumed"] is True
    assert out["rows_already_done"] > 0
    assert _wait_terminal(eng, jid) == "SUCCEEDED"
    res = eng.job_results(jid)
    assert len(res["outputs"]) == n
    # embeddings are unit-norm vectors
    for v in res["outputs"]:
        assert abs(float(np.linalg.norm(np.asarray(v))) - 1.0) < 1e-3


def test_embedding_mixed_lengths_order_preserved(
    tiny_ecfg, tmp_path, monkeypatch
):
    """Length-sorted batching (multi-batch: 20 rows over batch size 8)
    must not disturb the 1:1 row order — spot rows of distinct lengths
    each match their solo computation."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine

    eng = LocalEngine(tiny_ecfg)
    lengths = [50, 3, 30, 9, 21, 5, 44, 2, 17, 8,
               29, 4, 40, 11, 26, 6, 35, 13, 23, 7]
    texts = ["a" * n + "b" * (i % 3) for i, n in enumerate(lengths)]
    jid = eng.submit_batch_inference(
        {"model": "tiny-emb", "inputs": texts}
    )
    assert _wait_terminal(eng, jid) == "SUCCEEDED"
    res = eng.job_results(jid)
    assert len(res["outputs"]) == len(texts)
    # spot-check rows across the length spectrum (incl. ones that land
    # in different sorted batches) against their solo embeddings
    for probe in (0, 1, 7, 12, 19):
        solo_job = eng.submit_batch_inference(
            {"model": "tiny-emb", "inputs": [texts[probe]]}
        )
        assert _wait_terminal(eng, solo_job) == "SUCCEEDED"
        solo = eng.job_results(solo_job)["outputs"][0]
        np.testing.assert_allclose(
            np.asarray(res["outputs"][probe]), np.asarray(solo),
            atol=2e-4, rtol=2e-4,
        )
