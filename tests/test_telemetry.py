"""Engine telemetry subsystem (sutro_tpu/telemetry, OBSERVABILITY.md).

Covers the three pillars end to end:

1. registry semantics — counters/gauges/histograms, thread-sharded
   writes aggregating exactly, fixed label cardinality, deterministic
   exporters (golden file) and Prometheus-text validity;
2. flight recorder — bounded ring, per-job filtering, dump artifact;
3. the acceptance scenario — a seeded 256-row job with one PR-3
   injected quarantined row produces a dump whose span timeline covers
   every exercised stage and whose counters reconcile EXACTLY with the
   job's results and record, while /metrics parses as Prometheus text.

Plus the PR's satellites: JobMetrics subscriber churn and the
Throughput first-add anchor.
"""

import json
import re
import threading
import time
from pathlib import Path

import pytest

from sutro_tpu import telemetry
from sutro_tpu.engine import faults
from sutro_tpu.engine.api import LocalEngine
from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.metrics import JobMetrics, Throughput
from sutro_tpu.interfaces import JobStatus
from sutro_tpu.telemetry.registry import MetricsRegistry
from sutro_tpu.telemetry.spans import FlightRecorder

GOLDEN = Path(__file__).parent / "data" / "telemetry_export.golden"


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def _golden_registry() -> MetricsRegistry:
    """THE deterministic registry the golden file pins: fixed metrics,
    fixed values, fixed order. Regenerate the golden by running this
    file with --regen-golden (see __main__ below)."""
    r = MetricsRegistry()
    c = r.counter("demo_rows_total", "Rows by outcome",
                  labels=("outcome",))
    c.inc(3, "ok")
    c.inc(1, "quarantined")
    g = r.gauge("demo_tokens_per_second", "Throughput", unit="tokens/s")
    g.set(1234.5)
    h = r.histogram("demo_stage_seconds", "Stage latency",
                    labels=("stage",), buckets=(0.01, 0.1, 1.0))
    h.observe(0.005, "decode")
    h.observe(0.05, "decode")
    h.observe(2.0, "decode")
    return r


def test_exporter_matches_golden():
    assert GOLDEN.exists(), (
        "golden file missing (regen: python tests/test_telemetry.py "
        "--regen-golden)"
    )
    assert _golden_registry().to_prometheus() == GOLDEN.read_text()


# one exposition line: name{labels} value  (labels optional; value is
# an int/float, inf or NaN), optionally followed by an OpenMetrics
# exemplar: ` # {labels} value timestamp`
_LABELSET = (
    r"\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\}"
)
_NUMBER = r"-?\d+(\.\d+)?([eE]-?\d+)?"
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    rf"({_LABELSET})?"
    rf" ({_NUMBER}|\+Inf|-Inf|NaN)"
    rf"( # {_LABELSET} {_NUMBER} {_NUMBER})?$"
)


def assert_valid_prometheus(text: str) -> None:
    """Pure-python prom-text validator (exposition format 0.0.4):
    every line is a comment or a well-formed sample; every sample's
    metric family has HELP+TYPE (HELP before samples); label values
    carry no raw control characters (backslash/quote/newline must be
    escaped); histogram families carry _bucket/_sum/_count. Run over
    both the golden file and live output (satellite: no torn
    exposition under concurrent scrapes)."""
    assert text.endswith("\n")
    helps, types, samples = set(), {}, []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in samples, (
                f"HELP for {name} after its samples"
            )
            helps.add(name)
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            types[parts[2]] = parts[3]
            assert parts[3] in ("counter", "gauge", "histogram")
            continue
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
        # escaping: inside label values, every backslash must open a
        # valid escape and raw quotes/newlines cannot appear (the line
        # regex already rejects raw newlines; check escapes here)
        for lv in re.findall(r'="([^"]*)"', line):
            assert re.fullmatch(
                r'(?:[^\\]|\\\\|\\"|\\n)*', lv
            ), f"bad escaping in label value {lv!r}"
        name = line.split("{")[0].split(" ")[0]
        if " # " in line:
            # exemplar semantics: bucket samples only; the exemplar
            # labelset carries the forensics trace id; its value fits
            # inside the bucket's le bound
            assert name.endswith("_bucket"), (
                f"exemplar on non-bucket sample: {line!r}"
            )
            body, ex = line.split(" # ", 1)
            assert 'trace_id="' in ex, f"exemplar without trace_id: {ex!r}"
            le = re.search(r'le="([^"]*)"', body).group(1)
            ex_val = float(ex.rsplit(" ", 2)[-2])
            if le != "+Inf":
                assert ex_val <= float(le), (
                    f"exemplar value {ex_val} outside bucket le={le}"
                )
        samples.append(name)
    for name in samples:
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert base in types or name in types, f"sample {name} untyped"
    for name, kind in types.items():
        assert name in helps, f"{name} has TYPE but no HELP"
        if kind == "histogram" and any(
            s.startswith(name + "_") for s in samples
        ):
            assert name + "_sum" in samples
            assert name + "_count" in samples
            assert name + "_bucket" in samples


def test_prometheus_text_valid_for_golden_registry():
    assert_valid_prometheus(_golden_registry().to_prometheus())


def test_counter_shards_aggregate_across_threads():
    r = MetricsRegistry()
    c = r.counter("t_total", "x", labels=("k",))
    n_threads, n_inc = 8, 5000

    def worker():
        for _ in range(n_inc):
            c.inc(1, "a")

    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = r.collect()
    assert snap["t_total"]["series"]["a"] == n_threads * n_inc
    # dead threads' shards fold into the retired base: a second collect
    # (threads are dead now) returns the identical total
    assert r.collect()["t_total"]["series"]["a"] == n_threads * n_inc


def test_label_cardinality_bounded():
    r = MetricsRegistry()
    c = r.counter("card_total", "x", labels=("k",), max_series=4)
    for i in range(50):
        c.inc(1, f"v{i}")
    series = r.collect()["card_total"]["series"]
    assert len(series) <= 5  # 4 admitted + the _overflow bucket
    assert series.get("_overflow", 0) == 50 - 4


def test_histogram_buckets_bounded_and_cumulative():
    r = MetricsRegistry()
    h = r.histogram("h_seconds", "x", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    s = r.collect()["h_seconds"]["series"][""]
    assert s["count"] == 3 and abs(s["sum"] - 5.55) < 1e-9
    b = s["buckets"]
    assert b["0.1"] == 1 and b["1.0"] == 2 and b["+Inf"] == 3


def test_gauge_last_write_wins():
    r = MetricsRegistry()
    g = r.gauge("g", "x")
    g.set(1)
    g.set(42.5)
    assert r.collect()["g"]["series"][""] == 42.5


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_bounded():
    rec = FlightRecorder(capacity=32)
    for i in range(100):
        rec.record("s", f"job-{i % 2}", time.monotonic(), 0.001, None)
    snap = rec.snapshot()
    assert len(snap) == 32
    assert rec.dropped > 0


def test_flight_recorder_job_filter_includes_batch_spans():
    rec = FlightRecorder(capacity=64)
    t = time.monotonic()
    rec.record("tokenize", "job-a", t, 0.01, None)
    rec.record("decode_window", None, t, 0.02,
               {"jobs": ("job-a", "job-b")})
    rec.record("tokenize", "job-b", t, 0.01, None)
    a = rec.snapshot("job-a")
    assert [s["name"] for s in a] == ["tokenize", "decode_window"]
    assert len(rec.snapshot("job-b")) == 2
    assert len(rec.snapshot()) == 3


def test_span_context_manager_annotates_errors():
    rec = FlightRecorder(capacity=8)
    with pytest.raises(ValueError):
        with rec.span("flush", "j1", rows=3):
            raise ValueError("boom")
    (s,) = rec.snapshot("j1")
    assert s["attrs"]["rows"] == 3
    assert "ValueError" in s["attrs"]["error"]


# ---------------------------------------------------------------------------
# engine acceptance: seeded 256-row job with one quarantined row
# ---------------------------------------------------------------------------


def _wait_terminal(eng, job_id, timeout=600):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = JobStatus(eng.job_status(job_id))
        if st.is_terminal() and st != JobStatus.CANCELLING:
            return st
        time.sleep(0.05)
    raise TimeoutError(f"{job_id} not terminal within {timeout}s")


@pytest.fixture()
def telemetry_engine(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    telemetry.reset_for_tests()
    telemetry.set_enabled(True)
    eng = LocalEngine(
        EngineConfig(
            kv_page_size=8,
            max_pages_per_seq=16,
            decode_batch_size=8,
            max_model_len=128,
            use_pallas=False,
            param_dtype="float32",
            activation_dtype="float32",
            fault_plan="row.decode:error:rows=77",
            row_retries=1,
        )
    )
    yield eng
    faults.clear()
    eng.close(timeout=5)


def test_flight_recorder_dump_reconciles_256_rows(telemetry_engine):
    """Acceptance criterion verbatim: seeded 256-row job, one injected
    quarantined row -> dump covers every exercised stage, counters
    reconcile exactly, /metrics parses as Prometheus text."""
    eng = telemetry_engine
    n = 256
    jid = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": [f"telemetry row {i}" for i in range(n)],
            "sampling_params": {"max_new_tokens": 8,
                                "temperature": 0.0},
        }
    )
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED

    doc = eng.job_telemetry(jid, write=True)
    dump_path = Path(eng.jobs._dir(jid)) / "telemetry.json"
    assert dump_path.exists()
    persisted = json.loads(dump_path.read_text())
    assert persisted["job_id"] == jid

    # -- span timeline covers every stage this job exercises ----------
    stages = set(doc["stages"])
    assert {
        "tokenize", "admit", "prefill", "decode_window", "accept",
        "flush", "finalize",
    } <= stages, f"missing stages: {stages}"
    for s in doc["spans"]:
        assert s["dur_s"] >= 0 and s["t0_s"] >= 0

    # -- counters reconcile EXACTLY with job results -------------------
    res = eng.job_results(jid)
    rec = eng.jobs.get(jid)
    n_err = sum(1 for e in (res.get("errors") or []) if e)
    c = doc["counters"]
    assert c["rows_ok"] == n - n_err == 255
    assert c["rows_quarantined"] == n_err == 1
    assert c["rows_ok"] + c["rows_quarantined"] == rec.num_rows
    assert c["input_tokens"] == rec.input_tokens
    assert c["output_tokens"] == rec.output_tokens

    # the injected fault and its quarantine surfaced in the registry
    snap = telemetry.REGISTRY.collect()
    assert (
        snap["sutro_faults_injected_total"]["series"]["row.decode"] >= 1
    )
    assert (
        snap["sutro_failure_events_total"]["series"]["row_quarantined"]
        >= 1
    )
    assert snap["sutro_rows_total"]["series"]["quarantined"] >= 1

    # -- /metrics export is valid Prometheus text ----------------------
    assert_valid_prometheus(telemetry.REGISTRY.to_prometheus())


def test_metrics_endpoint_and_job_telemetry_over_http(tmp_path,
                                                      monkeypatch):
    """GET /metrics + GET /job-telemetry/{id} + SDK accessors over the
    daemon (remote backend)."""
    import urllib.request

    from sutro_tpu.server import start_server_thread

    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    telemetry.set_enabled(True)
    eng = LocalEngine(
        EngineConfig(
            kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
            max_model_len=128, use_pallas=False, param_dtype="float32",
            activation_dtype="float32",
        )
    )
    server, _, url = start_server_thread(eng)
    try:
        jid = eng.submit_batch_inference(
            {"model": "tiny-dense", "inputs": ["hi", "there"],
             "sampling_params": {"max_new_tokens": 4,
                                 "temperature": 0.0}}
        )
        assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
        with urllib.request.urlopen(f"{url}/metrics") as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert_valid_prometheus(text)
        assert "sutro_rows_total" in text
        with urllib.request.urlopen(f"{url}/job-telemetry/{jid}") as r:
            doc = json.loads(r.read())["telemetry"]
        assert doc["job_id"] == jid and doc["counters"]["rows_ok"] == 2
        with urllib.request.urlopen(f"{url}/job-doctor/{jid}") as r:
            diag = json.loads(r.read())["doctor"]
        assert diag["job_id"] == jid and diag["verdict"] in (
            "healthy", "host_bound_admit", "io_bound",
            "decode_below_roofline",
        )
        assert diag["evidence"]
        # SDK surface, both backends
        from sutro_tpu.sdk import Sutro

        remote = Sutro(api_key="k", base_url=url, backend="remote")
        assert remote.get_job_telemetry(jid)["job_id"] == jid
        assert "sutro_jobs_total" in remote.get_metrics_text()
        assert remote.diagnose_job(jid)["verdict"] == diag["verdict"]
    finally:
        server.shutdown()
        eng.close(timeout=5)


def test_failed_job_dumps_telemetry(tmp_path, monkeypatch):
    """A job that FAILs terminally leaves telemetry.json next to its
    failure_log — the crash-time postmortem pairing."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    telemetry.set_enabled(True)
    eng = LocalEngine(
        EngineConfig(
            kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
            max_model_len=128, use_pallas=False, param_dtype="float32",
            activation_dtype="float32",
            fault_plan="runner.prefill:error", row_retries=0,
        )
    )
    try:
        jid = eng.submit_batch_inference(
            {"model": "tiny-dense", "inputs": ["x"],
             "sampling_params": {"max_new_tokens": 4,
                                 "temperature": 0.0}}
        )
        assert _wait_terminal(eng, jid) == JobStatus.FAILED
        path = Path(eng.jobs._dir(jid)) / "telemetry.json"
        assert path.exists()
        doc = json.loads(path.read_text())
        assert doc["job_id"] == jid
        assert "tokenize" in doc["stages"]  # timeline reached tokenize
    finally:
        faults.clear()
        eng.close(timeout=5)


def test_telemetry_disabled_is_inert(tmp_path, monkeypatch):
    """SUTRO_TELEMETRY off: no spans recorded, no dump written, jobs
    unaffected."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    telemetry.reset_for_tests()
    telemetry.set_enabled(False)
    try:
        eng = LocalEngine(
            EngineConfig(
                kv_page_size=8, max_pages_per_seq=16,
                decode_batch_size=4, max_model_len=128,
                use_pallas=False, param_dtype="float32",
                activation_dtype="float32",
            )
        )
        jid = eng.submit_batch_inference(
            {"model": "tiny-dense", "inputs": ["a", "b"],
             "sampling_params": {"max_new_tokens": 4,
                                 "temperature": 0.0}}
        )
        assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
        assert telemetry.RECORDER.snapshot() == []
        doc = eng.job_telemetry(jid)  # still answers, just empty
        assert doc["spans"] == [] and doc["counters"] == {}
        assert not (Path(eng.jobs._dir(jid)) / "telemetry.json").exists()
        eng.close(timeout=5)
    finally:
        telemetry.set_enabled(True)


# ---------------------------------------------------------------------------
# satellite: concurrent /metrics scrapes during a running job
# ---------------------------------------------------------------------------


def test_concurrent_scrapes_valid_and_deterministic(telemetry_engine):
    """Scrapers hammering the registry while a job runs (and while a
    remote shard ingests mid-flight) must always see a structurally
    valid exposition with deterministic family/series ordering — no
    torn output."""
    eng = telemetry_engine
    jid = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": [f"scrape row {i}" for i in range(64)],
            "sampling_params": {"max_new_tokens": 8,
                                "temperature": 0.0},
        }
    )
    stop = threading.Event()
    payloads: list = []
    errors: list = []

    def scraper():
        try:
            while not stop.is_set():
                payloads.append(telemetry.REGISTRY.to_prometheus())
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    def ingester():
        # federation churn during scrapes: worker shards arriving
        # must not tear the exposition either
        i = 0
        while not stop.is_set():
            i += 1
            telemetry.REGISTRY.ingest_remote(
                "1",
                {"counters": [["sutro_tokenize_rows_total", [], 1.0]]},
            )
            time.sleep(0.001)

    threads = [threading.Thread(target=scraper) for _ in range(3)] + [
        threading.Thread(target=ingester)
    ]
    for t in threads:
        t.start()
    try:
        assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    finally:
        stop.set()
        for t in threads:
            t.join(30)
    assert not errors, errors
    assert len(payloads) > 10
    for text in payloads[:: max(len(payloads) // 50, 1)]:
        assert_valid_prometheus(text)

    def order_of(text):
        fams = [
            ln.split()[2]
            for ln in text.splitlines()
            if ln.startswith("# TYPE ")
        ]
        return fams

    # deterministic ordering: every scrape lists families sorted, and
    # within the final scrape series are sorted too
    for text in payloads[-5:]:
        fams = order_of(text)
        assert fams == sorted(fams)
    # the validator also covers the committed golden file (satellite:
    # golden + live output both validated by the same checker)
    assert_valid_prometheus(GOLDEN.read_text())


# ---------------------------------------------------------------------------
# satellite: telemetry dump on CANCELLED + status hint
# ---------------------------------------------------------------------------


def test_cancelled_job_dumps_telemetry_and_status_hints(
    tmp_path, monkeypatch
):
    """CANCELLED is a terminal state an operator debugs too: the
    flight-recorder dump must land exactly like on FAILED, and
    ``get_job_status(with_failure_log=True)`` must advertise it."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    telemetry.reset_for_tests()
    telemetry.set_enabled(True)
    eng = LocalEngine(
        EngineConfig(
            kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
            max_model_len=256, use_pallas=False, param_dtype="float32",
            activation_dtype="float32",
        )
    )
    try:
        jid = eng.submit_batch_inference(
            {
                "model": "tiny-dense",
                "inputs": [f"cancel row {i}" for i in range(32)],
                "sampling_params": {"max_new_tokens": 64,
                                    "temperature": 0.0},
            }
        )
        deadline = time.monotonic() + 120
        while (
            eng.job_status(jid) not in ("RUNNING",)
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        eng.cancel_job(jid)
        st = _wait_terminal(eng, jid)
        assert st == JobStatus.CANCELLED
        path = Path(eng.jobs._dir(jid)) / "telemetry.json"
        assert path.exists(), "CANCELLED must dump telemetry.json"
        doc = json.loads(path.read_text())
        assert doc["job_id"] == jid
        # the record advertises the dump for `sutro jobs status`
        assert eng.get_job(jid)["has_telemetry_dump"] is True
        from sutro_tpu.sdk import Sutro

        sdk = Sutro(api_key=None)
        sdk._engine = eng  # bind to THIS engine, not the singleton
        sdk.set_backend("tpu")
        out = sdk.get_job_status(jid, with_failure_log=True)
        assert out["has_telemetry_dump"] is True
    finally:
        eng.close(timeout=5)


# ---------------------------------------------------------------------------
# satellite: throughput gauges cover the embed path
# ---------------------------------------------------------------------------


def test_embed_job_feeds_rows_per_second_gauge(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    (tmp_path / "home").mkdir()
    telemetry.reset_for_tests()
    telemetry.set_enabled(True)
    eng = LocalEngine(
        EngineConfig(
            kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
            max_model_len=128, use_pallas=False, param_dtype="float32",
            activation_dtype="float32",
        )
    )
    try:
        jid = eng.submit_batch_inference(
            {
                "model": "tiny-emb",
                "inputs": [f"embed row {i}" for i in range(24)],
            }
        )
        assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
        snap = telemetry.REGISTRY.collect()
        rps = snap["sutro_rows_per_second"]["series"]
        assert "embed" in rps, rps  # the embed workload reports rows/s
        # the embed path also feeds the token gauges now
        assert snap["sutro_tokens_per_second"]["series"][""] >= 0
    finally:
        eng.close(timeout=5)


# ---------------------------------------------------------------------------
# satellite: job_trace reentrancy (refcounted device trace)
# ---------------------------------------------------------------------------


class TestJobTraceRefcount:
    def _fake_profiler(self, monkeypatch):
        import jax

        calls = {"start": [], "stop": 0}

        def fake_start(path):
            if calls["start"] and calls["stop"] < len(calls["start"]):
                raise RuntimeError("Profiler is already started")
            calls["start"].append(path)

        def fake_stop():
            calls["stop"] += 1

        monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
        monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)
        return calls

    def test_nested_job_traces_refcount(self, tmp_path, monkeypatch):
        """Two co-batched jobs with profile_dir: the first starts the
        trace, the second JOINS it (no second start_trace, which
        raises), the last one out stops it — and both jobs record the
        active trace path in their flight-recorder attrs."""
        from sutro_tpu.engine.profiling import job_trace

        telemetry.reset_for_tests()
        telemetry.set_enabled(True)
        calls = self._fake_profiler(monkeypatch)
        pd = str(tmp_path)
        with job_trace(pd, "job-a"):
            with job_trace(pd, "job-b"):  # used to raise here
                pass
            assert calls["stop"] == 0  # inner exit must NOT stop
        assert len(calls["start"]) == 1
        assert calls["start"][0].endswith("job-a")
        assert calls["stop"] == 1
        # both jobs know where their device trace went
        assert telemetry.JOBS.peek("job-a").attrs[
            "profile_trace"
        ].endswith("job-a")
        assert telemetry.JOBS.peek("job-b").attrs[
            "profile_trace"
        ].endswith("job-a")

    def test_sequential_traces_restart(self, tmp_path, monkeypatch):
        from sutro_tpu.engine.profiling import job_trace

        calls = self._fake_profiler(monkeypatch)
        with job_trace(str(tmp_path), "job-1"):
            pass
        with job_trace(str(tmp_path), "job-2"):
            pass
        assert [p.split("/")[-1] for p in calls["start"]] == [
            "job-1", "job-2",
        ]
        assert calls["stop"] == 2

    def test_no_profile_dir_is_inert(self, monkeypatch):
        from sutro_tpu.engine.profiling import job_trace

        calls = self._fake_profiler(monkeypatch)
        with job_trace(None, "job-x"):
            pass
        assert calls["start"] == [] and calls["stop"] == 0


# ---------------------------------------------------------------------------
# satellite: JobMetrics subscriber churn
# ---------------------------------------------------------------------------


class TestJobMetricsChurn:
    def test_concurrent_subscribe_unsubscribe_no_leaks(self):
        """Subscribers attach/detach while a producer publishes: every
        attach sees a snapshot first, stayers see the final count and
        the done sentinel, and nothing leaks from the subscriber list."""
        jm = JobMetrics()
        N = 400
        errors = []
        finals = []

        def producer():
            for i in range(1, N + 1):
                jm.progress(i)
                if i % 50 == 0:
                    jm.tokens({"input_tokens": i})
                if i % 97 == 0:
                    time.sleep(0.001)
            jm.finish()

        def stayer():
            try:
                seen = []
                for u in jm.subscribe():
                    if u["update_type"] == "progress":
                        seen.append(u["result"])
                assert seen, "no snapshot delivered"
                assert seen == sorted(seen), "progress went backwards"
                finals.append(seen[-1])
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def churner():
            try:
                for _ in range(10):
                    it = jm.subscribe()
                    first = next(it)
                    # mid-run attach sees a snapshot immediately
                    assert first["update_type"] == "progress"
                    assert 0 <= first["result"] <= N
                    it.close()  # unsubscribe mid-stream
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = (
            [threading.Thread(target=stayer) for _ in range(4)]
            + [threading.Thread(target=churner) for _ in range(4)]
        )
        prod = threading.Thread(target=producer)
        for t in threads:
            t.start()
        prod.start()
        prod.join(30)
        for t in threads:
            t.join(30)
        assert not errors, errors
        # no lost done-sentinel: every stayer terminated with the final
        # count (the pending-drain-before-done contract)
        assert finals == [N] * 4
        # no leaked subscribers after every generator exited
        assert jm._subscribers == []

    def test_late_attach_after_finish_gets_snapshot_and_returns(self):
        jm = JobMetrics()
        jm.progress(7)
        jm.tokens({"input_tokens": 3})
        jm.finish()
        updates = list(jm.subscribe())
        assert updates[0] == {"update_type": "progress", "result": 7}
        assert {"update_type": "tokens",
                "result": {"input_tokens": 3}} in updates
        assert jm._subscribers == []


# ---------------------------------------------------------------------------
# satellite: Throughput first-add anchor
# ---------------------------------------------------------------------------


class TestThroughputAnchor:
    def test_rate_anchors_at_first_add_not_construction(self):
        t = Throughput(n_chips=2)
        time.sleep(0.05)  # the "long compile" before any tokens
        t.add(1000)
        # anchored at add: elapsed is ~0, so the rate must NOT be
        # diluted by the 50 ms of pre-token dead time
        assert t.per_second() > 1000 / 0.05
        time.sleep(0.05)  # stable elapsed for the ratio check
        assert t.per_chip_per_second() == pytest.approx(
            t.per_second() / 2, rel=0.1
        )

    def test_zero_before_first_add(self):
        t = Throughput()
        assert t.per_second() == 0.0

    def test_note_total_baselines_first_report(self):
        t = Throughput()
        time.sleep(0.02)
        t.note_total(10_000)  # first report anchors AND baselines
        assert t.per_second() == 0.0
        t.note_total(10_100)
        time.sleep(0.01)
        rate = t.per_second()
        assert 0 < rate < 100 / 0.01


if __name__ == "__main__":
    import sys

    if "--regen-golden" in sys.argv:
        GOLDEN.parent.mkdir(parents=True, exist_ok=True)
        GOLDEN.write_text(_golden_registry().to_prometheus())
        print(f"wrote {GOLDEN}")
