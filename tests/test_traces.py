"""Tail-latency forensics (sutro_tpu/telemetry/traces.py +
traceexport.py, OBSERVABILITY.md "Forensics").

Covers the PR's acceptance criteria and test satellites:

1. trace store units — bounded ring eviction, per-trace span cap with a
   dropped counter, idempotent ``start_trace``;
2. Perfetto export golden — a deterministic request timeline renders to
   byte-identical Chrome trace-event JSON
   (``tests/data/trace_export.golden``; regen with
   ``python tests/test_traces.py --regen-golden``), and the timeline
   covers admission -> queue -> prefill -> decode -> flush with no gap
   wider than one decode window;
3. per-request doctor — the ``diagnose_request`` verdict matrix
   (queue_wait_bound / preemption_bound / stream_flush_bound / healthy
   / insufficient_data) over synthetic trace docs;
4. exemplars — OpenMetrics exemplar syntax on ``/metrics`` validated by
   the pure-python prom validator, capture determinism under concurrent
   scrapes (latency-biased keep policy converges to the max), and no
   exemplar output unless a call site opts in;
5. the live acceptance run — a real streamed chat request through the
   shared daemon; a fired ``interactive_ttft_p99`` alert carries an
   exemplar trace id that resolves via ``GET /trace/{id}`` to a
   Perfetto document whose spans cover the whole request.
"""

import json
import sys
import threading
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from sutro_tpu import telemetry
from sutro_tpu.telemetry import traceexport
from sutro_tpu.telemetry.doctor import diagnose_request
from sutro_tpu.telemetry.registry import MetricsRegistry
from sutro_tpu.telemetry.traces import (
    MAX_SPANS_PER_TRACE,
    TraceStore,
)
from tests.test_telemetry import assert_valid_prometheus

GOLDEN = Path(__file__).parent / "data" / "trace_export.golden"


# ---------------------------------------------------------------------------
# trace store units
# ---------------------------------------------------------------------------


def test_trace_ring_evicts_oldest():
    store = TraceStore(capacity=8)
    for i in range(12):
        store.start_trace(f"tr-{i}", t0_mono=float(i))
    assert store.ids() == [f"tr-{i}" for i in range(4, 12)]
    assert store.doc("tr-0") is None
    assert store.doc("tr-11") is not None


def test_trace_span_cap_counts_drops():
    store = TraceStore()
    store.start_trace("tr-a", t0_mono=0.0)
    for i in range(MAX_SPANS_PER_TRACE + 10):
        store.add("tr-a", "accept", float(i), 0.001)
    doc = store.doc("tr-a")
    assert len(doc["spans"]) == MAX_SPANS_PER_TRACE
    assert doc["dropped"] == 10


def test_start_trace_idempotent_and_end():
    store = TraceStore()
    a = store.start_trace("tr-a", "batch", {"job_id": "j"}, t0_mono=1.0)
    b = store.start_trace("tr-a", "interactive", t0_mono=99.0)
    assert a is b and a.kind == "batch" and a.t0_mono == 1.0
    store.add("tr-a", "prefill", 1.5, 0.25)
    store.end_trace("tr-a", "err")
    doc = store.doc("tr-a")
    assert doc["finished"] and doc["outcome"] == "err"
    assert doc["spans"][0] == {
        "name": "prefill", "t0_s": 0.5, "dur_s": 0.25,
    }
    # unknown ids are no-ops, not errors (the store is fire-and-forget)
    store.add("tr-missing", "prefill", 0.0, 0.1)
    store.end_trace("tr-missing")
    store.event("tr-missing", "finish")


# ---------------------------------------------------------------------------
# Perfetto export: deterministic golden + coverage-gap criterion
# ---------------------------------------------------------------------------


def _golden_trace_doc():
    """One interactive request's full lifecycle with pinned clocks:
    admission, queue, prefill, windowed decode with one preemption
    suspend/resume, prefix hit, SSE flushes, finish."""
    store = TraceStore(capacity=8)
    t0 = 100.0
    store.start_trace(
        "tr-ivr-7",
        "interactive",
        {"request_id": "ivr-7", "model": "tiny-dense", "tenant": "acme"},
        t0_mono=t0,
        created_unix=1700000000.0,
    )
    a = lambda *args, **kw: store.add("tr-ivr-7", *args, **kw)  # noqa: E731
    e = lambda n, at, **kw: store.event(  # noqa: E731
        "tr-ivr-7", n, kw or None, t_mono=t0 + at
    )
    a("admit_gateway", t0, 0.002, {"prompt_tokens": 24, "warm_tokens": 16})
    a("queue_wait", t0 + 0.002, 0.014)
    e("prefix_hit", 0.016, saved_tokens=16, paid_tokens=8)
    a("prefill", t0 + 0.016, 0.080)
    a("accept", t0 + 0.096, 0.001)
    a("decode_window", t0 + 0.097, 0.040)
    e("preempt_suspend", 0.137, row_id=0, by="job-b", lost_tokens=2)
    e("resume", 0.150, row_id=0)
    a("decode_window", t0 + 0.150, 0.040)
    a("accept", t0 + 0.190, 0.001)
    e("first_token", 0.191, ttft_s=0.191)
    a("stream_flush", t0 + 0.191, 0.0005, {"bytes": 120})
    a("decode_window", t0 + 0.1915, 0.040)
    a("stream_flush", t0 + 0.2315, 0.0004, {"bytes": 96})
    e("finish", 0.232, outcome="ok", tokens=3)
    store.end_trace("tr-ivr-7", "ok")
    return store.doc("tr-ivr-7")


def test_trace_export_matches_golden():
    assert GOLDEN.exists(), (
        "golden file missing (regen: python tests/test_traces.py "
        "--regen-golden)"
    )
    doc = _golden_trace_doc()
    assert traceexport.render(
        traceexport.trace_to_chrome(doc)
    ) == GOLDEN.read_text()


def test_trace_covers_request_without_decode_window_gaps():
    """Acceptance criterion: spans cover admission -> queue -> prefill
    -> decode -> flush and no coverage gap exceeds one decode window."""
    doc = _golden_trace_doc()
    assert {
        "admit_gateway", "queue_wait", "prefill", "decode_window",
        "stream_flush", "finish",
    } <= set(doc["stages"])
    one_window = max(
        s["dur_s"] for s in doc["spans"] if s["name"] == "decode_window"
    )
    assert traceexport.largest_gap_s(doc) <= one_window


def test_chrome_doc_shape_and_lanes():
    chrome = traceexport.trace_to_chrome(_golden_trace_doc())
    evs = chrome["traceEvents"]
    xs = [ev for ev in evs if ev["ph"] == "X"]
    metas = [ev for ev in evs if ev["ph"] == "M"]
    # every span event: µs timestamps, ≥1µs duration (instants must
    # stay visible in Perfetto), one process, named lanes
    assert all(ev["pid"] == 1 and ev["dur"] >= 1 for ev in xs)
    lane_names = {
        m["args"]["name"] for m in metas if m["name"] == "thread_name"
    }
    assert {"admit", "queue", "prefill", "decode", "stream"} <= lane_names
    other = chrome["otherData"]
    assert other["trace_id"] == "tr-ivr-7"
    assert other["kind"] == "interactive" and other["outcome"] == "ok"
    assert chrome["displayTimeUnit"] == "ms"
    # rendering is stable: sorted keys, trailing newline
    text = traceexport.render(chrome)
    assert text.endswith("\n") and json.loads(text) == chrome


# ---------------------------------------------------------------------------
# per-request doctor
# ---------------------------------------------------------------------------


def _doc(spans, trace_id="tr-x"):
    return {
        "trace_id": trace_id, "kind": "interactive", "outcome": "ok",
        "spans": [
            {"name": n, "t0_s": t0, "dur_s": d, "attrs": a}
            for (n, t0, d, a) in spans
        ],
    }


def test_diagnose_request_verdict_matrix():
    # queue dominates: waited for a slot, not the chip
    q = diagnose_request(_doc([
        ("queue_wait", 0.0, 0.8, None),
        ("prefill", 0.8, 0.1, None),
        ("decode_window", 0.9, 0.1, None),
    ]))
    assert q["verdict"] == "queue_wait_bound"
    assert q["legs"]["queue_s"] == pytest.approx(0.8)

    # suspend -> resume stall dominates
    p = diagnose_request(_doc([
        ("prefill", 0.0, 0.1, None),
        ("preempt_suspend", 0.1, 0.0, {"row_id": 1, "lost_tokens": 4}),
        ("resume", 0.9, 0.0, {"row_id": 1}),
        ("decode_window", 0.9, 0.1, None),
    ]))
    assert p["verdict"] == "preemption_bound"
    assert p["legs"]["preemptions"] == 1
    assert p["legs"]["preempt_stall_s"] == pytest.approx(0.8)

    # SSE flush (slow client socket) dominates
    f = diagnose_request(_doc([
        ("prefill", 0.0, 0.1, None),
        ("stream_flush", 0.1, 0.9, {"bytes": 1}),
    ]))
    assert f["verdict"] == "stream_flush_bound"

    # honest compute
    h = diagnose_request(_doc([
        ("queue_wait", 0.0, 0.01, None),
        ("prefill", 0.01, 0.5, None),
        ("decode_window", 0.51, 0.5, None),
        ("stream_flush", 1.01, 0.001, None),
    ]))
    assert h["verdict"] == "healthy"

    empty = diagnose_request({"trace_id": "tr-e", "spans": []})
    assert empty["verdict"] == "insufficient_data"


def test_diagnose_request_unresumed_suspend_stalls_to_end():
    d = diagnose_request(_doc([
        ("prefill", 0.0, 0.1, None),
        ("preempt_suspend", 0.1, 0.0, {"row_id": 2}),
        ("decode_window", 0.9, 0.1, None),
    ]))
    assert d["legs"]["preempt_stall_s"] == pytest.approx(0.9)
    assert d["verdict"] == "preemption_bound"


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------


def _reg_with_exemplars():
    r = MetricsRegistry()
    h = r.histogram(
        "fx_ttft_seconds", "x", buckets=(0.25, 1.0, 10.0),
        unit="seconds",
    )
    h.observe(0.2, exemplar="tr-fast", _now=1000.0)
    h.observe(6.0, exemplar="tr-slow", exemplar_attrs={"tenant": "acme"},
              _now=1001.0)
    return r, h


def test_exemplar_openmetrics_syntax_on_buckets():
    r, _ = _reg_with_exemplars()
    text = r.to_prometheus()
    assert_valid_prometheus(text)
    assert (
        'fx_ttft_seconds_bucket{le="0.25"} 1 '
        '# {trace_id="tr-fast"} 0.2 1000'
    ) in text
    assert (
        'fx_ttft_seconds_bucket{le="10"} 2 '
        '# {trace_id="tr-slow",tenant="acme"} 6 1001'
    ) in text
    # flat view for the monitor: worst first
    flat = r.exemplars("fx_ttft_seconds")
    assert [e["trace_id"] for e in flat] == ["tr-slow", "tr-fast"]


def test_exemplar_opt_in_only():
    r = MetricsRegistry()
    h = r.histogram("fx_plain_seconds", "x", buckets=(1.0,))
    h.observe(0.5)
    text = r.to_prometheus()
    assert " # " not in text
    assert all("exemplars" not in m for m in r.collect())
    assert r.exemplars("fx_plain_seconds") == []


def test_exemplar_keep_policy_latency_biased():
    r = MetricsRegistry()
    h = r.histogram("fx_keep_seconds", "x", buckets=(10.0,))
    h.observe(5.0, exemplar="tr-big", _now=1000.0)
    # smaller + recent: kept out (the tail is what forensics wants)
    h.observe(1.0, exemplar="tr-small", _now=1001.0)
    assert r.exemplars("fx_keep_seconds")[0]["trace_id"] == "tr-big"
    # smaller but the held exemplar has aged out: recency wins
    h.observe(1.0, exemplar="tr-fresh", _now=1200.0)
    assert r.exemplars("fx_keep_seconds")[0]["trace_id"] == "tr-fresh"


def test_exemplar_determinism_under_concurrent_scrapes():
    """Writers race observations (same bucket, fixed clock) while
    scrapers hammer the exporter: every scrape parses as valid
    exposition, and the keep policy converges on the max value
    regardless of interleaving."""
    r = MetricsRegistry()
    h = r.histogram("fx_race_seconds", "x", buckets=(10.0,))
    stop = threading.Event()
    errors = []

    def scraper():
        while not stop.is_set():
            try:
                assert_valid_prometheus(r.to_prometheus())
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))
                return

    def writer(seed):
        vals = [(seed * 7 + i * 3) % 90 / 10.0 for i in range(400)]
        for i, v in enumerate(vals):
            h.observe(v, exemplar=f"tr-{seed}-{i}", _now=1000.0)

    scr = [threading.Thread(target=scraper) for _ in range(3)]
    wrs = [threading.Thread(target=writer, args=(s,)) for s in range(4)]
    for t in scr + wrs:
        t.start()
    for t in wrs:
        t.join()
    stop.set()
    for t in scr:
        t.join()
    assert not errors, errors
    (top,) = r.exemplars("fx_race_seconds")
    assert top["value"] == 8.9  # max of every writer's sequence


def test_monitor_firing_event_embeds_exemplar_trace_ids():
    from sutro_tpu.telemetry.monitor import Monitor, SLORule

    telemetry.reset_for_tests()
    telemetry.TTFT_SECONDS.observe(7.0, exemplar="tr-worst")
    telemetry.TTFT_SECONDS.observe(0.1, exemplar="tr-fine")
    rule = SLORule(
        "interactive_ttft_p99", metric="ttft_p99_s", op=">",
        threshold=5.0, for_ticks=1, clear_ticks=1,
        workload="interactive",
    )
    mon = Monitor(rules=[rule])
    (ev,) = mon._evaluate_rules({"ttft_p99_s": 7.0}, 0.0)
    assert ev["state"] == "firing"
    assert ev["exemplar_trace_ids"][0] == "tr-worst"
    # resolved events carry no exemplars (nothing to chase)
    (ev2,) = mon._evaluate_rules({"ttft_p99_s": 0.0}, 1.0)
    assert ev2["state"] == "resolved"
    assert "exemplar_trace_ids" not in ev2
    telemetry.reset_for_tests()


# ---------------------------------------------------------------------------
# live acceptance: alert exemplar -> GET /trace/{id} -> full coverage
# ---------------------------------------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def test_live_alert_exemplar_resolves_to_full_trace(live_engine):
    """Acceptance criterion verbatim: stream a real chat request
    through the shared daemon, force the ``interactive_ttft_p99`` rule
    to fire, follow the alert's exemplar trace id through
    ``GET /trace/{id}``, and assert the Perfetto document covers
    admission -> queue -> prefill -> decode -> flush with no gap wider
    than one decode window."""
    from sutro_tpu.telemetry.monitor import SLORule

    engine, url, _home = live_engine
    assert telemetry.ENABLED and engine.monitor is not None
    saved_rules = list(engine.monitor._rules)
    engine.monitor.set_rules([
        SLORule(
            "interactive_ttft_p99", metric="ttft_p99_s", op=">",
            threshold=0.0, for_ticks=1, clear_ticks=10_000,
            workload="interactive",
        ),
    ])
    try:
        body = json.dumps({
            "model": "tiny-dense",
            "messages": [{"role": "user", "content": "trace me"}],
            "temperature": 0.0,
            "max_tokens": 4,
            "stream": True,
        }).encode()
        req = urllib.request.Request(
            f"{url}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            stream = resp.read().decode()
        assert "data: [DONE]" in stream

        # the monitor tick picks up the windowed TTFT and fires; the
        # firing event must carry the request's exemplar trace id
        ids = []
        import time as _t
        deadline = _t.monotonic() + 30
        while _t.monotonic() < deadline and not ids:
            doc = _get_json(f"{url}/monitor")["monitor"]
            for ev in doc["alerts"]["events"]:
                if (
                    ev["rule"] == "interactive_ttft_p99"
                    and ev["state"] == "firing"
                ):
                    ids = ev.get("exemplar_trace_ids") or []
            _t.sleep(0.05)
        assert ids, "firing alert never carried an exemplar trace id"

        chrome = _get_json(f"{url}/trace/{ids[0]}")
        assert chrome["otherData"]["trace_id"] == ids[0]
        names = {
            ev["name"] for ev in chrome["traceEvents"]
            if ev["ph"] == "X"
        }
        assert {
            "admit_gateway", "queue_wait", "prefill", "decode_window",
            "stream_flush", "finish",
        } <= names
        # per-request doctor rides in otherData
        verdict = chrome["otherData"]["verdict"]
        assert verdict["verdict"] in (
            "healthy", "queue_wait_bound", "preemption_bound",
            "stream_flush_bound",
        )
        # coverage: no gap wider than one decode window (source doc is
        # in-process — the daemon shares our interpreter)
        src = telemetry.TRACES.doc(ids[0])
        one_window = max(
            s["dur_s"] for s in src["spans"]
            if s["name"] == "decode_window"
        )
        assert traceexport.largest_gap_s(src) <= one_window + 0.05

        # sdk surface (remote backend) returns the same document
        from sutro_tpu.sdk import Sutro

        sdk = Sutro(api_key="k", base_url=url, backend="remote")
        assert sdk.get_trace(ids[0]) == _get_json(
            f"{url}/trace/{ids[0]}"
        )

        # unknown ids 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{url}/trace/tr-nope", timeout=10)
        assert ei.value.code == 404
    finally:
        engine.monitor.set_rules(saved_rules)


def test_batch_job_flight_record_export(live_engine):
    """A plain job id exports the whole-job flight record; the batch
    trace (tr-<job>) records queue wait and per-window stages."""
    engine, url, _home = live_engine
    jid = engine.submit_batch_inference({
        "model": "tiny-dense",
        "inputs": ["flight record row"],
        "sampling_params": {"max_new_tokens": 4, "temperature": 0.0},
    })
    import time as _t
    deadline = _t.monotonic() + 120
    while _t.monotonic() < deadline:
        if engine.job_status(jid) in ("SUCCEEDED", "FAILED"):
            break
        _t.sleep(0.05)
    assert engine.job_status(jid) == "SUCCEEDED"

    # the batch trace by id
    chrome = _get_json(f"{url}/trace/tr-{jid}")
    names = {
        ev["name"] for ev in chrome["traceEvents"] if ev["ph"] == "X"
    }
    assert "queue_wait" in names and "decode_window" in names
    # bare job id -> same trace (ring hit wins over flight record)
    assert _get_json(f"{url}/trace/{jid}")["otherData"][
        "trace_id"
    ] == f"tr-{jid}"


if __name__ == "__main__":
    if "--regen-golden" in sys.argv:
        GOLDEN.parent.mkdir(parents=True, exist_ok=True)
        GOLDEN.write_text(
            traceexport.render(
                traceexport.trace_to_chrome(_golden_trace_doc())
            )
        )
        print(f"wrote {GOLDEN}")
    else:
        sys.exit(pytest.main([__file__, "-v"]))
