"""Sarathi-style piggybacked prefill (EngineConfig.prefill_piggyback,
VERDICT r3 next-step 5): a long prompt admits as a PREFILLING slot that
advances one chunk per scheduler iteration while active rows keep
decoding — bounded cadence degradation instead of a full pause — and
produces bit-identical outputs to the stop-the-world path."""

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
from sutro_tpu.models.configs import MODEL_CONFIGS


def _ecfg(**kw):
    base = dict(
        kv_page_size=8, max_pages_per_seq=32, decode_batch_size=4,
        max_model_len=256, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", prefill_chunk=16,
    )
    base.update(kw)
    return EngineConfig(**base)


LONG = "this is a deliberately long prompt " * 4  # ~140 bytes > 8 chunks
SHORTS = ["quick a", "quick b", "quick c"]


def _reqs(tok, texts, **kw):
    return [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(tok.encode(t), np.int32),
            **kw,
        )
        for i, t in enumerate(texts)
    ]


def _run(ecfg, tok, reqs):
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
    b = ContinuousBatcher(runner, stop_ids=tok.stop_ids())
    res = {}
    out = b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
    assert out == "completed"
    return runner, b, res


def test_decode_continues_while_long_prompt_prefills(byte_tok):
    """The acceptance test the VERDICT asked for: decode dispatches for
    active rows appear BETWEEN the long row's prefill chunks instead of
    after all of them."""
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg())
    events = []

    orig_chunk = runner.prefill_batch_at
    orig_multi = runner.decode_multi_async
    orig_window = runner.decode_window
    orig_step = runner.decode_step

    def spy(name, fn):
        def wrapped(*a, **k):
            events.append(name)
            return fn(*a, **k)

        return wrapped

    runner.prefill_batch_at = spy("chunk", orig_chunk)
    runner.decode_multi_async = spy("decode", orig_multi)
    runner.decode_window = spy("decode", orig_window)
    runner.decode_step = spy("decode", orig_step)

    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    # shorts decode for a while; the long row admits alongside them
    reqs = _reqs(
        byte_tok, SHORTS + [LONG], max_new_tokens=30, temperature=0.0
    )
    res = {}
    out = b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
    assert out == "completed"
    assert set(res) == {0, 1, 2, 3}
    chunk_idx = [i for i, e in enumerate(events) if e == "chunk"]
    assert len(chunk_idx) >= 2, "long prompt did not chunk"
    interleaved = [
        e
        for e in events[chunk_idx[0] : chunk_idx[-1]]
        if e == "decode"
    ]
    assert interleaved, (
        "no decode dispatch between prefill chunks — the batch stalled "
        f"for the whole prefill: {events[:40]}"
    )


@pytest.mark.parametrize("native", [False, True])
def test_outputs_identical_piggyback_on_off(byte_tok, monkeypatch, native):
    """Greedy outputs are bit-identical with piggybacked and
    stop-the-world prefill, on both runtime paths."""
    from sutro_tpu.engine import native_runtime

    if native and not native_runtime.is_available():
        pytest.skip("native toolchain unavailable")
    monkeypatch.setenv("SUTRO_NATIVE_RUNTIME", "1" if native else "0")
    native_runtime._lib = None
    native_runtime._lib_failed = False
    try:
        texts = SHORTS + [LONG, "middle sized prompt right here ok"]
        kw = dict(max_new_tokens=12, temperature=0.0)
        _, b_on, on = _run(
            _ecfg(prefill_piggyback=True), byte_tok,
            _reqs(byte_tok, texts, **kw),
        )
        assert (b_on.native is not None) == native
        _, _, off = _run(
            _ecfg(prefill_piggyback=False), byte_tok,
            _reqs(byte_tok, texts, **kw),
        )
        assert set(on) == set(off)
        for i in on:
            assert on[i].token_ids == off[i].token_ids, i
        assert b_on.free_page_count == (
            b_on.native.free_count if native else b_on.allocator.free_count
        )
    finally:
        native_runtime._lib = None
        native_runtime._lib_failed = False


def test_piggyback_with_shared_prefix(byte_tok):
    """A job with a shared prefix AND long suffixes: chunks start at
    the shared offset; outputs equal the non-piggyback run."""
    prefix = "SHARED JOB SHELL PROMPT: analyse the following text: "
    texts = [
        prefix + "short tail",
        prefix + "another short",
        prefix + ("long tail segment " * 6),
    ]
    kw = dict(max_new_tokens=10, temperature=0.0)
    _, b_on, on = _run(
        _ecfg(prefill_piggyback=True), byte_tok,
        _reqs(byte_tok, texts, **kw),
    )
    _, _, off = _run(
        _ecfg(prefill_piggyback=False), byte_tok,
        _reqs(byte_tok, texts, **kw),
    )
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i
    # the shared prefix engaged (prefill accounting: prefix once)
    assert b_on.prefill_tokens < sum(
        len(byte_tok.encode(t)) for t in texts
    )


def test_cancel_while_prefilling_frees_pages(byte_tok):
    """Cancelling mid-prefill releases the prefilling slot's pages and
    emits the row as cancelled."""
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg())
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    before = b.free_page_count
    calls = [0]

    def cancel():
        calls[0] += 1
        return calls[0] > 3

    res = {}
    out = b.run(
        _reqs(byte_tok, [LONG, LONG + " two"], max_new_tokens=40),
        on_result=lambda r: res.__setitem__(r.row_id, r),
        should_cancel=cancel,
    )
    assert out == "cancelled"
    assert b.free_page_count == before
    assert all(r.finish_reason == "cancelled" for r in res.values())
