"""Cross-job co-batching (scheduler.run_multi + engine attach, VERDICT
r3 next-step 3): same-model jobs share one decode batch. Admission
pulls rows across jobs in (priority, seq) order, results/metrics route
per job, and an interactive p0 job admitted mid-flight of a big p1 job
completes in ~single-job latency WITHOUT preempting p1's active slots —
the multiplexing the reference's fleet does implicitly
(/root/reference/sutro/sdk.py:202-216)."""

import time

import numpy as np
import pytest

from sutro_tpu.engine.scheduler import (
    ContinuousBatcher,
    GenRequest,
    JobCtx,
)
from sutro_tpu.models.configs import MODEL_CONFIGS


def _reqs(tok, texts, row_base=0, **kw):
    return [
        GenRequest(
            row_id=row_base + i,
            prompt_ids=np.array(tok.encode(t), np.int32),
            **kw,
        )
        for i, t in enumerate(texts)
    ]


def _batcher(tiny_ecfg, byte_tok):
    from sutro_tpu.engine.runner import ModelRunner

    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], tiny_ecfg)
    return ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())


def _solo(tiny_ecfg, byte_tok, reqs):
    b = _batcher(tiny_ecfg, byte_tok)
    res = {}
    b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
    return res


def test_two_jobs_one_session_exact_results(tiny_ecfg, byte_tok):
    """Two greedy jobs sharing a session produce exactly the outputs of
    two solo runs, each streamed through its own callbacks."""
    a_texts = [f"alpha row {i}" for i in range(6)]
    b_texts = [f"bravo item {i}" for i in range(4)]
    kw = dict(max_new_tokens=8, temperature=0.0)
    solo_a = _solo(tiny_ecfg, byte_tok, _reqs(byte_tok, a_texts, **kw))
    solo_b = _solo(tiny_ecfg, byte_tok, _reqs(byte_tok, b_texts, **kw))

    b = _batcher(tiny_ecfg, byte_tok)
    got_a, got_b, done = {}, {}, []
    ctx_a = JobCtx(
        job_id="A",
        pending=_reqs(byte_tok, a_texts, **kw),
        on_result=lambda r: got_a.__setitem__(r.row_id, r),
        priority=1,
        seq=0,
    )
    ctx_b = JobCtx(
        job_id="B",
        pending=_reqs(byte_tok, b_texts, **kw),
        on_result=lambda r: got_b.__setitem__(r.row_id, r),
        priority=1,
        seq=1,
    )
    state = b.run_multi(
        [ctx_a, ctx_b],
        on_job_done=lambda c, o: done.append((c.job_id, o)),
    )
    assert state == "completed"
    assert dict(done) == {"A": "completed", "B": "completed"}
    assert {i: r.token_ids for i, r in got_a.items()} == {
        i: r.token_ids for i, r in solo_a.items()
    }
    assert {i: r.token_ids for i, r in got_b.items()} == {
        i: r.token_ids for i, r in solo_b.items()
    }
    # per-job accounting is exact and separate: sampled-token count is
    # len(token_ids), +1 for rows whose trailing stop token was stripped
    assert ctx_a.stats["rows"] == 6 and ctx_b.stats["rows"] == 4
    for ctx, got in ((ctx_a, got_a), (ctx_b, got_b)):
        lo = sum(len(r.token_ids) for r in got.values())
        assert lo <= ctx.stats["out"] <= lo + len(got)


def test_attached_p0_finishes_while_p1_keeps_its_slots(
    tiny_ecfg, byte_tok
):
    """The VERDICT's acceptance test: a p0 3-row job attached mid-flight
    of a p1 many-row job completes while p1 still has pending rows, and
    p1's active slots are never preempted (p1 completes normally with
    every row)."""
    p1_texts = [f"batch row {i}" for i in range(12)]
    p0_texts = ["quick a", "quick b", "quick c"]
    b = _batcher(tiny_ecfg, byte_tok)
    got1, got0, done = {}, {}, []
    ctx1 = JobCtx(
        job_id="p1",
        pending=_reqs(byte_tok, p1_texts, max_new_tokens=40,
                      temperature=0.0),
        on_result=lambda r: got1.__setitem__(r.row_id, r),
        priority=1,
        seq=0,
    )
    ctx0 = JobCtx(
        job_id="p0",
        pending=_reqs(byte_tok, p0_texts, max_new_tokens=4,
                      temperature=0.0),
        on_result=lambda r: got0.__setitem__(r.row_id, r),
        priority=0,
        seq=1,
    )
    handed = []

    def poll_new():
        # attach p0 once p1 has generated some tokens (mid-flight)
        if not handed and ctx1.stats["out"] > 20:
            handed.append(True)
            return ctx0
        return None

    state = b.run_multi(
        [ctx1],
        on_job_done=lambda c, o: done.append((c.job_id, o)),
        poll_new=poll_new,
    )
    assert state == "completed"
    assert handed, "p0 was never attached"
    # completion ORDER is the latency proof: p0 finished first
    assert done[0] == ("p0", "completed")
    assert done[-1] == ("p1", "completed")
    assert len(got0) == 3 and len(got1) == 12
    # no preemption: every p1 row ran to its natural finish
    assert all(r.finish_reason in ("stop", "length") for r in got1.values())


def test_per_job_cancel_leaves_other_job_running(tiny_ecfg, byte_tok):
    """Cancelling one co-batched job releases only ITS slots (emitted
    as cancelled); the other job runs to completion with outputs equal
    to a solo run."""
    a_texts = [f"keep going {i}" for i in range(4)]
    b_texts = [f"cancel me {i}" for i in range(4)]
    kw = dict(max_new_tokens=24, temperature=0.0)
    solo_a = _solo(tiny_ecfg, byte_tok, _reqs(byte_tok, a_texts, **kw))

    b = _batcher(tiny_ecfg, byte_tok)
    got_a, got_b, done = {}, {}, []
    ctx_a = JobCtx(
        job_id="A",
        pending=_reqs(byte_tok, a_texts, **kw),
        on_result=lambda r: got_a.__setitem__(r.row_id, r),
        seq=0,
    )
    ctx_b = JobCtx(
        job_id="B",
        pending=_reqs(byte_tok, b_texts, **kw),
        on_result=lambda r: got_b.__setitem__(r.row_id, r),
        seq=1,
    )
    # cancel B once it is mid-generation (some tokens out, rows not done)
    ctx_b.should_cancel = lambda: ctx_b.stats["out"] >= 5
    state = b.run_multi(
        [ctx_a, ctx_b],
        on_job_done=lambda c, o: done.append((c.job_id, o)),
    )
    assert state == "completed"
    assert ("B", "cancelled") in done
    assert ("A", "completed") in done
    assert {i: r.token_ids for i, r in got_a.items()} == {
        i: r.token_ids for i, r in solo_a.items()
    }
    # B's live rows were emitted as cancelled
    assert any(r.finish_reason == "cancelled" for r in got_b.values())


def test_cobatch_per_job_prefix_caches(tiny_ecfg, byte_tok):
    """Co-batched jobs each carry their OWN shared-prefix pages; the
    pool is fully restored at session end."""
    a_texts = [
        "SYSTEM PROMPT ALPHA VERSION: judge the following: " + t
        for t in ["one", "two tw", "three"]
    ]
    b_texts = [
        "completely different shell for job bravo here: " + t
        for t in ["x", "yy", "zzz"]
    ]
    kw = dict(max_new_tokens=6, temperature=0.0)
    b = _batcher(tiny_ecfg, byte_tok)
    free0 = b.free_page_count
    got_a, got_b = {}, {}
    ctx_a = JobCtx(
        job_id="A", pending=_reqs(byte_tok, a_texts, **kw),
        on_result=lambda r: got_a.__setitem__(r.row_id, r), seq=0,
    )
    ctx_b = JobCtx(
        job_id="B", pending=_reqs(byte_tok, b_texts, **kw),
        on_result=lambda r: got_b.__setitem__(r.row_id, r), seq=1,
    )
    state = b.run_multi([ctx_a, ctx_b], on_job_done=lambda c, o: None)
    assert state == "completed"
    assert len(got_a) == 3 and len(got_b) == 3
    assert b.free_page_count == free0
    # both prefixes engaged: total prefilled tokens < sum of full rows
    full = sum(
        len(byte_tok.encode(t)) for t in a_texts + b_texts
    )
    assert b.prefill_tokens < full
    # outputs equal solo runs despite two prefixes sharing the pool
    solo_a = _solo(tiny_ecfg, byte_tok, _reqs(byte_tok, a_texts, **kw))
    assert {i: r.token_ids for i, r in got_a.items()} == {
        i: r.token_ids for i, r in solo_a.items()
    }


# ---------------------------------------------------------------------------
# engine-level attach (LocalEngine)
# ---------------------------------------------------------------------------


def _wait(eng, job_id, *, until, timeout):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s = eng.job_status(job_id)
        if until(s):
            return s
        time.sleep(0.03)
    raise TimeoutError(f"job {job_id} stuck in {eng.job_status(job_id)}")


def test_engine_same_model_p0_attaches_without_preempting(
    tiny_ecfg, tmp_path, monkeypatch
):
    """Engine-level: a same-model p0 job submitted while a p1 job runs
    ATTACHES to the running session — it SUCCEEDs while p1 stays
    RUNNING (never requeued), and both finish with complete outputs."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.interfaces import JobStatus

    eng = LocalEngine(tiny_ecfg)
    p1 = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": [f"long batch row {i}" for i in range(12)],
            "sampling_params": {"max_new_tokens": 40},
            "job_priority": 1,
        }
    )
    _wait(eng, p1, until=lambda s: s == "RUNNING", timeout=120)
    p0 = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": ["quick a", "quick b", "quick c"],
            "sampling_params": {"max_new_tokens": 4},
            "job_priority": 0,
        }
    )
    seen_queued_again = []

    def until_p0_done(s):
        # record any p1 requeue while waiting (attach must NOT requeue)
        if eng.job_status(p1) == "QUEUED":
            seen_queued_again.append(True)
        return JobStatus(s).is_terminal()

    _wait(eng, p0, until=until_p0_done, timeout=300)
    assert eng.job_status(p0) == "SUCCEEDED"
    # p1 kept its session: never requeued, still running (or finished)
    assert not seen_queued_again
    assert eng.job_status(p1) in ("RUNNING", "SUCCEEDED")
    _wait(
        eng, p1, until=lambda s: JobStatus(s).is_terminal(), timeout=300
    )
    assert eng.job_status(p1) == "SUCCEEDED"
    res1 = eng.job_results(p1)
    assert len(res1["outputs"]) == 12
    assert all(o is not None for o in res1["outputs"])
    res0 = eng.job_results(p0, include_cumulative_logprobs=True)
    assert len(res0["outputs"]) == 3
    assert all(o is not None for o in res0["outputs"])
    # per-job accounting stayed separate (output_tokens re-tokenizes
    # the decoded text, so compare magnitudes, not sampled counts)
    rec0 = eng.get_job(p0)
    rec1 = eng.get_job(p1)
    assert rec0["output_tokens"] > 0
    assert rec1["output_tokens"] > rec0["output_tokens"]


def test_engine_different_model_still_preempts(
    tiny_ecfg, tmp_path, monkeypatch
):
    """A higher-priority job on a DIFFERENT model cannot attach — the
    running batch yields (reference two-priority preemption), the p0
    job runs, and the batch resumes row-granularly."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.interfaces import JobStatus

    eng = LocalEngine(tiny_ecfg)
    p1 = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": [f"long batch row {i}" for i in range(10)],
            "sampling_params": {"max_new_tokens": 40},
            "job_priority": 1,
        }
    )
    _wait(eng, p1, until=lambda s: s == "RUNNING", timeout=120)
    p0 = eng.submit_batch_inference(
        {
            "model": "tiny-moe",
            "inputs": ["quick a", "quick b"],
            "sampling_params": {"max_new_tokens": 4},
            "job_priority": 0,
        }
    )
    _wait(eng, p0, until=lambda s: JobStatus(s).is_terminal(), timeout=300)
    assert eng.job_status(p0) == "SUCCEEDED"
    # single worker + different model: p0 finishing first proves p1
    # yielded mid-run
    assert eng.job_status(p1) != "SUCCEEDED"
    _wait(
        eng, p1, until=lambda s: JobStatus(s).is_terminal(), timeout=300
    )
    assert eng.job_status(p1) == "SUCCEEDED"
    res1 = eng.job_results(p1)
    assert len(res1["outputs"]) == 10
    assert all(o is not None for o in res1["outputs"])
