"""Fleet observability plane (OBSERVABILITY.md "Fleet observability"):
cross-replica trace propagation + stitching, federated /metrics under
the ``replica`` label, the fleet SLO monitor, and the trace-replay
harness.

Layout mirrors the plane's layers:

1. unit — trace stitching against canned transports (golden Perfetto
   export with pinned clocks, skew re-anchor clamp), federation delta/
   cache/gauge semantics, the telemetry-off zero-op contract, and the
   replay capture/synthesize/round-trip/driver pieces (no engines);
2. fleet-monitor units — FLEET_RULES fire and resolve on hand-driven
   ticks, alert events embed route-latency exemplar trace ids;
3. integration over TWO live engines behind a live router — the
   acceptance stitch (router route_pick→first_byte AND replica
   admit_gateway→decode_window in one timeline, no negative offsets),
   federated /metrics, monitor endpoints, /replay-log + CLI;
4. protocol skew both directions + chaos: ``fleet.replica_crash``
   fires AND resolves a stock rule on the live monitor.

Destructive tests build their OWN servers/routers around the shared
engines so the module fixture stays healthy (same discipline as
tests/test_fleet.py).
"""

import json
import sys
import threading
import time
from pathlib import Path

import pytest
import requests

from sutro_tpu import telemetry
from sutro_tpu.engine import faults
from sutro_tpu.engine.api import LocalEngine
from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.fleet import frames
from sutro_tpu.fleet import replay as replay_mod
from sutro_tpu.fleet.membership import CLOSED
from sutro_tpu.fleet.obs import (
    FLEET_AGG,
    FLEET_RULES,
    FleetMonitor,
    FleetObservability,
)
from sutro_tpu.fleet.router import FleetRouter, start_fleet_thread
from sutro_tpu.server import EngineHTTPHandler, start_server_thread
from sutro_tpu.telemetry import traceexport
from sutro_tpu.telemetry.registry import MetricsRegistry

GOLDEN = Path(__file__).parent / "data" / "fleet_trace_export.golden"

pytestmark = pytest.mark.skipif(
    not telemetry.ENABLED, reason="fleet observability needs telemetry"
)


def _wait(pred, timeout=30.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------
# 1a. stitching: golden export with pinned clocks + skew clamp
# ---------------------------------------------------------------------

#: replica-side trace half with PINNED clocks: created 4ms after the
#: router's trace on the replica's wall clock, so the stitcher must
#: re-anchor every replica span by +0.004s onto the router timeline
_REPLICA_CREATED_SKEW_S = 0.004


def _replica_half(created_unix):
    from sutro_tpu.telemetry.traces import TraceStore

    store = TraceStore()
    tr = store.start_trace(
        "tr-fr-1", "interactive", {"model": "tiny-dense"},
        t0_mono=500.0, created_unix=created_unix,
    )
    tr.add("admit_gateway", 500.0, 0.0004, {"slot": 0})
    tr.add("prefill", 500.0008, 0.003, {"tokens": 7})
    tr.add("decode_window", 500.004, 0.0025, {"steps": 4})
    tr.end("ok")
    return tr.to_doc()


def _golden_stitched_doc(skew_s=_REPLICA_CREATED_SKEW_S):
    rdoc = _replica_half(1700000000.0 + skew_s)

    def canned_send(method, url, timeout=None):
        assert method == "get" and url.endswith("/trace-doc/tr-fr-1")
        return frames.trace_doc_frame(1700000000.2, rdoc)

    obs = FleetObservability(send=canned_send)
    tid = obs.trace_begin(
        "interactive", {"kind": "chat", "model": "tiny-dense"},
        t0_mono=100.0, created_unix=1700000000.0,
    )
    assert tid == "tr-fr-1"
    obs.span(tid, "route_pick", 100.0, 0.0031, {"n_candidates": 2})
    obs.span(tid, "affinity_probe", 100.0005, 0.0018, {"n_healthy": 2})
    obs.annotate(tid, {"replica": "r1", "replica_url": "http://rb"})
    obs.span(
        tid, "upstream_connect", 100.0032, 0.0009,
        {"rid": "r1", "status": 200},
    )
    obs.event(tid, "first_byte", {"rid": "r1"}, t_mono=100.0125)
    obs.end(tid, "ok")
    return obs.stitch_trace(tid)


def test_stitched_export_matches_golden():
    assert GOLDEN.exists(), (
        "golden file missing (regen: python tests/test_fleet_obs.py "
        "--regen-golden)"
    )
    doc = _golden_stitched_doc()
    assert traceexport.render(
        traceexport.stitched_to_chrome(doc)
    ) == GOLDEN.read_text()


def test_stitched_doc_shape_and_reanchor():
    doc = _golden_stitched_doc()
    assert doc["kind"] == "fleet" and doc["trace_id"] == "tr-fr-1"
    procs = doc["processes"]
    assert [p["process"] for p in procs] == ["router", "replica r1"]
    assert procs[0]["role"] == "router" and procs[0]["t_off_s"] == 0.0
    assert procs[1]["t_off_s"] == _REPLICA_CREATED_SKEW_S
    merged = traceexport.stitched_spans(doc)
    names = [s["name"] for s in merged]
    assert {"route_pick", "first_byte", "admit_gateway",
            "decode_window"} <= set(names)
    # no negative offsets after re-anchoring: every span sits at or
    # after the router's request arrival, and the replica's admission
    # never renders before the router picked it
    assert all(s["t0_s"] >= 0.0 for s in merged)
    by_name = {s["name"]: s for s in merged}
    assert by_name["admit_gateway"]["t0_s"] >= by_name["route_pick"]["t0_s"]


def test_stitch_clamps_negative_clock_skew():
    """A replica whose wall clock runs BEHIND the router's can never
    push its spans before the request arrived: t_off clamps at 0."""
    doc = _golden_stitched_doc(skew_s=-0.25)
    assert doc["processes"][1]["t_off_s"] == 0.0
    assert all(
        s["t0_s"] >= 0.0 for s in traceexport.stitched_spans(doc)
    )


def test_stitch_degrades_to_router_only_when_replica_gone():
    def dead_send(method, url, timeout=None):
        raise OSError("connection refused")

    obs = FleetObservability(send=dead_send)
    tid = obs.trace_begin("interactive", t0_mono=1.0, created_unix=2.0)
    obs.span(tid, "route_pick", 1.0, 0.001)
    obs.annotate(tid, {"replica": "r0", "replica_url": "http://gone"})
    obs.end(tid, "error")
    doc = obs.stitch_trace(tid)
    assert [p["process"] for p in doc["processes"]] == ["router"]
    # junk instead of a trace-doc frame degrades identically
    obs2 = FleetObservability(send=lambda *a, **k: {"t": "nope"})
    tid2 = obs2.trace_begin("interactive", t0_mono=1.0, created_unix=2.0)
    obs2.annotate(tid2, {"replica": "r0", "replica_url": "http://old"})
    obs2.end(tid2)
    assert len(obs2.stitch_trace(tid2)["processes"]) == 1
    assert obs2.stitch_trace("tr-fr-404") is None


# ---------------------------------------------------------------------
# 1b. federation: delta / cache / label / gauge semantics
# ---------------------------------------------------------------------


class _FakeMembership:
    def __init__(self, rows):
        self.rows = rows

    def all(self):
        return list(self.rows)


def _snap_with_counter(n):
    """A replica-side snapshot carrying real global metric names (the
    mirror registry only admits metrics the router also declares)."""
    reg = MetricsRegistry()
    c = reg.counter(
        "sutro_interactive_requests_total", "requests",
        labels=("outcome",), max_series=8,
    )
    for _ in range(n):
        c.inc(1, "ok")
    g = reg.gauge("sutro_interactive_active", "in flight")
    g.set(3.0)
    return reg.export_snapshot()


def test_federate_delta_cache_and_gauge_exclusion():
    sent = []

    def canned_send(method, url, timeout=None):
        sent.append(url)
        return frames.metrics_snapshot_frame(0.0, canned_send.snap)

    canned_send.snap = _snap_with_counter(5)
    obs = FleetObservability(scrape_interval_s=10.0, send=canned_send)
    mem = _FakeMembership(
        [
            {"rid": "rA", "url": "http://a", "state": CLOSED,
             "fleet_obs": True},
            {"rid": "rOld", "url": "http://b", "state": CLOSED,
             "fleet_obs": False},  # pre-obs replica: never scraped
        ]
    )
    assert obs.federate(mem, now=100.0) == 1
    assert sent == ["http://a/metrics-snapshot"]

    def remote_counter(worker):
        shard = obs.registry._remote[worker]
        return sum(
            v for (n, _lv), v in shard["counters"].items()
            if n == "sutro_interactive_requests_total"
        )

    assert remote_counter("rA") == 5
    assert remote_counter(FLEET_AGG) == 5
    # within the scrape interval: cache hit, no upstream traffic
    assert obs.federate(mem, now=100.5) == 0
    assert len(sent) == 1
    # next interval ingests the DELTA (cumulative stays exact)
    canned_send.snap = _snap_with_counter(8)
    assert obs.federate(mem, now=111.0) == 1
    assert remote_counter("rA") == 8
    assert remote_counter(FLEET_AGG) == 8
    # gauges are NOT federated — a replica gauge is that process's
    # "now", and relabeling it would corrupt the router's own census
    # strings (sutro_fleet_replicas{state="healthy"} N stays exact)
    assert obs.registry._remote["rA"]["gauges"] == {}
    text = obs.registry.to_prometheus()
    assert 'replica="rA"' in text and 'replica="_fleet"' in text
    assert not any(
        "sutro_interactive_active" in ln and 'replica="' in ln
        for ln in text.splitlines()
    )


def test_telemetry_off_is_zero_op_and_zero_send(monkeypatch):
    def no_send(method, url, timeout=None):
        raise AssertionError("telemetry off must not touch the network")

    obs = FleetObservability(send=no_send)
    monkeypatch.setattr(telemetry, "ENABLED", False)
    tid = obs.trace_begin("interactive", {"kind": "chat"})
    assert tid is None
    # the whole surface accepts the None id silently
    obs.span(tid, "route_pick", 0.0, 0.001)
    obs.event(tid, "first_byte")
    obs.annotate(tid, {"replica": "r0"})
    obs.end(tid)
    obs.observe_route(0.001, "chat", trace_id=tid)
    obs.refresh_router_gauges({"n_healthy": 2, "replicas": []})
    mem = _FakeMembership(
        [{"rid": "rA", "url": "http://a", "state": CLOSED,
          "fleet_obs": True}]
    )
    assert obs.federate(mem, now=1e9) == 0
    assert len(obs.traces.ids()) == 0
    assert obs.route_latency_summary() is None


def test_observe_route_records_summary_and_exemplar():
    obs = FleetObservability(send=lambda *a, **k: None)
    obs.observe_route(0.002, "chat", trace_id="tr-fr-901")
    obs.observe_route(0.004, "completions", trace_id="tr-fr-902")
    summary = obs.route_latency_summary()
    assert summary["count"] == 2 and summary["p99_s"] > 0
    tids = {
        ex.get("trace_id")
        for ex in obs.registry.exemplars("sutro_fleet_route_seconds")
    }
    assert "tr-fr-901" in tids or "tr-fr-902" in tids


# ---------------------------------------------------------------------
# 1c. replay: capture, synthesis, file format, driver
# ---------------------------------------------------------------------


def test_synthetic_records_deterministic_round_robin():
    a = replay_mod.synthetic_records(n=8, n_sessions=4)
    b = replay_mod.synthetic_records(n=8, n_sessions=4)
    assert a == b
    # sessions interleave round-robin: consecutive turns of one
    # session are n_sessions arrivals apart (the predecessor's KV has
    # time to checkpoint before the follow-up turn replays)
    assert [r["session_id"] for r in a[:4]] == [
        "replay-sess-0", "replay-sess-1", "replay-sess-2",
        "replay-sess-3",
    ]
    assert a[4]["session_id"] == "replay-sess-0"
    offs = [r["arrival_offset_s"] for r in a]
    assert offs == sorted(offs) and offs[0] > 0
    assert all(r["body"]["session_id"] == r["session_id"] for r in a)


def test_records_from_traces_rebases_and_caps(tmp_path):
    from sutro_tpu.telemetry.traces import TraceStore

    store = TraceStore()
    body = {"model": "tiny-dense", "messages": [], "stream": True}
    store.start_trace(
        "tr-fr-2", "interactive",
        replay_mod.replay_attrs(body, True, True, 1000.5, 64),
    )
    store.start_trace(
        "tr-fr-1", "interactive",
        replay_mod.replay_attrs(body, True, True, 1000.2, 64),
    )
    # oversized body: captured as a record, but not replayable
    store.start_trace(
        "tr-fr-3", "interactive",
        replay_mod.replay_attrs(
            body, False, False, 1000.9,
            replay_mod.REPLAY_BODY_MAX_BYTES + 1,
        ),
    )
    # non-request trace (no arrival stamp) is ignored
    store.start_trace("tr-fr-4", "probe", {"kind": "probe"})
    recs = replay_mod.records_from_traces(store)
    assert [r["arrival_offset_s"] for r in recs] == [0.0, 0.3, 0.7]
    assert recs[0]["body"] == body and "body" not in recs[2]
    assert recs[2]["kind"] == "completions"
    path = tmp_path / "w.jsonl"
    replay_mod.dump_jsonl(recs, path)
    assert replay_mod.load_jsonl(path) == recs


def test_replay_driver_honors_arrivals_open_loop():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    hits = []

    class Stub(BaseHTTPRequestHandler):
        def do_POST(self):
            hits.append((time.perf_counter(), self.path))
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            data = b'data: {"ok": true}\n\ndata: [DONE]\n\n'
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *args):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Stub)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    recs = [
        {"arrival_offset_s": 0.0, "kind": "chat",
         "body": {"model": "m"}},
        {"arrival_offset_s": 0.4, "kind": "completions",
         "body": {"model": "m"}},
        {"arrival_offset_s": 0.5, "kind": "chat"},  # no body: skipped
    ]
    try:
        doc = replay_mod.replay(url, recs, speedup=2.0, timeout=30.0)
    finally:
        srv.shutdown()
        srv.server_close()
    assert doc["n"] == 3 and doc["sent"] == 2 and doc["ok"] == 2
    assert doc["skipped_no_body"] == 1 and doc["errors"] == []
    assert doc["ttft"]["count"] == 2 and doc["ttft"]["p99_s"] > 0
    paths = sorted(p for _, p in hits)
    assert paths == ["/v1/chat/completions", "/v1/completions"]
    # 0.4s offset at 2x replays ~0.2s after start, never before
    ts = sorted(t for t, _ in hits)
    assert ts[1] - ts[0] >= 0.15


# ---------------------------------------------------------------------
# 2. fleet monitor: rules fire and resolve on hand-driven ticks
# ---------------------------------------------------------------------


def test_fleet_rules_catalog_is_stable():
    names = {r.name for r in FLEET_RULES}
    assert names == {
        "fleet_ttft_p99", "fleet_failover_rate",
        "fleet_prefix_hit_floor", "fleet_replica_imbalance",
        "fleet_replicas_down",
    }
    assert all(r.workload == "fleet" for r in FLEET_RULES)


def test_fleet_monitor_fires_and_resolves_failover_rate():
    router = FleetRouter([], probe_interval=3600.0)
    mon = FleetMonitor(router, interval_s=0.05, window_s=0.4)
    # an exemplar on the route histogram BEFORE the alert fires: the
    # firing event must point at a concrete stitched timeline
    router.obs.observe_route(0.003, "chat", trace_id="tr-fr-7171")
    mon.tick()
    time.sleep(0.05)
    router.counters["failover_stream_error"] += 10
    # for_ticks=2 debounce: one breaching tick arms (pending), the
    # second fires — while the spike is still inside the window
    mon.tick()
    time.sleep(0.05)
    mon.tick()
    doc = mon.snapshot_doc()
    active = {a["name"] for a in doc["alerts"]["active"]}
    assert "fleet_failover_rate" in active
    fired = [
        e for e in doc["alerts"]["events"]
        if e["rule"] == "fleet_failover_rate" and e["state"] == "firing"
    ]
    assert fired and "tr-fr-7171" in fired[0]["exemplar_trace_ids"]
    # chaos over: once the spike ages out of the window, the rate
    # clears the hysteresis level and the rule resolves
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        time.sleep(0.1)
        mon.tick()
        doc = mon.snapshot_doc()
        active = {a["name"] for a in doc["alerts"]["active"]}
        if "fleet_failover_rate" not in active:
            break
    assert "fleet_failover_rate" not in active
    assert any(
        e["rule"] == "fleet_failover_rate" and e["state"] == "resolved"
        for e in doc["alerts"]["events"]
    )


def test_fleet_monitor_replicas_down_is_census_driven():
    """A dead replica pages even when the fleet is idle: the rule reads
    the membership census, not traffic."""
    router = FleetRouter(
        ["http://127.0.0.1:1"], probe_interval=3600.0
    )
    mon = FleetMonitor(router, interval_s=0.05, window_s=0.4)
    router.membership.note_probe_success(
        "r0", {"ready": True, "draining": False, "load": {}}
    )
    mon.tick()
    assert "fleet_replicas_down" not in {
        a["name"] for a in mon.snapshot_doc()["alerts"]["active"]
    }
    for _ in range(10):  # breaker opens past the fail threshold
        router.membership.note_probe_failure("r0")
    mon.tick()  # pending (for_ticks=2)
    time.sleep(0.05)
    mon.tick()  # firing
    doc = mon.snapshot_doc()
    assert doc["stats"]["n_unhealthy"] >= 1.0
    assert "fleet_replicas_down" in {
        a["name"] for a in doc["alerts"]["active"]
    }
    assert doc["verdicts"]["fleet"]["verdict"] != "healthy"


# ---------------------------------------------------------------------
# 3. integration: two live engines behind a live router
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet(tmp_path_factory, monkeypatch_module):
    home = tmp_path_factory.mktemp("fleet-obs-home")
    monkeypatch_module.setenv("SUTRO_HOME", str(home))
    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", max_new_tokens=8,
        interactive_slots=2,
    )
    eng_a = LocalEngine(ecfg)
    eng_b = LocalEngine(ecfg)
    srv_a, _, url_a = start_server_thread(eng_a)
    srv_b, _, url_b = start_server_thread(eng_b)
    router, fsrv, _, furl = start_fleet_thread(
        [url_a, url_b], probe_interval=0.2,
        monitor_interval=0.25, monitor_window=3.0,
    )
    from sutro_tpu.sdk import Sutro

    sdk = Sutro(api_key="fleet-key", base_url=furl, backend="fleet")
    _wait(
        lambda: router.membership.snapshot()["n_healthy"] == 2,
        timeout=15, what="both replicas healthy",
    )

    class F:
        pass

    f = F()
    f.eng_a, f.eng_b = eng_a, eng_b
    f.url_a, f.url_b = url_a, url_b
    f.router, f.furl, f.sdk = router, furl, sdk
    f.home = str(home)
    yield f
    faults.clear()
    router.stop()
    fsrv.shutdown()
    srv_a.shutdown()
    srv_b.shutdown()
    eng_a.close(timeout=10)
    eng_b.close(timeout=10)


def _routed_chat(furl, content, session=None, stream=True):
    body = {
        "model": "tiny-dense",
        "messages": [{"role": "user", "content": content}],
        "max_tokens": 4,
        "temperature": 0,
        "stream": stream,
    }
    if session:
        body["session_id"] = session
    r = requests.post(
        furl + "/v1/chat/completions", json=body, stream=stream,
        timeout=120,
    )
    assert r.status_code == 200, r.text[:300]
    if stream:
        lines = [ln for ln in r.iter_lines() if ln]
        assert lines[-1] == b"data: [DONE]"
    return r


def test_stitched_trace_e2e_through_two_replica_fleet(fleet):
    """THE acceptance stitch: one request through the fleet yields a
    single timeline with router spans (route_pick → first_byte) AND
    replica spans (admit_gateway → decode_window), all offsets
    non-negative after wall-clock re-anchoring."""
    before = set(fleet.router.obs.traces.ids())
    _routed_chat(fleet.furl, "stitch me a timeline")
    new = [t for t in fleet.router.obs.traces.ids() if t not in before]
    assert len(new) == 1
    tid = new[0]
    assert tid.startswith("tr-fr-")
    _wait(
        lambda: fleet.router.obs.traces.get(tid).finished,
        timeout=10, what="router trace finished",
    )
    doc = fleet.router.obs.stitch_trace(tid)
    assert [p["process"] for p in doc["processes"]][0] == "router"
    assert len(doc["processes"]) == 2
    merged = traceexport.stitched_spans(doc)
    names = {s["name"] for s in merged}
    assert {"route_pick", "upstream_connect", "first_byte"} <= names
    assert {"admit_gateway", "decode_window"} <= names
    assert all(s["t0_s"] >= 0.0 for s in merged), merged
    # and the HTTP surface serves the same thing as raw Chrome JSON
    r = requests.get(f"{fleet.furl}/trace/{tid}", timeout=10)
    assert r.status_code == 200
    chrome = r.json()
    assert chrome["otherData"]["trace_id"] == tid
    procs = chrome["otherData"]["processes"]
    assert procs[0] == "router" and procs[1].startswith("replica r")
    assert requests.get(
        f"{fleet.furl}/trace/tr-fr-404404", timeout=10
    ).status_code == 404


def test_federated_metrics_replica_label_and_exemplars(fleet):
    _routed_chat(fleet.furl, "metrics fodder", stream=False)
    time.sleep(0.3)  # past the scrape-cache interval
    text = requests.get(fleet.furl + "/metrics", timeout=10).text
    # per-replica serving series next to the fleet aggregate
    ttft_lines = [
        ln for ln in text.splitlines()
        if ln.startswith("sutro_interactive_ttft_seconds")
    ]
    assert any('replica="r0"' in ln or 'replica="r1"' in ln
               for ln in ttft_lines), ttft_lines[:5]
    assert any('replica="_fleet"' in ln for ln in ttft_lines)
    # the router's own series: route latency with exemplar trace ids
    assert "sutro_fleet_route_seconds" in text
    assert "tr-fr-" in text
    # census gauges stay NON-federated and exact
    assert 'sutro_fleet_replicas{state="healthy"} 2' in text


def test_fleet_snapshot_surfaces_probe_only_and_route_latency(fleet):
    doc = fleet.sdk.get_fleet()
    assert doc["probe_only_routes"] == 0
    lat = doc["route_latency"]
    assert lat is not None and lat["count"] >= 1 and lat["p99_s"] > 0


def test_fleet_monitor_endpoints_and_stream(fleet):
    _wait(
        lambda: fleet.router.monitor is not None
        and fleet.router.monitor.snapshot_doc()["ticks"] >= 1,
        timeout=15, what="first monitor tick",
    )
    doc = fleet.sdk.get_fleet_monitor()
    assert doc["running"] and doc["degraded"] is None
    assert {r["name"] for r in doc["rules"]} == {
        r.name for r in FLEET_RULES
    }
    assert doc["verdicts"]["fleet"]["verdict"] in (
        "healthy", "degraded", "down", "insufficient_data",
    )
    r = requests.get(
        fleet.furl + "/fleet-monitor/stream?ticks=2", stream=True,
        timeout=30,
    )
    assert r.status_code == 200
    recs = [json.loads(ln) for ln in r.iter_lines() if ln]
    assert len(recs) == 3 and recs[-1]["t"] == "end"
    assert recs[-1]["degraded"] is None


def test_replay_log_roundtrip_and_cli(fleet, tmp_path, monkeypatch):
    from click.testing import CliRunner

    from sutro_tpu import cli as cli_mod

    _routed_chat(
        fleet.furl, "record this turn", session="replay-capture-sess"
    )
    records = fleet.sdk.get_replay_log()
    assert records and all("arrival_offset_s" in r for r in records)
    withbody = [r for r in records if r.get("body")]
    assert withbody, "small chat bodies must be captured replayable"
    assert withbody[-1]["kind"] == "chat"
    runner = CliRunner()
    assert runner.invoke(
        cli_mod.cli, ["set-base-url", fleet.furl]
    ).exit_code == 0
    assert runner.invoke(
        cli_mod.cli, ["set-backend", "fleet"]
    ).exit_code == 0
    out_path = tmp_path / "captured.jsonl"
    out = runner.invoke(
        cli_mod.cli, ["replay", "record", "-o", str(out_path)]
    )
    assert out.exit_code == 0, out.output
    loaded = replay_mod.load_jsonl(out_path)
    assert [r.get("session_id") for r in loaded] == [
        r.get("session_id") for r in records
    ]
    # fleet status renders the new observability lines
    out = runner.invoke(cli_mod.cli, ["fleet", "status"])
    assert out.exit_code == 0, out.output
    assert "probe-only routes" in out.output
    assert "route latency" in out.output


# ---------------------------------------------------------------------
# 4a. protocol skew, both directions
# ---------------------------------------------------------------------


def test_skew_new_router_old_replica_degrades_not_crashes(fleet):
    """An old replica (no fleet-state/warm/obs endpoints) behind a new
    router: routes still work probe-only, the forwarded X-Sutro-Trace
    header is ignored harmlessly, /trace/{id} degrades to router-only
    lanes, and federation skips the replica without erroring."""
    eng = fleet.eng_b

    class LegacyHandler(EngineHTTPHandler):
        engine = eng

        def do_GET(self):  # noqa: N802
            head = self.path.split("?")[0].strip("/").partition("/")[0]
            if head in ("fleet-state", "metrics-snapshot", "trace-doc"):
                self._error(404, f"Unknown endpoint GET /{head}")
                return
            super().do_GET()

        def do_POST(self):  # noqa: N802
            head = self.path.split("?")[0].strip("/").partition("/")[0]
            if head == "fleet-warm":
                self._error(404, f"Unknown endpoint POST /{head}")
                return
            super().do_POST()

    from http.server import ThreadingHTTPServer

    srv = ThreadingHTTPServer(("127.0.0.1", 0), LegacyHandler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    legacy_url = f"http://127.0.0.1:{srv.server_address[1]}"
    router2, fsrv2, _, furl2 = start_fleet_thread(
        [legacy_url], probe_interval=0.2
    )
    try:
        _wait(
            lambda: router2.membership.snapshot()["n_healthy"] == 1,
            timeout=15, what="legacy replica probed healthy",
        )
        assert not router2.membership.get("r0").get("fleet_obs")
        _routed_chat(furl2, "legacy skew route", stream=False)
        assert router2.counters["probe_only_routes"] >= 1
        tid = router2.obs.traces.ids()[-1]
        r = requests.get(f"{furl2}/trace/{tid}", timeout=10)
        assert r.status_code == 200
        assert r.json()["otherData"]["processes"] == ["router"]
        # federation sweeps right past the pre-obs replica
        text = requests.get(furl2 + "/metrics", timeout=10).text
        assert 'sutro_fleet_replicas{state="healthy"} 1' in text
        assert 'replica="r0"' not in text
    finally:
        router2.stop()
        fsrv2.shutdown()
        srv.shutdown()
        srv.server_close()


def test_skew_old_router_new_replica_mints_own_trace(fleet):
    """The other direction: a router that predates the obs plane sends
    no X-Sutro-Trace — the replica mints its own trace id and all obs
    endpoints still answer. With the header, the replica ADOPTS the
    router's id so /trace-doc/{id} can serve the far half."""
    before = set(telemetry.TRACES.ids())
    body = {
        "model": "tiny-dense",
        "messages": [{"role": "user", "content": "old router turn"}],
        "max_tokens": 4,
        "temperature": 0,
    }
    r = requests.post(
        fleet.url_a + "/v1/chat/completions", json=body, timeout=120
    )
    assert r.status_code == 200
    minted = [t for t in telemetry.TRACES.ids() if t not in before]
    assert minted and not minted[0].startswith("tr-fr-")
    # adoption: a router-assigned id becomes the replica trace id
    ext = "tr-fr-987654"
    r = requests.post(
        fleet.url_a + "/v1/chat/completions", json=body,
        headers={"X-Sutro-Trace": ext}, timeout=120,
    )
    assert r.status_code == 200
    assert telemetry.TRACES.get(ext) is not None
    raw = requests.get(
        f"{fleet.url_a}/trace-doc/{ext}", timeout=10
    ).json()
    parsed = frames.parse_trace_doc(raw)
    assert parsed is not None and parsed["doc"]["trace_id"] == ext
    # and the snapshot endpoint the router federates from
    raw = requests.get(
        fleet.url_a + "/metrics-snapshot", timeout=10
    ).json()
    assert frames.parse_metrics_snapshot(raw) is not None


# ---------------------------------------------------------------------
# 4b. chaos: a stock rule fires AND resolves on the live monitor
# ---------------------------------------------------------------------


def test_chaos_replica_crash_fires_and_resolves_fleet_rule(fleet):
    """fleet.replica_crash mid-stream -> failover_stream_error spikes
    -> fleet_failover_rate fires on the live monitor (with exemplar
    trace ids pointing at stitched timelines); chaos ends -> the spike
    ages out of the window -> the rule RESOLVES. `sutro fleet watch`
    renders the firing frame."""
    from click.testing import CliRunner

    from sutro_tpu import cli as cli_mod

    srv, _, url = start_server_thread(fleet.eng_a)
    router2, fsrv2, _, furl2 = start_fleet_thread(
        [url], probe_interval=0.2, stall_timeout=10.0,
        monitor_interval=0.1, monitor_window=1.0,
    )
    try:
        _wait(
            lambda: router2.membership.snapshot()["n_healthy"] == 1,
            timeout=15, what="replica healthy",
        )
        _routed_chat(furl2, "warm the streamed path")
        faults.install(faults.parse_plan(json.dumps([
            {"site": "fleet.replica_crash", "kind": "crash",
             "job": "stream:", "nth": 3, "times": 1}
        ])))
        r = requests.post(
            furl2 + "/v1/chat/completions",
            json={
                "model": "tiny-dense",
                "messages": [
                    {"role": "user", "content": "stream then die"}
                ],
                "max_tokens": 8,
                "stream": True,
            },
            stream=True,
            timeout=(5, 60),
        )
        assert r.status_code == 200
        assert any(
            '"error"' in ln.decode() for ln in r.iter_lines() if ln
        )
        faults.clear()
        assert router2.counters["failover_stream_error"] == 1

        def monitor_doc():
            resp = requests.get(furl2 + "/fleet-monitor", timeout=10)
            assert resp.status_code == 200
            return resp.json()["fleet_monitor"]

        def active_names():
            return {
                a["name"]
                for a in monitor_doc()["alerts"]["active"]
            }

        _wait(
            lambda: "fleet_failover_rate" in active_names(),
            timeout=15, what="fleet_failover_rate firing",
        )
        doc = monitor_doc()
        fired = [
            e for e in doc["alerts"]["events"]
            if e["rule"] == "fleet_failover_rate"
            and e["state"] == "firing"
        ]
        assert fired and fired[0]["exemplar_trace_ids"], fired
        assert all(
            t.startswith("tr-fr-")
            for t in fired[0]["exemplar_trace_ids"]
        )
        # the operator view of the firing frame
        runner = CliRunner()
        assert runner.invoke(
            cli_mod.cli, ["set-base-url", furl2]
        ).exit_code == 0
        assert runner.invoke(
            cli_mod.cli, ["set-backend", "fleet"]
        ).exit_code == 0
        out = runner.invoke(cli_mod.cli, ["fleet", "watch", "--once"])
        assert out.exit_code == 0, out.output
        assert "sutro fleet watch" in out.output
        assert "fleet_failover_rate" in out.output
        # chaos over: the rule must RESOLVE, not latch
        _wait(
            lambda: "fleet_failover_rate" not in active_names(),
            timeout=20, what="fleet_failover_rate resolved",
        )
        assert any(
            e["rule"] == "fleet_failover_rate"
            and e["state"] == "resolved"
            for e in monitor_doc()["alerts"]["events"]
        )
        out = runner.invoke(cli_mod.cli, ["fleet", "watch", "--once"])
        assert out.exit_code == 0, out.output
    finally:
        faults.clear()
        router2.stop()
        fsrv2.shutdown()
        srv.shutdown()
        srv.server_close()


if __name__ == "__main__":
    if "--regen-golden" in sys.argv:
        GOLDEN.parent.mkdir(parents=True, exist_ok=True)
        GOLDEN.write_text(
            traceexport.render(
                traceexport.stitched_to_chrome(_golden_stitched_doc())
            )
        )
        print(f"wrote {GOLDEN}")
