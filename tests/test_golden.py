"""Golden path on real HF-format weights (BASELINE config #1 analog).

Two layers of evidence that the engine decodes real checkpoints
correctly (the README quickstart path, /root/reference/README.md:124-160):

1. **Torch parity**: a real (tiny) Qwen3 architecture instantiated by
   ``transformers``, saved as a standard HF safetensors checkpoint, is
   loaded through engine/weights.py and must produce the same logits as
   the torch reference forward — validating the weight remapping
   (transpose conventions, stacking), RoPE, QK-norm, GQA attention, and
   the tied LM head against an independent implementation.

2. **Quickstart classify**: the same checkpoint plus a real trained BPE
   ``tokenizer.json`` is placed in ``weights_dir/<engine_key>/`` and the
   3-row sentiment quickstart runs through ``so.classify`` end to end —
   chat template, schema-constrained decoding, JSON unpack — asserting
   deterministic, schema-valid labels (greedy). Label *quality* needs
   trained weights, which the sandbox cannot fetch; correctness of the
   decode contract does not.
"""

import json
import os

import numpy as np
import pytest

from sutro_tpu.models.configs import MODEL_CONFIGS, ModelConfig

VOCAB = 512
TINY = ModelConfig(
    name="tiny-qwen3-hf", vocab_size=VOCAB, hidden_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=32, intermediate_size=128,
    qk_norm=True, tie_embeddings=True, rope_theta=1_000_000.0,
    chat_template="chatml",
)


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = transformers.Qwen3Config(
        vocab_size=VOCAB,
        hidden_size=TINY.hidden_size,
        num_hidden_layers=TINY.num_layers,
        num_attention_heads=TINY.num_heads,
        num_key_value_heads=TINY.num_kv_heads,
        head_dim=TINY.head_dim,
        intermediate_size=TINY.intermediate_size,
        rms_norm_eps=TINY.norm_eps,
        rope_theta=TINY.rope_theta,
        tie_word_embeddings=True,
        attention_bias=False,
        max_position_embeddings=512,
    )
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(cfg).eval()
    out = tmp_path_factory.mktemp("ckpt") / "tiny-qwen3-hf"
    model.save_pretrained(out, safe_serialization=True)
    return model, str(out)


def _train_tokenizer(path: str) -> None:
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    corpus = [
        "I absolutely love this product, it works great!",
        "Terrible quality, broke after one day.",
        "It's fine, nothing special either way.",
        "Classify the sentiment of the review.",
        "You are an expert classifier. positive negative neutral",
        "scratchpad classification json schema { } \" : ,",
        "system user assistant\n",
    ] * 50
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=VOCAB,
        special_tokens=["<|endoftext|>", "<|im_start|>", "<|im_end|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(path)


def test_qwen3_torch_parity(hf_checkpoint):
    torch = pytest.importorskip("torch")
    import jax.numpy as jnp

    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.engine.weights import load_checkpoint
    from sutro_tpu.models import transformer

    model, ckpt_dir = hf_checkpoint
    ecfg = EngineConfig(param_dtype="float32", use_pallas=False)
    params = load_checkpoint(ckpt_dir, TINY, ecfg)

    rng = np.random.default_rng(3)
    B, T = 2, 17
    ids = rng.integers(0, VOCAB, (B, T)).astype(np.int32)

    with torch.no_grad():
        ref = model(torch.from_numpy(ids).long()).logits.numpy()

    positions = np.broadcast_to(np.arange(T, dtype=np.int32)[None], (B, T))
    got, _, _ = transformer.forward(
        TINY, params, jnp.asarray(ids), jnp.asarray(positions),
        jnp.full((B,), T, jnp.int32),
    )
    got = np.asarray(got)

    np.testing.assert_allclose(got, ref, atol=2e-3, rtol=2e-3)
    # greedy continuation parity at every position
    np.testing.assert_array_equal(
        got.argmax(-1), ref.argmax(-1)
    )


def test_quickstart_classify_on_real_checkpoint(
    hf_checkpoint, tmp_path, monkeypatch
):
    pytest.importorskip("transformers")
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path / "home"))
    _, ckpt_dir = hf_checkpoint
    _train_tokenizer(os.path.join(ckpt_dir, "tokenizer.json"))

    MODEL_CONFIGS["tiny-qwen3-hf"] = TINY
    try:
        from sutro_tpu.engine.api import reset_engine
        from sutro_tpu.sdk import Sutro

        reset_engine()
        so = Sutro(
            engine_config=dict(
                weights_dir=os.path.dirname(ckpt_dir),
                kv_page_size=8,
                max_pages_per_seq=32,
                decode_batch_size=4,
                max_model_len=256,
                max_new_tokens=96,
                use_pallas=False,
                param_dtype="float32",
                temperature=0.0,  # greedy => deterministic goldens
            )
        )
        reviews = [
            "I absolutely love this product, it works great!",
            "Terrible quality, broke after one day.",
            "It's fine, nothing special either way.",
        ]
        labels = ["positive", "negative", "neutral"]
        dfs = []
        for _ in range(2):  # twice: assert determinism
            df = so.classify(
                reviews, classes=labels, model="tiny-qwen3-hf",
                sampling_params={"temperature": 0.0},
            )
            assert df is not None and len(df) == 3
            assert "classification" in df.columns
            # schema-constrained decoding guarantees every label is from
            # the enum — even with untrained weights
            assert all(c in labels for c in df["classification"])
            dfs.append(list(df["classification"]))
        assert dfs[0] == dfs[1]
    finally:
        MODEL_CONFIGS.pop("tiny-qwen3-hf", None)
        from sutro_tpu.engine.api import reset_engine

        reset_engine()


# ---------------------------------------------------------------------------
# family parity: every catalog architecture vs its torch reference
# ---------------------------------------------------------------------------


def _forward_ours(cfg, ckpt_dir, ids):
    import jax.numpy as jnp

    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.engine.weights import load_checkpoint

    from sutro_tpu.models import transformer

    ecfg = EngineConfig(param_dtype="float32", use_pallas=False)
    params = load_checkpoint(ckpt_dir, cfg, ecfg)
    B, T = ids.shape
    positions = np.broadcast_to(np.arange(T, dtype=np.int32)[None], (B, T))
    got, _, _ = transformer.forward(
        cfg, params, jnp.asarray(ids), jnp.asarray(positions),
        jnp.full((B,), T, jnp.int32),
    )
    return np.asarray(got)


def _parity(hf_model, cfg, tmp_path, atol=3e-3):
    torch = pytest.importorskip("torch")
    out_dir = str(tmp_path / cfg.name)
    hf_model.save_pretrained(out_dir, safe_serialization=True)
    rng = np.random.default_rng(5)
    ids = rng.integers(0, cfg.vocab_size, (2, 13)).astype(np.int32)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(ids).long()).logits.numpy()
    got = _forward_ours(cfg, out_dir, ids)
    np.testing.assert_allclose(got, ref, atol=atol, rtol=atol)
    np.testing.assert_array_equal(got.argmax(-1), ref.argmax(-1))


def test_llama3_torch_parity(tmp_path):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = ModelConfig(
        name="tiny-llama3-hf", vocab_size=256, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, qk_norm=False, tie_embeddings=False,
        rope_theta=500_000.0, norm_eps=1e-5, chat_template="llama3",
    )
    hf = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=128, rms_norm_eps=1e-5, rope_theta=500_000.0,
        tie_word_embeddings=False, attention_bias=False,
        mlp_bias=False, max_position_embeddings=256,
    )
    torch.manual_seed(1)
    _parity(transformers.LlamaForCausalLM(hf).eval(), cfg, tmp_path)


def test_qwen3_moe_torch_parity(tmp_path):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = ModelConfig(
        name="tiny-qwen3moe-hf", vocab_size=256, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=96, qk_norm=True, tie_embeddings=False,
        moe_experts=4, moe_top_k=2, moe_intermediate_size=96,
        rope_theta=1_000_000.0,
    )
    hf = transformers.Qwen3MoeConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        moe_intermediate_size=96, intermediate_size=96,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[],
        rms_norm_eps=1e-6, rope_theta=1_000_000.0,
        tie_word_embeddings=False, attention_bias=False,
        max_position_embeddings=256,
    )
    torch.manual_seed(2)
    _parity(
        transformers.Qwen3MoeForCausalLM(hf).eval(), cfg, tmp_path
    )


def test_gemma3_torch_parity(tmp_path):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    # 8 layers exercises the 5-local:1-global pattern + both RoPE bases
    cfg = ModelConfig(
        name="tiny-gemma3-hf", vocab_size=256, hidden_size=64,
        num_layers=8, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, qk_norm=True, tie_embeddings=True,
        rope_theta=1_000_000.0, local_rope_theta=10_000.0,
        sliding_window=8, sliding_pattern="gemma", post_norms=True,
        embed_scale=True, activation="gelu", norm_zero_centered=True,
        chat_template="gemma",
    )
    hf = transformers.Gemma3TextConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=8,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=128, rms_norm_eps=1e-6,
        rope_theta=1_000_000.0, rope_local_base_freq=10_000.0,
        sliding_window=8, sliding_window_pattern=6,
        query_pre_attn_scalar=16,  # == head_dim: same softmax scale
        tie_word_embeddings=True, attention_bias=False,
        max_position_embeddings=256, attn_logit_softcapping=None,
        final_logit_softcapping=None,
    )
    torch.manual_seed(3)
    _parity(
        transformers.Gemma3ForCausalLM(hf).eval(), cfg, tmp_path
    )


def test_gpt_oss_torch_parity(tmp_path):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = ModelConfig(
        name="tiny-oss-hf", vocab_size=256, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=96, qk_norm=False, tie_embeddings=False,
        moe_experts=4, moe_top_k=2, moe_intermediate_size=96,
        rope_theta=150_000.0, sliding_window=8,
        sliding_pattern="alternate", attention_sink=True,
        attn_bias=True, moe_bias=True, activation="swiglu_oss",
        # real gpt-oss checkpoints ship factor-32 YaRN over 4096
        rope_scaling_factor=32.0, rope_original_max=4096,
    )
    hf = transformers.GptOssConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=96, num_local_experts=4,
        num_experts_per_tok=2, rms_norm_eps=1e-6,
        rope_theta=150_000.0, sliding_window=8,
        layer_types=["sliding_attention", "full_attention"],
        tie_word_embeddings=False, attention_bias=True,
        rope_scaling={
            "rope_type": "yarn",
            "factor": 32.0,
            "original_max_position_embeddings": 4096,
            "beta_fast": 32.0,
            "beta_slow": 1.0,
        },
        max_position_embeddings=131_072,
    )
    torch.manual_seed(4)
    _parity(
        transformers.GptOssForCausalLM(hf).eval(), cfg, tmp_path
    )


def test_qwen3_embedding_torch_parity(tmp_path):
    """Embedding head parity: the bare Qwen3 trunk (as Qwen3-Embedding
    ships it — no LM head, no 'model.' key prefix) loaded through the
    weight converter must reproduce torch's last-token-pooled,
    L2-normalized embeddings."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import jax.numpy as jnp

    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.engine.weights import load_checkpoint
    from sutro_tpu.models import transformer

    cfg = ModelConfig(
        name="tiny-qwen3emb-hf", vocab_size=256, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, qk_norm=True, tie_embeddings=True,
        rope_theta=1_000_000.0, head="embedding", pooling="last",
    )
    hf = transformers.Qwen3Config(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=128, rms_norm_eps=1e-6,
        rope_theta=1_000_000.0, max_position_embeddings=256,
    )
    torch.manual_seed(6)
    trunk = transformers.Qwen3Model(hf).eval()
    out_dir = str(tmp_path / "emb")
    trunk.save_pretrained(out_dir, safe_serialization=True)

    rng = np.random.default_rng(7)
    B, T = 3, 11
    ids = rng.integers(0, 256, (B, T)).astype(np.int32)
    lens = np.asarray([11, 7, 1], np.int32)
    mask = (np.arange(T)[None] < lens[:, None]).astype(np.int64)
    with torch.no_grad():
        hs = trunk(
            torch.from_numpy(ids).long(),
            attention_mask=torch.from_numpy(mask),
        ).last_hidden_state.numpy()
    pooled = hs[np.arange(B), lens - 1]
    want = pooled / np.linalg.norm(pooled, axis=-1, keepdims=True)

    params = load_checkpoint(
        out_dir, cfg, EngineConfig(param_dtype="float32", use_pallas=False)
    )
    positions = np.broadcast_to(np.arange(T, dtype=np.int32)[None], (B, T))
    got, _, _ = transformer.forward(
        cfg, params, jnp.asarray(ids), jnp.asarray(positions),
        jnp.asarray(lens),
    )
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=3e-4)
