"""Concurrency invariants under load (SURVEY §5.2): the engine's
single-writer worker + durable jobstore must hold their guarantees when
many threads submit, cancel, resume, and read concurrently:

- results are visible if and only if the job reached SUCCEEDED, and they
  are always complete and input-ordered (1:1 contract, README.md:221);
- a job never runs twice concurrently (resume storms double-enqueue
  nothing);
- cancel mid-run leaves a consistent CANCELLED record that resume turns
  into a complete SUCCEEDED one.
"""

import threading
import time

import pytest

from sutro_tpu.interfaces import JobStatus


@pytest.fixture()
def eng(tiny_ecfg, tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine

    return LocalEngine(tiny_ecfg)


def _await(eng, jid, timeout=600):
    """Wait for a terminal status. The generous timeout is deliberate:
    this file's storm tests serialize many jobs through the single
    engine worker on a possibly-loaded CI box, and the one observed
    flake of this suite (round-3 post-mortem, memory races-test-flake)
    was load-coincident — a timeout here must read as 'box overloaded',
    with enough context to tell that apart from a real invariant
    breach."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s = eng.job_status(jid)
        if s in ("SUCCEEDED", "FAILED", "CANCELLED"):
            return s
        time.sleep(0.03)
    rec = eng.get_job(jid)
    raise TimeoutError(
        f"job {jid} not terminal after {timeout}s: "
        f"status={rec.get('status')!r} "
        f"failure_reason={rec.get('failure_reason')!r} "
        f"current={getattr(eng, '_current_job', None)!r} "
        f"queued={len(getattr(eng, '_queued', ()))}"
    )


def test_concurrent_submits_all_complete_ordered(eng):
    """8 threads x 2 jobs: every job succeeds with complete, ordered
    outputs; readers polling results mid-flight only ever see them
    after SUCCEEDED."""
    jids = []
    jlock = threading.Lock()
    violations = []

    def submit(tid):
        for j in range(2):
            rows = [f"t{tid}-j{j}-row{r}" for r in range(3)]
            jid = eng.submit_batch_inference(
                {"model": "tiny-dense", "inputs": rows,
                 "sampling_params": {"max_new_tokens": 4},
                 "job_priority": tid % 2}
            )
            with jlock:
                jids.append((jid, rows))

    def reader(stop):
        while not stop.is_set():
            with jlock:
                snapshot = list(jids)
            for jid, rows in snapshot:
                status = eng.job_status(jid)
                try:
                    res = eng.job_results(jid)
                except Exception:
                    continue  # not written yet — fine unless SUCCEEDED
                if len(res["outputs"]) != len(rows):
                    violations.append((jid, "partial results visible"))
                if status not in ("SUCCEEDED",) and res["outputs"]:
                    # results existed before terminal success
                    if eng.job_status(jid) != "SUCCEEDED":
                        violations.append((jid, f"results at {status}"))
            time.sleep(0.01)

    stop = threading.Event()
    threads = [
        threading.Thread(target=submit, args=(t,)) for t in range(8)
    ]
    rthread = threading.Thread(target=reader, args=(stop,))
    rthread.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for jid, rows in jids:
        assert _await(eng, jid) == "SUCCEEDED"
        res = eng.job_results(jid, include_inputs=True)
        assert len(res["outputs"]) == len(rows)
        assert all(o is not None for o in res["outputs"])
        assert res["inputs"] == rows  # order preserved
    stop.set()
    rthread.join()
    # a violation here is SERIOUS (results visible pre-terminal) — dump
    # each offender's full record so a failure is diagnosable from the
    # log alone (round-3 flake post-mortem lost the assertion text)
    assert not violations, [
        (jid, why, eng.get_job(jid)) for jid, why in violations[:5]
    ]


def test_resume_storm_runs_job_once(eng):
    """A cancelled job hit by 8 concurrent resume calls re-runs exactly
    once: at most one call wins (resumed=True), and the job converges to
    SUCCEEDED with complete ordered outputs."""
    rows = [f"row {i}" for i in range(10)]
    jid = eng.submit_batch_inference(
        {"model": "tiny-dense", "inputs": rows,
         "sampling_params": {"max_new_tokens": 30}}
    )
    # wait until running (or already terminal), then cancel mid-flight
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and eng.job_status(jid) not in (
        "RUNNING", "SUCCEEDED", "FAILED", "CANCELLED",
    ):
        time.sleep(0.02)
    eng.cancel_job(jid)
    status = _await(eng, jid)
    if status == "SUCCEEDED":
        return  # raced to completion; nothing to resume
    assert status == "CANCELLED"

    outs = []
    olock = threading.Lock()

    def resume():
        out = eng.resume_job(jid)
        with olock:
            outs.append(out)

    threads = [threading.Thread(target=resume) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [o for o in outs if o.get("resumed")]
    assert len(winners) <= 1, outs
    assert _await(eng, jid) == "SUCCEEDED"
    res = eng.job_results(jid)
    assert len(res["outputs"]) == len(rows)
    assert all(o is not None for o in res["outputs"])
