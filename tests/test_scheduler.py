"""Continuous-batching scheduler behaviors: ordering, cancellation,
truncation, admission, determinism, sampling-param plumbing."""

import numpy as np

from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest

from .conftest import make_requests


def run_all(batcher, reqs, **kw):
    res = {}
    batcher.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r), **kw)
    return res


def test_all_rows_complete_in_order_keyed(tiny_runner, byte_tok):
    b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
    reqs = make_requests(
        byte_tok,
        [f"row number {i}" for i in range(9)],
        max_new_tokens=6,
        temperature=0.5,
    )
    res = run_all(b, reqs)
    assert set(res) == set(range(9))
    assert all(r.input_tokens > 0 for r in res.values())


def test_greedy_determinism_across_batching(tiny_runner, byte_tok):
    b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
    reqs = make_requests(
        byte_tok, ["same prompt"] * 4, max_new_tokens=8, temperature=0.0
    )
    res = run_all(b, reqs)
    seqs = [tuple(res[i].token_ids) for i in range(4)]
    assert len(set(seqs)) == 1


def test_truncation_and_too_long(tiny_runner, byte_tok):
    b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
    long_ids = np.arange(500, dtype=np.int32) % 200
    reqs = [
        GenRequest(row_id=0, prompt_ids=long_ids, max_new_tokens=4),
        GenRequest(
            row_id=1, prompt_ids=long_ids, max_new_tokens=4,
            allow_truncate=False,
        ),
    ]
    res = run_all(b, reqs)
    assert res[0].finish_reason in ("length", "stop")
    assert res[1].finish_reason == "error_too_long"
    assert res[1].token_ids == []


def test_cancellation(tiny_runner, byte_tok):
    b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
    calls = [0]

    def cancel():
        calls[0] += 1
        return calls[0] > 2

    res = run_all(
        b,
        make_requests(byte_tok, ["a", "b"], max_new_tokens=50),
        should_cancel=cancel,
    )
    assert all(r.finish_reason == "cancelled" for r in res.values())


def test_progress_stream_fields(tiny_runner, byte_tok):
    """Progress updates carry the reference NDJSON token fields
    (sdk.py:339-366)."""
    b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
    updates = []
    run_all(
        b,
        make_requests(byte_tok, ["x", "y"], max_new_tokens=4),
        on_progress=updates.append,
        progress_every=0.0,
    )
    assert updates, "no progress reported"
    last = updates[-1]
    assert {
        "rows_completed",
        "input_tokens",
        "output_tokens",
        "total_tokens_processed_per_second",
    } <= set(last)
    assert last["rows_completed"] == 2


def test_pages_released(tiny_runner, byte_tok):
    b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
    free0 = b.free_page_count
    run_all(b, make_requests(byte_tok, ["p1", "p2", "p3"], max_new_tokens=5))
    assert b.free_page_count == free0


def test_constraint_mask_smaller_than_model_vocab(tiny_ecfg, byte_tok):
    """Tokenizer vocab < padded model vocab: masks must pad with False
    (code-review regression — real HF checkpoints pad the embedding)."""
    import numpy as np

    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
    from sutro_tpu.models.configs import MODEL_CONFIGS

    cfg = MODEL_CONFIGS["tiny-dense"]
    short = cfg.vocab_size - 100  # pretend tokenizer is 100 ids short

    class HeadOnly:
        """Allows only token id 7, mask sized to the short vocab."""

        def allowed_tokens(self):
            m = np.zeros((short,), bool)
            m[7] = True
            return m

        def advance(self, tok):
            pass

        def is_complete(self):
            return False

    b = ContinuousBatcher(
        ModelRunner(cfg, tiny_ecfg), stop_ids=byte_tok.stop_ids()
    )
    res = {}
    b.run(
        [
            GenRequest(
                row_id=0,
                prompt_ids=np.array(byte_tok.encode("x"), np.int32),
                max_new_tokens=4,
                constraint=HeadOnly(),
            )
        ],
        on_result=lambda r: res.__setitem__(r.row_id, r),
    )
    assert all(t == 7 for t in res[0].token_ids)


def test_job_perf_profile_recorded(tiny_ecfg, byte_tok, tmp_path, monkeypatch):
    """Completed jobs carry a StepTimer latency summary in their record
    (engine/profiling.py; SURVEY §5.1 engine-level profiling)."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine

    eng = LocalEngine(tiny_ecfg)
    job_id = eng.submit_batch_inference(
        {"model": "tiny-dense", "inputs": ["a", "bb"],
         "sampling_params": {"max_new_tokens": 5}}
    )
    import time

    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        from sutro_tpu.interfaces import JobStatus

        if JobStatus(eng.job_status(job_id)).is_terminal():
            break
        time.sleep(0.2)
    rec = eng.get_job(job_id)
    assert rec["status"] == "SUCCEEDED", rec.get("failure_reason")
    perf = rec["perf"]
    assert perf and "decode" in perf and "prefill" in perf
    # both rows ride ONE batched prefill dispatch (runner.prefill_batch)
    assert perf["prefill"]["count"] == 1
    assert perf["decode"]["p50_ms"] > 0


def test_multi_step_matches_single_step_greedy(tiny_ecfg, byte_tok):
    """Fused multi-step decode windows (decode_multi_step) must produce
    exactly the single-step greedy outputs (greedy is rng-independent)."""
    import dataclasses

    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.models.configs import MODEL_CONFIGS

    texts = ["alpha", "beta gamma", "", "longer prompt here"]

    def run(multi):
        ecfg = dataclasses.replace(tiny_ecfg, decode_multi_step=multi)
        b = ContinuousBatcher(
            ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg),
            stop_ids=byte_tok.stop_ids(),
        )
        res = run_all(
            b,
            make_requests(byte_tok, texts, max_new_tokens=11,
                          temperature=0.0),
        )
        return {i: (tuple(r.token_ids), r.finish_reason)
                for i, r in res.items()}

    assert run(1) == run(8)


def test_batched_prefill_matches_single(tiny_ecfg, byte_tok):
    """Greedy outputs must be identical whether rows prefill one per
    dispatch (prefill_batch_size=1) or batched — batching is purely an
    execution-shape change."""
    import dataclasses

    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.models.configs import MODEL_CONFIGS

    texts = ["alpha beta", "gamma", "delta epsilon zeta", "eta", "theta!"]
    outs = []
    for pbs in (1, 4):
        ecfg = dataclasses.replace(tiny_ecfg, prefill_batch_size=pbs)
        runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
        b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
        reqs = make_requests(
            byte_tok, texts, max_new_tokens=6, temperature=0.0
        )
        res = run_all(b, reqs)
        outs.append([tuple(res[i].token_ids) for i in range(len(texts))])
    assert outs[0] == outs[1]


def test_inadmissible_row_fails_alone(tiny_ecfg, byte_tok):
    """A row whose prompt+max_new exceeds total KV capacity fails with a
    per-row error result; every other row still succeeds and the job
    completes (no whole-job MemoryError)."""
    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.models.configs import MODEL_CONFIGS

    # cache holds only 7 usable pages (56 tokens) < the 16 pages the bad
    # row's worst case needs — it can never fit even an empty machine
    runner = ModelRunner(
        MODEL_CONFIGS["tiny-dense"], tiny_ecfg, num_pages=8
    )
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    ok1 = make_requests(byte_tok, ["good row"], max_new_tokens=4)[0]
    bad = GenRequest(
        row_id=1,
        prompt_ids=(np.arange(40) % 200).astype(np.int32),
        max_new_tokens=tiny_ecfg.max_context(),
    )
    ok2 = make_requests(byte_tok, ["another good row"], max_new_tokens=4)[0]
    ok2 = GenRequest(
        row_id=2, prompt_ids=ok2.prompt_ids, max_new_tokens=4
    )
    res = run_all(b, [ok1, bad, ok2])
    assert set(res) == {0, 1, 2}
    assert res[1].finish_reason == "error_capacity"
    assert res[1].token_ids == []
    assert res[0].finish_reason in ("stop", "length")
    assert res[2].finish_reason in ("stop", "length")


def test_python_fallback_batched_admission(tiny_runner, byte_tok, monkeypatch):
    """The pure-Python allocator path (no native runtime) must admit a
    multi-row batch into DISTINCT slots — regression for a reservation
    collision where every same-batch row got slots.index(None)."""
    import sutro_tpu.engine.native_runtime as nr

    monkeypatch.setattr(nr, "maybe_native_runtime", lambda *a, **k: None)
    b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
    assert b.native is None and b.allocator is not None
    texts = ["one", "two", "three", "four"]
    res = run_all(
        b, make_requests(byte_tok, texts, max_new_tokens=5)
    )
    assert set(res) == set(range(len(texts)))
    assert b.free_page_count == b.allocator.num_pages - 1  # all released


def test_page_allocator_contiguous_runs():
    """Contiguous-first allocation: runs are ascending and re-allocation
    after frees still finds holes (first-fit), falling back to scattered
    only when no hole fits."""
    from sutro_tpu.engine.kvcache import PageAllocator

    a = PageAllocator(num_pages=17)  # pages 1..16
    r1 = a.alloc(4)
    r2 = a.alloc(4)
    r3 = a.alloc(4)
    for r in (r1, r2, r3):
        assert r == list(range(r[0], r[0] + 4))
    a.free(r2)  # hole of 4 in the middle
    r4 = a.alloc(3)  # fits the hole (first fit)
    assert r4 == list(range(r4[0], r4[0] + 3))
    a.free(r1)
    a.free(r3)
    a.free(r4)
    assert a.free_count == 16
    big = a.alloc(16)
    assert big == list(range(1, 17))


def test_truncation_reserves_schema_room(tiny_runner):
    """A long prompt on a constrained row is truncated far enough that
    the schema's minimal JSON still fits (regression: prompts that fill
    the context left 1 token of room and emitted just "{")."""
    import json

    from sutro_tpu.engine.constrain import schema_constraint_factory
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    schema = {
        "type": "object",
        "properties": {
            "scratchpad": {"type": "string"},
            "label": {"enum": ["a", "b"]},
        },
        "required": ["scratchpad", "label"],
    }
    fac = schema_constraint_factory(schema, tok)
    b = ContinuousBatcher(tiny_runner, stop_ids=tok.stop_ids())
    cap = tiny_runner.ecfg.max_context()
    long_prompt = np.asarray(
        tok.encode("x" * (cap + 40)), np.int32
    )
    results = {}
    b.run(
        [
            GenRequest(
                row_id=0, prompt_ids=long_prompt, max_new_tokens=64,
                temperature=0.0, constraint=fac(),
            )
        ],
        on_result=lambda r: results.__setitem__(r.row_id, r),
    )
    r = results[0]
    assert r.finish_reason not in ("error_too_long",)
    obj = json.loads(tok.decode(r.token_ids))
    assert obj["label"] in ("a", "b")


def test_unfittable_schema_fails_row_clearly(tiny_runner):
    """If the schema's minimal JSON cannot fit the context at all, the
    row fails with error_too_long instead of emitting invalid JSON."""
    from sutro_tpu.engine.constrain import schema_constraint_factory
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    cap = tiny_runner.ecfg.max_context()
    # enum of one long literal whose minimal JSON exceeds the context
    schema = {
        "type": "object",
        "properties": {"v": {"enum": ["y" * (cap + 16)]}},
        "required": ["v"],
    }
    fac = schema_constraint_factory(schema, tok)
    b = ContinuousBatcher(tiny_runner, stop_ids=tok.stop_ids())
    results = {}
    b.run(
        [
            GenRequest(
                row_id=0,
                prompt_ids=np.asarray(tok.encode("hi"), np.int32),
                max_new_tokens=cap + 64, temperature=0.0,
                constraint=fac(),
            )
        ],
        on_result=lambda r: results.__setitem__(r.row_id, r),
    )
    assert results[0].finish_reason == "error_too_long"


def test_stop_sequences_end_generation(tiny_runner, byte_tok):
    """A stop sequence appearing in the decoded output (even spanning
    token boundaries) finishes the row with reason "stop"."""
    b = ContinuousBatcher(
        tiny_runner, stop_ids=byte_tok.stop_ids(),
        token_bytes=byte_tok.token_bytes,
    )
    results = {}
    # force the output deterministically by constraining to a const
    # string that CONTAINS the stop sequence
    from sutro_tpu.engine.constrain import schema_constraint_factory

    fac = schema_constraint_factory(
        {"const": "abcSTOPdef"}, byte_tok
    )
    b.run(
        [
            GenRequest(
                row_id=0,
                prompt_ids=np.asarray(byte_tok.encode("x"), np.int32),
                max_new_tokens=40, temperature=0.0, constraint=fac(),
                stop_seqs=[b"STOP"],
            )
        ],
        on_result=lambda r: results.__setitem__(r.row_id, r),
    )
    r = results[0]
    assert r.finish_reason == "stop"
    out = byte_tok.decode(r.token_ids)
    assert "STOP" in out            # engine stops AT the sequence...
    assert not out.endswith("def")  # ...without generating the rest


def test_repetition_penalty_via_scheduler(tiny_runner, byte_tok):
    """Penalty rows route through the single-step path with host-side
    counts; a strong repetition penalty measurably reduces repeats vs
    the unpenalized greedy decode of the same prompt."""
    def run(rep):
        b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
        results = {}
        b.run(
            [
                GenRequest(
                    row_id=0,
                    prompt_ids=np.asarray(
                        byte_tok.encode("abab"), np.int32
                    ),
                    max_new_tokens=24, temperature=0.0,
                    repetition_penalty=rep,
                )
            ],
            on_result=lambda r: results.__setitem__(r.row_id, r),
        )
        return results[0].token_ids

    base = run(1.0)
    pen = run(8.0)

    def max_run(ids):
        best = cur = 1
        for a, c in zip(ids, ids[1:]):
            cur = cur + 1 if a == c else 1
            best = max(best, cur)
        return best

    # greedy tiny models loop hard; a strong penalty must break the
    # longest repeat run (or change the output entirely)
    assert pen != base
    if len(base) > 4:
        assert max_run(pen) <= max_run(base)


def test_speculative_rejection_is_per_row(tiny_runner, byte_tok, monkeypatch):
    """One adversarial constrained row (scaffold-heavy const schema,
    rejected nearly every window) must NOT degrade the batch to masked
    single-steps: the rejecting row takes its FSM-masked step inside the
    next window (allowed0) while other rows keep full window cadence."""
    import json

    from sutro_tpu.engine.constrain import schema_constraint_factory

    calls = {"window": 0, "window_masked": 0, "single": 0}
    orig_window = tiny_runner.decode_window
    orig_step = tiny_runner.decode_step

    def window(*a, **kw):
        calls["window"] += 1
        if kw.get("allowed0") is not None:
            calls["window_masked"] += 1
        return orig_window(*a, **kw)

    def step(*a, **kw):
        calls["single"] += 1
        return orig_step(*a, **kw)

    monkeypatch.setattr(tiny_runner, "decode_window", window)
    monkeypatch.setattr(tiny_runner, "decode_step", step)
    b = ContinuousBatcher(
        tiny_runner, stop_ids=byte_tok.stop_ids(),
        token_bytes=byte_tok.token_bytes,
    )
    # this test pins the WINDOW path's per-row rejection recovery; the
    # FSM fast-forward would otherwise commit the const row's forced
    # run without dispatching any window at all (its own invariant is
    # pinned by tests/test_fastforward.py)
    import dataclasses as _dc

    b.ecfg = _dc.replace(b.ecfg, constrain_fastforward=0)
    fac = schema_constraint_factory({"const": "zqxzqxzqxzqx"}, byte_tok)
    reqs = [
        GenRequest(
            row_id=0,
            prompt_ids=np.array(byte_tok.encode("adv"), np.int32),
            max_new_tokens=40, temperature=0.0, constraint=fac(),
        ),
        GenRequest(
            row_id=1,
            prompt_ids=np.array(byte_tok.encode("bystander"), np.int32),
            # window-aligned cap: a non-multiple of decode_multi_step
            # would run its TAIL single-step by the documented
            # all-or-nothing window rule, which is not what this test
            # measures
            max_new_tokens=2 * tiny_runner.ecfg.decode_multi_step,
            temperature=0.0,
        ),
    ]
    res = {}
    b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
    out0 = b"".join(byte_tok.token_bytes(t) for t in res[0].token_ids)
    assert json.loads(out0.decode()) == "zqxzqxzqxzqx"
    assert res[0].finish_reason == "schema_complete"
    assert len(res[1].token_ids) == 2 * tiny_runner.ecfg.decode_multi_step
    # the invariant under test: rejections recovered inside windows,
    # never by flipping the whole batch to masked single-steps
    assert calls["single"] == 0, calls
    assert calls["window_masked"] >= 1, calls
    assert calls["window"] >= 2, calls


def test_masked_window_step_trusts_mask_no_livelock(tiny_runner, byte_tok):
    """Budget-infeasible corner: allowed_tokens degrades to unfiltered
    while token_allowed still rejects. The flagged row's step-0 token is
    mask-chosen, so it must be accepted WITHOUT re-verification (the old
    masked single-step's semantics) — re-checking would reject it and
    spin the scheduler forever at zero progress."""

    class DivergentConstraint:
        def __init__(self, vocab):
            self.v = vocab

        def allowed_tokens(self, remaining=None):
            return np.ones(self.v, bool)  # degrade: unfiltered

        def token_allowed(self, tok, remaining=None):
            return False  # strict check: nothing fits

        def advance(self, tok):
            pass

        def is_complete(self):
            return False

        def min_tokens(self):
            return 1

    b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
    reqs = [
        GenRequest(
            row_id=0,
            prompt_ids=np.array(byte_tok.encode("x"), np.int32),
            max_new_tokens=6, temperature=0.0,
            constraint=DivergentConstraint(tiny_runner.mcfg.vocab_size),
        )
    ]
    res = {}
    b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
    # terminates (no livelock) and makes real progress via masked steps
    assert len(res[0].token_ids) == 6


def test_row_seed_independent_of_batch_composition(tiny_runner, byte_tok):
    """The reference's random_seed_per_input contract (sample()
    docstring): a seeded row's output stream is reproducible regardless
    of batch composition — pinned across admission-group sizes (1-row
    job vs 3-row job). The 3-row group pads to the 4-bucket in
    round-5's bucketed admission sampling, so this also pins that a
    padded group does not perturb real rows' draws."""
    import numpy as np

    from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest

    def run_job(reqs):
        b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
        res = {}
        out = b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
        assert out == "completed"
        return res

    def mk(i, txt, seed=None):
        return GenRequest(
            row_id=i,
            prompt_ids=np.frombuffer(txt.encode(), np.uint8).astype(
                np.int32
            ),
            max_new_tokens=10,
            temperature=0.9,
            row_seed=seed,
        )

    solo = run_job([mk(0, "the quick brown fox", seed=42)])
    crowd = run_job(
        [
            mk(0, "alpha"),
            mk(1, "much longer prompt here padding things"),
            mk(2, "the quick brown fox", seed=42),
        ]
    )
    assert solo[0].token_ids == crowd[2].token_ids


class _AdmitStubRunner:
    """Minimal runner surface for admission-only scheduler tests."""

    def __init__(self, ecfg, vocab=300):
        class _M:
            vocab_size = vocab

        self.ecfg = ecfg
        self.mcfg = _M()
        self.sp = 1
        self.pp = 1
        self.num_pages = 1 + ecfg.decode_batch_size * ecfg.max_pages_per_seq


def _parity_ecfg():
    from sutro_tpu.engine.config import EngineConfig

    return EngineConfig(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
    )


def test_admission_parity_prefix_covers_whole_need(monkeypatch):
    """When the job's shared prefix already covers a row's worst-case
    page need (own < 1 before clamping), BOTH admission paths must
    clamp to 1 own page and admit while the table row has room —
    native rt_try_admit_pfx always did; the Python fallback used to
    reject (`own < 1 -> None`), diverging from the C++ verdict."""
    import pytest

    from sutro_tpu.engine import native_runtime as nr
    from sutro_tpu.engine.scheduler import JobCtx, _SharedPrefix

    verdicts = {}
    for native in (False, True):
        monkeypatch.setenv(
            "SUTRO_NATIVE_RUNTIME", "1" if native else "0"
        )
        nr._lib = None
        nr._lib_failed = False
        if native and not nr.is_available():
            nr._lib = None
            nr._lib_failed = False
            pytest.skip("native toolchain unavailable")
        try:
            ecfg = _parity_ecfg()
            b = ContinuousBatcher(_AdmitStubRunner(ecfg), stop_ids=[0])
            assert (b.native is not None) == native
            # a prefix of 4 pages (32 tokens) while the row's whole
            # worst case is 1 page: own = 1 - 4 < 1 before the clamp
            if native:
                pfx_pages = b.native.alloc_pages(4)
            else:
                pfx_pages = b.allocator.alloc(4)
            ctx = JobCtx(
                job_id="parity", pending=[], on_result=lambda r: None
            )
            ctx.prefix = _SharedPrefix(tokens=32, pages=list(pfx_pages))
            req = GenRequest(
                row_id=0,
                prompt_ids=np.arange(3, dtype=np.int32),
                max_new_tokens=2,
            )
            r = b._reserve(req, ctx)
            assert r is not None, f"native={native} rejected"
            slot_idx, own_pages, table = r
            # table head carries the prefix, exactly one own page after
            assert list(table[:4]) == list(pfx_pages)
            assert len(list(own_pages)) == 1
            assert table[4] == list(own_pages)[0]
            verdicts[native] = True
        finally:
            nr._lib = None
            nr._lib_failed = False
    assert verdicts.get(False) == verdicts.get(True)


def test_admission_parity_prefix_fills_table_row(monkeypatch):
    """Companion bound: when the prefix already fills the whole table
    row (npfx == MP), the clamped own page has nowhere to go — BOTH
    paths must reject (the native side grew this guard for a heap
    smash; the Python side must agree)."""
    import pytest

    from sutro_tpu.engine import native_runtime as nr
    from sutro_tpu.engine.scheduler import JobCtx, _SharedPrefix

    for native in (False, True):
        monkeypatch.setenv(
            "SUTRO_NATIVE_RUNTIME", "1" if native else "0"
        )
        nr._lib = None
        nr._lib_failed = False
        if native and not nr.is_available():
            nr._lib = None
            nr._lib_failed = False
            pytest.skip("native toolchain unavailable")
        try:
            ecfg = _parity_ecfg()
            b = ContinuousBatcher(_AdmitStubRunner(ecfg), stop_ids=[0])
            MP = ecfg.max_pages_per_seq
            if native:
                pfx_pages = b.native.alloc_pages(MP)
            else:
                pfx_pages = b.allocator.alloc(MP)
            ctx = JobCtx(
                job_id="parity2", pending=[], on_result=lambda r: None
            )
            ctx.prefix = _SharedPrefix(
                tokens=MP * ecfg.kv_page_size, pages=list(pfx_pages)
            )
            req = GenRequest(
                row_id=0,
                prompt_ids=np.arange(3, dtype=np.int32),
                max_new_tokens=2,
            )
            assert b._reserve(req, ctx) is None, f"native={native}"
        finally:
            nr._lib = None
            nr._lib_failed = False


def test_plain_window_zero_budget_finishes_immediately(byte_tok):
    """_accept_plain_window with a non-positive remaining budget must
    emit the row with ZERO tokens taken — the old max(..., 1) silently
    accepted one token past max_new_tokens / the context limit."""
    from sutro_tpu.engine import native_runtime as nr
    from sutro_tpu.engine.scheduler import _Slot

    ecfg = _parity_ecfg()
    import sutro_tpu.engine.scheduler as sched

    b = ContinuousBatcher.__new__(ContinuousBatcher)
    # hand-build just enough batcher state for the unit call
    b.ecfg = ecfg
    b.vocab = 300
    b.stop_ids = {0}
    b._stop_arr = np.array([0], np.int64)
    b._max_ctx = ecfg.max_context()
    b.native = None
    from sutro_tpu.engine.kvcache import PageAllocator

    b.allocator = PageAllocator(16)
    b.slots = [None] * 4
    b._gen = [0] * 4
    b._needs_mask = set()
    from sutro_tpu.engine.profiling import StepTimer

    b.timer = StepTimer()

    req = GenRequest(
        row_id=7, prompt_ids=np.arange(4, dtype=np.int32),
        max_new_tokens=3,
    )
    pages = b.allocator.alloc(2)
    slot = _Slot(req=req, pages=pages, pos=7, last_token=5)
    slot.out_ids = [5, 6, 9]  # already AT the max_new cap
    results = {}
    ctx = sched.JobCtx(
        job_id="zb", pending=[],
        on_result=lambda r: results.setdefault(r.row_id, r),
    )
    slot.job = ctx
    ctx.n_slots = 1
    b.slots[1] = slot
    wK = 4
    toks = np.full((wK, 4), 9, np.int32)
    logps = np.full((wK, 4), -1.0, np.float32)
    b._accept_plain_window([1], toks, logps, wK)
    assert 7 in results, "row must finish"
    assert len(results[7].token_ids) == 3  # nothing accepted past cap
    assert results[7].finish_reason == "length"
    assert b.slots[1] is None
    assert b.allocator.free_count == 15  # PageAllocator(16): page 0 reserved
