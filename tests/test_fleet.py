"""Replica fleet front door (fleet/): health-checked routing over N
engine daemons, per-replica circuit breakers, warm-prefix affinity,
batch-job failover via the shared jobstore, and graceful degradation
when router and replica disagree on protocol.

Layout mirrors the fleet's layers:

1. unit — breaker state machine, tolerant frame parsers, pure pick
   policies, the fleet doctor (no HTTP, no engines);
2. prober degradation against fake transports (old replica vs new
   router — health-probe-only routing, never a crash);
3. integration over TWO live engines sharing one SUTRO_HOME behind a
   live router (the fleet topology the chaos gate grades);
4. chaos — replica death mid-batch-job fails over with zero lost or
   duplicated rows and bit-identical outputs; a replica death
   mid-SSE-stream becomes a structured error frame, never a hang.

Destructive tests (anything that kills a server) build their OWN
servers/routers around the shared engines so the module fixture stays
healthy for later tests.
"""

import json
import threading
import time

import pytest
import requests

from sutro_tpu import telemetry
from sutro_tpu.engine import faults
from sutro_tpu.engine.api import LocalEngine
from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.fleet import frames
from sutro_tpu.fleet.affinity import WarmAffinity
from sutro_tpu.fleet.health import HealthProber
from sutro_tpu.fleet.membership import FleetMembership
from sutro_tpu.fleet.router import (
    pick_batch,
    pick_interactive,
    start_fleet_thread,
)
from sutro_tpu.interfaces import JobStatus
from sutro_tpu.server import (
    EngineHTTPHandler,
    bind_engine,
    make_server,
    start_server_thread,
)
from sutro_tpu.telemetry import doctor

from .conftest import free_low_port


def _wait(pred, timeout=30.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


STATE_OK = {
    "ready": True,
    "draining": False,
    "load": {},
    "models": [],
    "fleet_protocol": True,
    "warm_probe": True,
}


# ---------------------------------------------------------------------
# 1. breaker state machine + frames + pick policies (pure units)
# ---------------------------------------------------------------------


def test_breaker_opens_after_threshold_then_recloses():
    trans = []
    m = FleetMembership(
        ["http://x:1"], probe_interval=1.0,
        on_transition=lambda *a: trans.append(a),
    )
    now = 100.0
    m.note_probe_success("r0", STATE_OK, now=now)
    assert [r["rid"] for r in m.healthy()] == ["r0"]
    # two failures stay closed; the third opens the breaker
    m.note_probe_failure("r0", now=now)
    m.note_probe_failure("r0", now=now)
    assert m.get("r0")["state"] == "closed"
    m.note_probe_failure("r0", now=now)
    assert m.get("r0")["state"] == "open"
    assert ("r0", "closed", "open") in trans
    assert m.healthy() == []
    # open -> half_open trial; a failed trial re-opens with backoff
    m.note_half_open("r0", now=now)
    assert m.get("r0")["state"] == "half_open"
    m.note_probe_failure("r0", now=now)
    assert m.get("r0")["state"] == "open"
    # open_probes=1 -> next probe at interval * 2, not every sweep
    assert m.due_probes(now=now + 1.5) == []
    assert [d["rid"] for d in m.due_probes(now=now + 2.5)] == ["r0"]
    # successful trial recloses and restores routability
    m.note_half_open("r0", now=now + 2.5)
    m.note_probe_success("r0", STATE_OK, now=now + 2.5)
    assert m.get("r0")["state"] == "closed"
    assert [r["rid"] for r in m.healthy()] == ["r0"]


def test_breaker_backoff_is_bounded():
    m = FleetMembership(
        ["http://x:1"], probe_interval=1.0, backoff_cap=8.0,
    )
    now = 0.0
    for _ in range(3):
        m.note_probe_failure("r0", now=now)
    # pile on failures: the probe spacing grows but caps at backoff_cap
    for _ in range(20):
        m.note_probe_failure("r0", now=now)
    assert m.due_probes(now=now + 7.9) == []
    assert [d["rid"] for d in m.due_probes(now=now + 8.1)] == ["r0"]


def test_flap_detection_feeds_doctor_verdict():
    m = FleetMembership(["http://x:1"], probe_interval=0.01)
    # real monotonic timestamps: snapshot()'s flap window uses them
    m.note_probe_success("r0", STATE_OK)
    for _ in range(3):
        m.note_probe_failure("r0")
    m.note_half_open("r0")
    m.note_probe_success("r0", STATE_OK)
    assert m.flapping() == ["r0"]
    snap = m.snapshot()
    assert snap["replicas"][0]["transitions_in_window"] >= 3
    verdict = doctor.diagnose_fleet(snap)
    assert verdict["verdict"] == "replica_flapping"
    assert verdict["flapping"] == ["r0"]


def test_doctor_fleet_verdict_priorities():
    assert (
        doctor.diagnose_fleet({"replicas": [], "n_healthy": 0})["verdict"]
        == "no_healthy_replicas"
    )
    row = {
        "rid": "r0", "state": "closed", "ready": True, "draining": False,
        "transitions_in_window": 0,
    }
    healthy = doctor.diagnose_fleet({"replicas": [row], "n_healthy": 1})
    assert healthy["verdict"] == "healthy"
    degraded = doctor.diagnose_fleet(
        {
            "replicas": [row, dict(row, rid="r1", state="open")],
            "n_healthy": 1,
        }
    )
    assert degraded["verdict"] == "fleet_degraded"
    draining = doctor.diagnose_fleet(
        {
            "replicas": [row, dict(row, rid="r1", draining=True)],
            "n_healthy": 1,
        }
    )
    assert draining["verdict"] == "fleet_degraded"
    assert any("draining" in e for e in draining["evidence"])


def test_frame_parsers_tolerate_skew_and_junk():
    # newer-peer frame with unknown keys parses; junk 't' is refused
    frame = frames.fleet_state_frame(
        "ready", False, True, {"jobs_queued": 2, "new_field": "x"}, ["m"]
    )
    frame["future_knob"] = {"nested": True}
    frame["v"] = 99
    parsed = frames.parse_fleet_state(frame)
    assert parsed["ready"] and parsed["fleet_protocol"]
    assert frames.load_score(parsed["load"]) == 2
    # legacy /healthz doc (no 't'): alive, but health-probe-only
    legacy = frames.parse_fleet_state({"ok": True, "junk": 1})
    assert legacy["ready"] and not legacy["fleet_protocol"]
    assert not legacy["warm_probe"]
    assert frames.parse_fleet_state({"t": "not_fleet_state"}) is None
    assert frames.parse_fleet_state("nonsense") is None
    assert frames.parse_warm_report({"warm_tokens": "bogus"}) == 0
    assert frames.parse_warm_report(None) == 0
    assert frames.parse_warm_report({"warm_tokens": 7, "x": 1}) == 7
    assert frames.load_score({"jobs_queued": "NaN?", "jobs_running": 3}) == 3


def test_pick_policies_are_deterministic():
    reps = [
        {"rid": "r0", "load": 2},
        {"rid": "r1", "load": 0},
        {"rid": "r2", "load": 1},
    ]
    assert [r["rid"] for r in pick_batch(reps)] == ["r1", "r2", "r0"]
    # warmth dominates load; load breaks warmth ties
    order = pick_interactive(reps, {"r0": 64, "r2": 64})
    assert [r["rid"] for r in order] == ["r2", "r0", "r1"]
    assert [r["rid"] for r in pick_interactive(reps, {})] == [
        "r1", "r2", "r0",
    ]


# ---------------------------------------------------------------------
# 2. prober degradation against fake transports
# ---------------------------------------------------------------------


def test_degradation_old_replica_downgrades_to_healthz_probe():
    """A replica that 404s /fleet-state (predates the fleet protocol)
    is probed via /healthz and stays routable — with warm-probe
    affinity disabled for it, never a crash."""
    m = FleetMembership(["http://legacy:9"], probe_interval=0.01)
    calls = []

    def fake_send(method, url, frame=None, timeout=2.0):
        calls.append(url)
        if url.endswith("/fleet-state"):
            return {"detail": "Unknown endpoint GET /fleet-state",
                    "_status": 404}
        if url.endswith("/healthz"):
            return {"ok": True, "unexpected_key": [1, 2], "_status": 200}
        raise AssertionError(f"unexpected probe url {url}")

    p = HealthProber(m, send=fake_send)
    p.sweep_once()
    row = m.get("r0")
    assert row["state"] == "closed" and row["ready"]
    assert not row["fleet_protocol"] and not row["warm_probe"]
    # the downgrade sticks: the next sweep goes straight to /healthz
    calls.clear()
    m.note_probe_success("r0", frames.parse_fleet_state({"ok": True}))
    p.probe_one("r0", "http://legacy:9")
    assert calls == ["http://legacy:9/healthz"]
    # affinity omits legacy replicas: least-loaded routing only
    aff = WarmAffinity(send=fake_send)
    assert aff.scores({"model": "m", "messages": []}, True, [row]) == {}


def test_degradation_garbage_answers_open_breaker_not_crash():
    m = FleetMembership(["http://weird:9"], probe_interval=0.01)

    def junk_send(method, url, frame=None, timeout=2.0):
        return {"t": "completely_unknown_frame", "_status": 200}

    p = HealthProber(m, send=junk_send)
    for _ in range(5):
        m._replicas["r0"].next_probe_at = 0.0
        p.sweep_once()
    assert m.get("r0")["state"] == "open"
    assert m.healthy() == []


def test_fleet_probe_fault_site_drives_breaker():
    """fleet.probe with job=<rid> fails probes deterministically — the
    chaos suite's no-real-kill way to exercise breaker transitions."""
    m = FleetMembership(["http://a:1", "http://b:2"], probe_interval=0.01)

    def ok_send(method, url, frame=None, timeout=2.0):
        return dict(frames.fleet_state_frame("ready", False, True, {}, []),
                    _status=200)

    p = HealthProber(m, send=ok_send)
    faults.configure("fleet.probe:error:job=r0")
    try:
        for _ in range(4):
            for r in ("r0", "r1"):
                m._replicas[r].next_probe_at = 0.0
            p.sweep_once()
    finally:
        faults.clear()
    assert m.get("r0")["state"] == "open"
    assert [r["rid"] for r in m.healthy()] == ["r1"]


# ---------------------------------------------------------------------
# 3. integration: two live engines, one shared home, one router
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet(tmp_path_factory, monkeypatch_module):
    """TWO tiny engines sharing one SUTRO_HOME (the shared-jobstore
    fleet topology) behind a live router; r0 -> eng_a, r1 -> eng_b."""
    home = tmp_path_factory.mktemp("fleet-home")
    monkeypatch_module.setenv("SUTRO_HOME", str(home))
    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", max_new_tokens=8,
        interactive_slots=2,
    )
    eng_a = LocalEngine(ecfg)
    eng_b = LocalEngine(ecfg)
    srv_a, _, url_a = start_server_thread(eng_a)
    srv_b, _, url_b = start_server_thread(eng_b)
    router, fsrv, _, furl = start_fleet_thread(
        [url_a, url_b], probe_interval=0.2
    )
    from sutro_tpu.sdk import Sutro

    sdk = Sutro(api_key="fleet-key", base_url=furl, backend="fleet")
    _wait(
        lambda: router.membership.snapshot()["n_healthy"] == 2,
        timeout=15, what="both replicas healthy",
    )

    class F:
        pass

    f = F()
    f.eng_a, f.eng_b = eng_a, eng_b
    f.url_a, f.url_b = url_a, url_b
    f.router, f.furl, f.sdk = router, furl, sdk
    f.home = str(home)
    yield f
    faults.clear()
    router.stop()
    fsrv.shutdown()
    srv_a.shutdown()
    srv_b.shutdown()
    eng_a.close(timeout=10)
    eng_b.close(timeout=10)


def test_healthz_warming_ready_draining(fleet):
    """Satellite: /healthz is a 3-state readiness gate — 503 before the
    engine is warm, 200 ready, 503 while draining."""
    srv = make_server(None, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        r = requests.get(url + "/healthz", timeout=5)
        assert r.status_code == 503 and r.json()["state"] == "warming"
        r = requests.get(url + "/fleet-state", timeout=5)
        assert r.status_code == 503 and r.json()["state"] == "warming"
        # non-health endpoints also refuse while warming (no 500s)
        assert requests.get(url + "/list-jobs", timeout=5).status_code == 503
        bind_engine(srv, fleet.eng_a)
        r = requests.get(url + "/healthz", timeout=5)
        assert r.status_code == 200
        assert r.json() == {"ok": True, "state": "ready", "v": 1}
        srv.draining = True
        r = requests.get(url + "/healthz", timeout=5)
        assert r.status_code == 503 and r.json()["state"] == "draining"
        r = requests.get(url + "/fleet-state", timeout=5)
        assert r.status_code == 503 and r.json()["draining"] is True
    finally:
        srv.shutdown()
        srv.server_close()


def test_degradation_new_replica_answers_old_router(fleet):
    """Vice-versa skew: an old router knows only GET /healthz — a new
    replica still answers it with the legacy 'ok' contract."""
    doc = requests.get(fleet.url_a + "/healthz", timeout=5).json()
    assert doc["ok"] is True
    # and the fleet frame is additive on top, not instead
    state = requests.get(fleet.url_a + "/fleet-state", timeout=5).json()
    assert state["t"] == "fleet_state" and state["ok"] is True
    assert frames.load_score(state["load"]) >= 0


def test_fleet_snapshot_doctor_and_metrics(fleet):
    doc = fleet.sdk.get_fleet()
    assert doc["n_replicas"] == 2 and doc["n_healthy"] == 2
    assert doc["doctor"]["verdict"] == "healthy"
    assert {r["rid"] for r in doc["replicas"]} == {"r0", "r1"}
    assert all(r["fleet_protocol"] for r in doc["replicas"])
    r = requests.get(fleet.furl + "/healthz", timeout=5)
    assert r.status_code == 200 and r.json()["role"] == "fleet-router"
    if telemetry.ENABLED:
        text = requests.get(fleet.furl + "/metrics", timeout=5).text
        assert 'sutro_fleet_replicas{state="healthy"} 2' in text


def test_routed_batch_submit_progress_and_results(fleet):
    jid = fleet.sdk.infer(
        [f"fleet row {i}" for i in range(6)],
        model="tiny-dense",
        stay_attached=False,
        sampling_params={"max_new_tokens": 5, "temperature": 0.0},
    )
    assert fleet.router.job_owner(jid) in ("r0", "r1")
    df = fleet.sdk.await_job_completion(jid, timeout=300)
    assert df is not None and len(df) == 6
    assert fleet.router.counters["batch_routed"] >= 1
    # job-scoped GETs route through the front door too
    assert (
        fleet.sdk.get_job_status(jid) == JobStatus.SUCCEEDED.value
    )
    rec = fleet.sdk._fetch_job(jid)
    assert rec["num_rows"] == 6


def test_interactive_routes_to_warm_replica(fleet):
    """Warm-prefix affinity: a live chat session's KV pins follow-up
    turns to the replica that holds it (probe_warm counts a session as
    warmth), tie-breaking least-loaded for cold traffic."""
    body = {
        "model": "tiny-dense",
        "messages": [{"role": "user", "content": "affinity probe turn"}],
        "session_id": "fleet-affinity-sess",
        "max_tokens": 4,
        "temperature": 0,
    }
    # warm replica B directly (not through the router)
    r = requests.post(
        fleet.url_b + "/v1/chat/completions", json=body, timeout=120
    )
    assert r.status_code == 200
    follow = dict(
        body,
        messages=[{"role": "user", "content": "second turn, same session"}],
    )
    cands, scores = fleet.router.candidates_interactive(follow, chat=True)
    assert scores["r1"] > 0 and scores.get("r0", 0) == 0
    assert cands[0]["rid"] == "r1"
    before = fleet.router.counters["prefix_hits"]
    r = requests.post(
        fleet.furl + "/v1/chat/completions", json=follow, timeout=120
    )
    assert r.status_code == 200 and r.json()["choices"]
    assert fleet.router.counters["prefix_hits"] == before + 1


def test_route_fault_retries_on_next_replica_before_first_token(fleet):
    """fleet.route failing the chosen replica pre-connect is invisible
    to the client: the request lands on the next candidate."""
    before = dict(fleet.router.counters)
    faults.configure("fleet.route:error:nth=1,times=1")
    try:
        r = requests.post(
            fleet.furl + "/v1/chat/completions",
            json={
                "model": "tiny-dense",
                "messages": [{"role": "user", "content": "retry me"}],
                "max_tokens": 4,
            },
            timeout=120,
        )
    finally:
        faults.clear()
    assert r.status_code == 200 and r.json()["choices"]
    after = fleet.router.counters
    assert after["failover_interactive"] == before["failover_interactive"] + 1
    assert after["interactive_routed"] == before["interactive_routed"] + 1


def test_drain_excludes_replica_without_failover(fleet):
    """SIGTERM drain integration: a draining replica is alive-but-
    unroutable — new work flows to its peers and no failover fires."""
    failovers_before = fleet.router.counters["failover_batch"]
    try:
        resp = requests.get(fleet.url_a + "/fleet-state", timeout=5)
        assert resp.status_code == 200
        # the flag the SIGTERM drain path flips (gateway.begin_drain);
        # the HTTP loop stays up so probes see alive-but-draining
        fleet.eng_a.gateway.begin_drain()
        _wait(
            lambda: fleet.router.membership.snapshot()["n_draining"] == 1,
            timeout=15, what="router to observe the drain",
        )
        snap = fleet.router.membership.snapshot()
        r0 = next(r for r in snap["replicas"] if r["rid"] == "r0")
        assert r0["draining"] and r0["state"] == "closed"
        assert snap["n_healthy"] == 1
        assert fleet.router.snapshot()["doctor"]["verdict"] == (
            "fleet_degraded"
        )
        jid = fleet.sdk.infer(
            ["drained row"], model="tiny-dense", stay_attached=False,
            sampling_params={"max_new_tokens": 4, "temperature": 0.0},
        )
        assert fleet.router.job_owner(jid) == "r1"
        fleet.sdk.await_job_completion(
            jid, timeout=300, obtain_results=False
        )
    finally:
        fleet.eng_a.gateway.draining = False
    _wait(
        lambda: fleet.router.membership.snapshot()["n_healthy"] == 2,
        timeout=15, what="replica to rejoin after drain",
    )
    assert fleet.router.counters["failover_batch"] == failovers_before


def test_degradation_legacy_replica_routes_probe_only(fleet):
    """Old replica vs new router, end to end: a replica whose server
    404s the fleet endpoints still serves traffic — probed via
    /healthz, excluded from warm affinity, counted probe_only."""
    eng = fleet.eng_b

    class LegacyHandler(EngineHTTPHandler):
        engine = eng

        def do_GET(self):  # noqa: N802
            head = self.path.split("?")[0].strip("/").partition("/")[0]
            if head == "fleet-state":
                self._error(404, f"Unknown endpoint GET /{head}")
                return
            super().do_GET()

        def do_POST(self):  # noqa: N802
            head = self.path.split("?")[0].strip("/").partition("/")[0]
            if head == "fleet-warm":
                self._error(404, f"Unknown endpoint POST /{head}")
                return
            super().do_POST()

    from http.server import ThreadingHTTPServer

    srv = ThreadingHTTPServer(("127.0.0.1", 0), LegacyHandler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    legacy_url = f"http://127.0.0.1:{srv.server_address[1]}"
    router2, fsrv2, _, furl2 = start_fleet_thread(
        [legacy_url], probe_interval=0.2
    )
    try:
        _wait(
            lambda: router2.membership.snapshot()["n_healthy"] == 1,
            timeout=15, what="legacy replica probed healthy",
        )
        row = router2.membership.get("r0")
        assert not row["fleet_protocol"] and not row["warm_probe"]
        r = requests.post(
            furl2 + "/v1/chat/completions",
            json={
                "model": "tiny-dense",
                "messages": [{"role": "user", "content": "legacy route"}],
                "max_tokens": 4,
            },
            timeout=120,
        )
        assert r.status_code == 200 and r.json()["choices"]
        assert router2.counters["probe_only_routes"] >= 1
        assert router2.counters["prefix_hits"] == 0
    finally:
        router2.stop()
        fsrv2.shutdown()
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------
# 4. chaos: replica death mid-stream and mid-batch-job
# ---------------------------------------------------------------------


def test_chaos_midstream_crash_yields_structured_error_not_hang(fleet):
    """A replica that dies AFTER the first streamed token cannot be
    retried transparently (tokens would replay): the client gets a
    structured SSE error frame + [DONE] within the stall timeout."""
    srv, _, url = start_server_thread(fleet.eng_a)
    router2, fsrv2, _, furl2 = start_fleet_thread(
        [url], probe_interval=0.2, stall_timeout=10.0
    )
    try:
        _wait(
            lambda: router2.membership.snapshot()["n_healthy"] == 1,
            timeout=15, what="replica healthy",
        )
        # warm the STREAMED interactive path (compiles + first-token
        # latency) so the faulted request below emits token frames
        # promptly instead of heartbeat pings — the fleet.replica_crash
        # site counts every streamed object, pings included, so a cold
        # stream would spend the nth budget on pings
        warm = requests.post(
            furl2 + "/v1/chat/completions",
            json={
                "model": "tiny-dense",
                "messages": [{"role": "user", "content": "warmup"}],
                "max_tokens": 4,
                "stream": True,
            },
            stream=True,
            timeout=120,
        )
        assert warm.status_code == 200
        warm_lines = [ln for ln in warm.iter_lines() if ln]
        assert warm_lines[-1] == b"data: [DONE]"
        faults.install(faults.parse_plan(json.dumps([
            {"site": "fleet.replica_crash", "kind": "crash",
             "job": "stream:", "nth": 3, "times": 1}
        ])))
        t0 = time.monotonic()
        r = requests.post(
            furl2 + "/v1/chat/completions",
            json={
                "model": "tiny-dense",
                "messages": [{"role": "user", "content": "stream then die"}],
                "max_tokens": 8,
                "stream": True,
            },
            stream=True,
            timeout=(5, 60),
        )
        assert r.status_code == 200
        lines = [
            ln.decode() for ln in r.iter_lines() if ln
        ]
        elapsed = time.monotonic() - t0
    finally:
        faults.clear()
        router2.stop()
        fsrv2.shutdown()
        srv.shutdown()
        srv.server_close()
    # at least one real frame relayed before the crash
    assert any(
        ln.startswith("data: {") and "error" not in ln for ln in lines
    )
    err_lines = [ln for ln in lines if '"error"' in ln]
    assert err_lines, f"no structured error frame in {lines}"
    err = json.loads(err_lines[-1][len("data: "):])["error"]
    assert err["code"] == 502 and err["replica"] == "r0"
    assert lines[-1] == "data: [DONE]"
    # bounded: well inside stall_timeout + slack, never a silent hang
    assert elapsed < 30.0
    assert router2.counters["failover_stream_error"] == 1
    assert fleet.router.counters["failover_stream_error"] == 0  # isolated


def test_chaos_replica_kill_mid_job_fails_over_bit_identical(fleet):
    """THE acceptance gate: kill a replica mid-batch-job; the router's
    breaker opens, the job resumes on a healthy replica through the
    shared jobstore, finishes SUCCEEDED with zero lost or duplicated
    rows, and (temperature 0) results are bit-identical to an
    un-killed run."""
    n = 12
    payload = {
        "model": "tiny-dense",
        "inputs": [f"failover row {i}" for i in range(n)],
        "sampling_params": {"max_new_tokens": 5, "temperature": 0.0},
        "job_priority": 0,
    }
    # reference: the same rows, no faults, straight on engine B
    jid_ref = fleet.eng_b.submit_batch_inference(dict(payload))
    _wait(
        lambda: JobStatus(fleet.eng_b.job_status(jid_ref)).is_terminal(),
        timeout=300, what="reference job",
    )
    assert fleet.eng_b.job_status(jid_ref) == JobStatus.SUCCEEDED.value
    ref = fleet.eng_b.job_results(jid_ref)["outputs"]

    srv_a, _, url_a = start_server_thread(fleet.eng_a)
    srv_b, _, url_b = start_server_thread(fleet.eng_b)
    servers = {"r0": srv_a, "r1": srv_b}
    router2, fsrv2, _, furl2 = start_fleet_thread(
        [url_a, url_b], probe_interval=0.2
    )
    from sutro_tpu.sdk import Sutro

    sdk2 = Sutro(api_key="k", base_url=furl2, backend="fleet")
    store = fleet.eng_b.jobs  # either handle: the jobstore is shared
    try:
        _wait(
            lambda: router2.membership.snapshot()["n_healthy"] == 2,
            timeout=15, what="both replicas healthy",
        )
        # the job dies on its first owner after partial progress
        faults.configure("runner.decode:oom:nth=2,times=1")
        jid = sdk2.infer(
            payload["inputs"], model="tiny-dense", stay_attached=False,
            sampling_params=payload["sampling_params"],
        )
        owner = router2.job_owner(jid)
        assert owner in ("r0", "r1")
        survivor = "r1" if owner == "r0" else "r0"
        _wait(
            lambda: store.status(jid) == JobStatus.FAILED,
            timeout=300, what="job to fail on its first owner",
        )
        faults.clear()
        # rows completed before the fault are already in the shared
        # partial store — the resumed run must skip, not regenerate
        partial_rows = set(store.read_partial(jid).keys())
        # now the replica actually dies (connection refused)
        servers[owner].shutdown()
        servers[owner].server_close()
        _wait(
            lambda: router2.counters["failover_batch"] >= 1,
            timeout=60, what="router to fail the job over",
        )
        assert router2.job_owner(jid) == survivor
        _wait(
            lambda: sdk2.get_job_status(jid)
            == JobStatus.SUCCEEDED.value,
            timeout=300, what="failed-over job to succeed",
        )
        snap = router2.snapshot()
        assert snap["n_healthy"] == 1
        assert snap["doctor"]["verdict"] != "healthy"
        assert telemetry is not None  # counters live on the router too
        assert snap["failovers"]["batch"] >= 1
        # zero rows lost, zero duplicated (chunk-granular first-result-
        # wins over the shared store)
        df = store.read_results(jid)
        assert sorted(df["row_id"].tolist()) == list(range(n))
        # bit-identical to the un-killed reference at temperature 0
        assert fleet.eng_b.job_results(jid)["outputs"] == ref
        if partial_rows:
            # the pre-crash partials survived as-is into the final set
            assert partial_rows <= set(df["row_id"].tolist())
    finally:
        faults.clear()
        router2.stop()
        fsrv2.shutdown()
        for srv in servers.values():
            try:
                srv.shutdown()
                srv.server_close()
            except OSError:
                pass


def test_chaos_sdk_progress_reconnects_with_cursor(fleet):
    """Satellite: the SDK's progress tail survives a daemon restart —
    reconnect with ?cursor resumes the stream monotonically instead of
    raising or replaying rows."""
    port = free_low_port()
    srv, _, url = start_server_thread(fleet.eng_b, port=port)
    from sutro_tpu.sdk import Sutro

    sdk3 = Sutro(api_key="k", base_url=url, backend="remote")
    restarted = []
    try:
        jid = sdk3.infer(
            [f"reconnect row {i}" for i in range(24)],
            model="tiny-dense", stay_attached=False,
            sampling_params={"max_new_tokens": 8, "temperature": 0.0},
        )
        # the replica crashes mid-progress-stream (no terminal frame),
        # taking its HTTP loop down with it
        faults.install(faults.parse_plan(json.dumps([
            {"site": "fleet.replica_crash", "kind": "crash",
             "job": "stream:" + jid, "nth": 3, "times": 1}
        ])))

        def restarter():
            # the crashed server's listen socket stays bound (only the
            # accept loop died), so liveness needs a served exchange,
            # not a bare connect
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    requests.get(url + "/healthz", timeout=(0.5, 0.5))
                    time.sleep(0.02)
                except requests.RequestException:
                    break
            else:
                return
            faults.clear()
            srv.server_close()
            restarted.append(start_server_thread(fleet.eng_b, port=port))

        t = threading.Thread(target=restarter, daemon=True)
        t.start()
        progress = []
        for update in sdk3._iter_progress(jid):
            if update.get("update_type") == "progress":
                progress.append(int(update.get("result") or 0))
        t.join(timeout=60)
        assert restarted, "server was never restarted (crash not fired?)"
        # monotone across the reconnect: the cursor suppressed replays
        assert progress and all(
            b >= a for a, b in zip(progress, progress[1:])
        )
        sdk3.await_job_completion(jid, timeout=300, obtain_results=False)
        assert sdk3.get_job_status(jid) == JobStatus.SUCCEEDED.value
    finally:
        faults.clear()
        for extra in restarted:
            extra[0].shutdown()
            extra[0].server_close()
        try:
            srv.shutdown()
            srv.server_close()
        except OSError:
            pass


def test_cli_fleet_status_renders_router_snapshot(fleet, monkeypatch):
    from click.testing import CliRunner

    from sutro_tpu import cli as cli_mod

    runner = CliRunner()
    out = runner.invoke(
        cli_mod.cli, ["set-base-url", fleet.furl],
    )
    assert out.exit_code == 0
    out = runner.invoke(cli_mod.cli, ["set-backend", "fleet"])
    assert out.exit_code == 0
    out = runner.invoke(cli_mod.cli, ["fleet", "status", "--json"])
    assert out.exit_code == 0, out.output
    doc = json.loads(out.output)
    assert doc["n_replicas"] == 2
    out = runner.invoke(cli_mod.cli, ["fleet", "status"])
    assert out.exit_code == 0, out.output
    assert "verdict" in out.output
