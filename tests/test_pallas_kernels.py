"""Pallas kernel correctness (interpret mode on CPU).

Each kernel is validated against the pure-jnp reference path in
ops/attention.py — the always-correct fallback — over the shape/flag
matrix the engine actually uses (GQA, sliding windows, sinks, ragged
past lengths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sutro_tpu.ops.attention import chunk_attention
from sutro_tpu.ops.pallas_paged import paged_decode_attention


def _make_decode_case(
    rng, *, B=3, NH=4, KVH=2, Dh=16, PS=8, MP=6, NP=32, past=None
):
    q = jnp.asarray(rng.standard_normal((B, 1, NH, Dh)), jnp.float32)
    k_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    v_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    # pools carry the fused [NP, PS, KVH*Dh] layout (engine/kvcache.py)
    k_pages = jnp.asarray(
        rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32
    )
    # distinct pages per row
    table = np.zeros((B, MP), np.int32)
    next_p = 1
    for b in range(B):
        table[b] = np.arange(next_p, next_p + MP)
        next_p += MP
    if past is None:
        past = rng.integers(1, MP * PS, B)
    past_len = jnp.asarray(past, jnp.int32)
    return q, k_cur, v_cur, k_pages, v_pages, jnp.asarray(table), past_len


@pytest.mark.parametrize("window", [0, 5])
@pytest.mark.parametrize("with_sink", [False, True])
def test_paged_decode_matches_reference(window, with_sink):
    rng = np.random.default_rng(42)
    NH = 4
    q, k_cur, v_cur, kp, vp, table, past_len = _make_decode_case(rng)
    sink = (
        jnp.asarray(rng.standard_normal(NH), jnp.float32)
        if with_sink
        else None
    )
    win = jnp.asarray(window, jnp.int32)
    B = q.shape[0]
    positions = past_len[:, None]

    ref = chunk_attention(
        q, k_cur, v_cur,
        positions=positions,
        valid_len=jnp.ones((B,), jnp.int32),
        past_k_pages=kp, past_v_pages=vp, page_table=table,
        past_len=past_len, window=win, sink=sink,
        use_pallas=False,
    )
    got = paged_decode_attention(
        q[:, 0], kp, vp, table, past_len, k_cur[:, 0], v_cur[:, 0],
        win, sink, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref[:, 0]), atol=2e-5, rtol=2e-5
    )


def test_paged_decode_zero_past():
    """First decode step after an empty prefill: only self-attention."""
    rng = np.random.default_rng(0)
    q, k_cur, v_cur, kp, vp, table, _ = _make_decode_case(rng)
    past_len = jnp.zeros((q.shape[0],), jnp.int32)
    got = paged_decode_attention(
        q[:, 0], kp, vp, table, past_len, k_cur[:, 0], v_cur[:, 0],
        jnp.asarray(0, jnp.int32), None, interpret=True,
    )
    # softmax over a single key == that key's value
    want = jnp.repeat(v_cur[:, 0], q.shape[2] // k_cur.shape[2], axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-5
    )


def test_decode_step_via_runner_matches_dense(tiny_ecfg):
    """End-to-end: the runner's paged decode (jnp path after refactor)
    still reproduces full-context forward logits."""
    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.models import transformer
    from sutro_tpu.models.configs import MODEL_CONFIGS

    cfg = MODEL_CONFIGS["tiny-dense"]
    runner = ModelRunner(cfg, tiny_ecfg)
    rng = np.random.default_rng(1)
    n = 11
    prompt = rng.integers(0, 200, n).astype(np.int32)
    table = np.zeros((tiny_ecfg.max_pages_per_seq,), np.int32)
    table[:4] = [1, 2, 3, 4]
    logits = runner.prefill(prompt, table)

    nxt = int(np.argmax(logits))
    B = tiny_ecfg.decode_batch_size
    tables = np.zeros((B, tiny_ecfg.max_pages_per_seq), np.int32)
    tables[0] = table
    last = np.zeros((B,), np.int32)
    last[0] = nxt
    past = np.zeros((B,), np.int32)
    past[0] = n
    toks, _ = runner.decode_step(
        last, past, tables, jax.random.PRNGKey(0),
        np.zeros((B,), np.float32),  # greedy
        np.ones((B,), np.float32),
    )

    # dense reference over prompt + nxt
    full = np.concatenate([prompt, [nxt]]).astype(np.int32)
    ids = jnp.asarray(full[None])
    pos = jnp.arange(len(full), dtype=jnp.int32)[None]
    vlen = jnp.asarray([len(full)], jnp.int32)
    ref_logits, _, _ = transformer.forward(
        cfg, runner.params, ids, pos, vlen
    )
    ref_tok = int(np.argmax(np.asarray(ref_logits[0, -1])))
    assert int(toks[0]) == ref_tok


# ---------------------------------------------------------------------------
# flash prefill kernel
# ---------------------------------------------------------------------------

from sutro_tpu.ops.pallas_flash import (  # noqa: E402
    flash_prefill,
    flash_prefill_supported,
)


def _make_prefill_case(rng, *, B=2, T=128, NH=4, KVH=2, Dh=128):
    q = jnp.asarray(rng.standard_normal((B, T, NH, Dh)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, KVH, Dh)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, KVH, Dh)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("window", [0, 5, 200])
@pytest.mark.parametrize("with_sink", [False, True])
def test_flash_prefill_matches_reference(window, with_sink):
    rng = np.random.default_rng(7)
    B, T, NH = 2, 256, 4
    q, k, v = _make_prefill_case(rng, B=B, T=T, NH=NH)
    sink = (
        jnp.asarray(rng.standard_normal(NH), jnp.float32)
        if with_sink
        else None
    )
    win = jnp.asarray(window, jnp.int32)
    positions = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None], (B, T)
    )
    valid_len = jnp.full((B,), T, jnp.int32)

    ref = chunk_attention(
        q, k, v, positions=positions, valid_len=valid_len,
        window=win, sink=sink, use_pallas=False,
    )
    got = flash_prefill(q, k, v, window=win, sink=sink, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_prefill_ragged_valid_len():
    """Padded rows: every used position (t < valid_len) must match the
    jnp path, which additionally masks padded keys — causality makes the
    two equivalent on used rows."""
    rng = np.random.default_rng(11)
    B, T = 3, 128
    q, k, v = _make_prefill_case(rng, B=B, T=T)
    valid = jnp.asarray([128, 57, 1], jnp.int32)
    positions = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None], (B, T)
    )
    ref = chunk_attention(
        q, k, v, positions=positions, valid_len=valid,
        window=None, sink=None, use_pallas=False,
    )
    got = flash_prefill(q, k, v, interpret=True)
    for b in range(B):
        n = int(valid[b])
        np.testing.assert_allclose(
            np.asarray(got)[b, :n],
            np.asarray(ref)[b, :n],
            atol=2e-5,
            rtol=2e-5,
        )


def test_flash_prefill_gate():
    rng = np.random.default_rng(0)
    q, k, v = _make_prefill_case(rng, B=1, T=128)
    assert flash_prefill_supported(q, k, None, None)
    q2, k2, _ = _make_prefill_case(rng, B=1, T=64)  # sub-block chunk
    assert not flash_prefill_supported(q2, k2, None, None)
    q3 = jnp.zeros((1, 128, 4, 64), jnp.float32)  # Dh % 128 != 0
    k3 = jnp.zeros((1, 128, 2, 64), jnp.float32)
    assert not flash_prefill_supported(q3, k3, None, None)


@pytest.mark.parametrize("window", [0, 5])
def test_paged_decode_with_window_buffer(window):
    """Fused-window variant: pages + window buffer + current token must
    reproduce the jnp reference fed the same window K/V."""
    rng = np.random.default_rng(21)
    NH, KVH, Dh, W = 4, 2, 16, 8
    q, k_cur, v_cur, kp, vp, table, past_len = _make_decode_case(rng)
    B = q.shape[0]
    # window buffers carry the fused [B, W, KVH*Dh] layout
    win_k = jnp.asarray(
        rng.standard_normal((B, W, KVH * Dh)), jnp.float32
    )
    win_v = jnp.asarray(
        rng.standard_normal((B, W, KVH * Dh)), jnp.float32
    )
    win_len = jnp.asarray(5, jnp.int32)  # slots 0..4 valid
    win = jnp.asarray(window, jnp.int32)
    positions = (past_len + win_len)[:, None]

    ref = chunk_attention(
        q, k_cur, v_cur,
        positions=positions,
        valid_len=jnp.ones((B,), jnp.int32),
        past_k_pages=kp, past_v_pages=vp, page_table=table,
        past_len=past_len, window=win, sink=None,
        use_pallas=False,
        win_k=win_k, win_v=win_v, win_len=win_len,
    )
    got = paged_decode_attention(
        q[:, 0], kp, vp, table, past_len, k_cur[:, 0], v_cur[:, 0],
        win, None, win_k=win_k, win_v=win_v, win_len=win_len,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref[:, 0]), atol=2e-5, rtol=2e-5
    )


# ---------------------------------------------------------------------------
# grouped matmul (MoE expert GEMM)
# ---------------------------------------------------------------------------

from sutro_tpu.ops.pallas_gmm import grouped_matmul  # noqa: E402


@pytest.mark.parametrize(
    "sizes",
    [
        [100, 28, 0, 128],       # ragged + one empty group
        [64, 64, 64, 64],        # tile-aligned
        [256, 0, 0, 0],          # single hot expert
        [1, 2, 3, 250],          # tiny groups
    ],
)
def test_grouped_matmul_matches_ragged_dot(sizes):
    rng = np.random.default_rng(13)
    E, H, F = len(sizes), 128, 256
    M = sum(sizes)
    lhs = jnp.asarray(rng.standard_normal((M, H)), jnp.float32)
    rhs = jnp.asarray(rng.standard_normal((E, H, F)), jnp.float32)
    gs = jnp.asarray(sizes, jnp.int32)
    want = jax.lax.ragged_dot(lhs, rhs, gs)
    got = grouped_matmul(lhs, rhs, gs, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


@pytest.mark.parametrize("kv_chunk", [2, 3])
def test_paged_decode_chunked_contiguous(kv_chunk):
    """Contiguous-KV mode: fetching kv_chunk pages per DMA over an
    ascending page run must match the per-page walk and the jnp
    reference (over-read past the run is masked by past_len)."""
    rng = np.random.default_rng(31)
    B, NH, KVH, Dh, PS, MP, NP = 3, 4, 2, 16, 8, 6, 64
    q = jnp.asarray(rng.standard_normal((B, 1, NH, Dh)), jnp.float32)
    k_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    v_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32)
    # ascending contiguous runs per row
    table = np.zeros((B, MP), np.int32)
    starts = [1, 11, 21]
    for b in range(B):
        table[b] = np.arange(starts[b], starts[b] + MP)
    table = jnp.asarray(table)
    past_len = jnp.asarray([5, 17, MP * PS - 1], jnp.int32)
    win = jnp.asarray(0, jnp.int32)

    ref = chunk_attention(
        q, k_cur, v_cur,
        positions=past_len[:, None],
        valid_len=jnp.ones((B,), jnp.int32),
        past_k_pages=kp, past_v_pages=vp, page_table=table,
        past_len=past_len, window=win, sink=None,
        use_pallas=False,
    )
    got = paged_decode_attention(
        q[:, 0], kp, vp, table, past_len, k_cur[:, 0], v_cur[:, 0],
        win, None, kv_chunk=kv_chunk, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref[:, 0]), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("kv_chunk", [1, 2])
def test_paged_decode_cross_row_handoff(kv_chunk):
    """cross_row mode (row b prefetches row b+1's first chunk) must be
    bit-identical to the independent-row kernel, including across a
    zero-past row in the middle (handoff predicate skips it) and ragged
    chunk counts (slot parity never collides)."""
    rng = np.random.default_rng(77)
    B, NH, KVH, Dh, PS, MP, NP = 4, 4, 2, 16, 8, 6, 64
    q = jnp.asarray(rng.standard_normal((B, 1, NH, Dh)), jnp.float32)
    k_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    v_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32)
    table = np.zeros((B, MP), np.int32)
    starts = [1, 11, 21, 31]
    for b in range(B):
        table[b] = np.arange(starts[b], starts[b] + MP)
    table = jnp.asarray(table)
    # odd/even chunk counts + an empty row mid-batch
    past_len = jnp.asarray([5, 0, 17, MP * PS - 1], jnp.int32)
    win = jnp.asarray(0, jnp.int32)

    base = paged_decode_attention(
        q[:, 0], kp, vp, table, past_len, k_cur[:, 0], v_cur[:, 0],
        win, None, kv_chunk=kv_chunk, interpret=True, cross_row=False,
    )
    xrow = paged_decode_attention(
        q[:, 0], kp, vp, table, past_len, k_cur[:, 0], v_cur[:, 0],
        win, None, kv_chunk=kv_chunk, interpret=True, cross_row=True,
    )
    np.testing.assert_array_equal(np.asarray(xrow), np.asarray(base))


# ---------------------------------------------------------------------------
# KV page write kernel (RMW + roll)
# ---------------------------------------------------------------------------

from sutro_tpu.engine.kvcache import KVCache, write_kv  # noqa: E402
from sutro_tpu.ops.pallas_kv import kv_write_pallas  # noqa: E402


@pytest.mark.parametrize(
    "starts,valids,tb",
    [
        ([0, 8, 3], [16, 16, 5], 16),    # aligned, offset, ragged
        ([7, 60, 0], [16, 9, 0], 16),    # page-crossing, empty row
        ([0, 5, 63], [40, 33, 1], 40),   # multi-page runs
    ],
)
def test_kv_write_pallas_matches_scatter(starts, valids, tb):
    """The RMW+roll write kernel (interpret mode) must land exactly the
    same bytes as the XLA scatter fallback, at any offset/page split,
    and leave every untouched row intact."""
    rng = np.random.default_rng(5)
    L, NP, PS, KD = 2, 12, 8, 256
    B, MP = 3, 4
    k0 = jnp.asarray(rng.standard_normal((L, NP, PS, KD)), jnp.float32)
    v0 = jnp.asarray(rng.standard_normal((L, NP, PS, KD)), jnp.float32)
    table = np.zeros((B, MP), np.int32)
    nxt = 1
    for b in range(B):
        table[b] = np.arange(nxt, nxt + MP)
        nxt += MP
    kc = jnp.asarray(rng.standard_normal((L, B, tb, KD)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((L, B, tb, KD)), jnp.float32)
    start = jnp.asarray(starts, jnp.int32)
    valid = jnp.asarray(valids, jnp.int32)
    tab = jnp.asarray(table)

    ref = write_kv(
        KVCache(k_pages=k0, v_pages=v0), kc, vc, tab, start, valid,
        use_pallas=False,
    )
    got_k, got_v = kv_write_pallas(
        k0.copy(), v0.copy(), kc, vc, tab, start, valid, interpret=True
    )
    # page 0 is the garbage page: the scatter fallback dumps invalid
    # tokens there, the kernel skips them — its content is unspecified
    np.testing.assert_array_equal(
        np.asarray(got_k)[:, 1:], np.asarray(ref.k_pages)[:, 1:]
    )
    np.testing.assert_array_equal(
        np.asarray(got_v)[:, 1:], np.asarray(ref.v_pages)[:, 1:]
    )


@pytest.mark.parametrize("window", [0, 7])
@pytest.mark.parametrize("quantized", [False, True])
def test_paged_decode_prefix_carry_injection(window, quantized):
    """Shared-prefix (Hydragen-style) mode: computing the table-head
    prefix's attention ONCE outside the kernel (prefix_attention_carry)
    and injecting it as the online-softmax carry while the kernel skips
    those pages must match the plain kernel walking the full table —
    including rows OUTSIDE the prefix group (pfx_cnt 0, cold carry) and
    under sliding windows that cut into or past the prefix."""
    from sutro_tpu.ops.pallas_paged import prefix_attention_carry

    rng = np.random.default_rng(7)
    B, NH, KVH, Dh, PS, MP, NP = 4, 4, 2, 16, 8, 6, 40
    n_pfx = 3  # 24 prefix tokens
    q = jnp.asarray(rng.standard_normal((B, NH, Dh)), jnp.float32)
    k_cur = jnp.asarray(rng.standard_normal((B, KVH, Dh)), jnp.float32)
    v_cur = jnp.asarray(rng.standard_normal((B, KVH, Dh)), jnp.float32)
    if quantized:
        k_pages = jnp.asarray(
            rng.integers(-127, 127, (NP, PS, KVH * Dh)), jnp.int8
        )
        v_pages = jnp.asarray(
            rng.integers(-127, 127, (NP, PS, KVH * Dh)), jnp.int8
        )
        k_scale = jnp.asarray(
            rng.uniform(0.005, 0.02, (NP, PS)), jnp.float32
        )
        v_scale = jnp.asarray(
            rng.uniform(0.005, 0.02, (NP, PS)), jnp.float32
        )
    else:
        k_pages = jnp.asarray(
            rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32
        )
        v_pages = jnp.asarray(
            rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32
        )
        k_scale = v_scale = None
    # rows 0..2 share prefix pages [1, 2, 3]; row 3 is NOT in the group
    pfx_pages = np.array([1, 2, 3], np.int32)
    table = np.zeros((B, MP), np.int32)
    next_p = 4
    for b in range(B):
        if b < 3:
            table[b, :n_pfx] = pfx_pages
            own = np.arange(next_p, next_p + (MP - n_pfx))
            table[b, n_pfx:] = own
            next_p += MP - n_pfx
        else:
            table[b] = np.arange(next_p, next_p + MP)
            next_p += MP
    # member rows: past spans prefix + some own tokens; non-member: own
    past = np.array(
        [n_pfx * PS + 5, n_pfx * PS + 11, n_pfx * PS + 2, 17], np.int32
    )
    table = jnp.asarray(table)
    past_len = jnp.asarray(past)
    win = jnp.asarray(window, jnp.int32)

    ref = paged_decode_attention(
        q, k_pages, v_pages, table, past_len, k_cur, v_cur, win, None,
        interpret=True, cross_row=False,
        k_scale=k_scale, v_scale=v_scale,
    )

    pfx_len = jnp.asarray(
        [n_pfx * PS, n_pfx * PS, n_pfx * PS, 0], jnp.int32
    )
    pfx_cnt = jnp.asarray([n_pfx, n_pfx, n_pfx, 0], jnp.int32)
    m0, l0, acc0 = prefix_attention_carry(
        q, k_pages, v_pages, jnp.asarray(pfx_pages), pfx_len,
        past_len,  # q_pos: no window buffer, query sits at past_len
        win, k_scale=k_scale, v_scale=v_scale,
    )
    got = paged_decode_attention(
        q, k_pages, v_pages, table, past_len, k_cur, v_cur, win, None,
        interpret=True, cross_row=False,
        k_scale=k_scale, v_scale=v_scale,
        pfx_cnt=pfx_cnt, m0=m0, l0=l0, acc0=acc0,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("window", [0, 7, 2])
def test_prefix_carry_pallas_matches_xla_gather(window):
    """In-place prefix-carry kernel (page-indexed BlockSpecs reading the
    shared pages straight from the pool) vs the XLA gather reference —
    same (m, l, acc) carry, including windows that cut into the prefix
    and rows outside the group (pfx_len 0). window=2 masks the WHOLE
    prefix for every row: both paths must agree on the all-masked carry
    (l == 0, acc == 0)."""
    from sutro_tpu.ops.pallas_paged import (
        prefix_attention_carry,
        prefix_attention_carry_pallas,
    )

    rng = np.random.default_rng(11)
    B, NH, KVH, Dh, PS, NP = 4, 4, 2, 16, 8, 40
    n_pfx = 3
    q = jnp.asarray(rng.standard_normal((B, NH, Dh)), jnp.float32)
    k_pages = jnp.asarray(
        rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32
    )
    pfx_pages = jnp.asarray([1, 2, 3], jnp.int32)
    pfx_len = jnp.asarray(
        [n_pfx * PS, n_pfx * PS, n_pfx * PS, 0], jnp.int32
    )
    q_pos = jnp.asarray([29, 35, 26, 17], jnp.int32)
    win = jnp.asarray(window, jnp.int32)

    m_ref, l_ref, a_ref = prefix_attention_carry(
        q, k_pages, v_pages, pfx_pages, pfx_len, q_pos, win
    )
    m_got, l_got, a_got = prefix_attention_carry_pallas(
        q, k_pages, v_pages, pfx_pages, pfx_len, q_pos, win,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(l_got), np.asarray(l_ref), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(a_got), np.asarray(a_ref), rtol=2e-5, atol=2e-5
    )
    # m only matters where something was in range (l > 0); all-masked
    # rows carry an arbitrary -inf-ish max in both implementations
    live = np.asarray(l_ref) > 0
    np.testing.assert_allclose(
        np.asarray(m_got)[live], np.asarray(m_ref)[live],
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("window", [0, 7])
def test_paged_decode_with_pallas_carry_injection(window):
    """End-to-end: the in-place kernel's carry injected into the paged
    decode kernel must match the plain kernel walking the full table —
    the exact composition ops/attention.py runs on the split-prefix
    decode path when prefix_carry_supported holds."""
    from sutro_tpu.ops.pallas_paged import prefix_attention_carry_pallas

    rng = np.random.default_rng(13)
    B, NH, KVH, Dh, PS, MP, NP = 4, 4, 2, 16, 8, 6, 40
    n_pfx = 3
    q = jnp.asarray(rng.standard_normal((B, NH, Dh)), jnp.float32)
    k_cur = jnp.asarray(rng.standard_normal((B, KVH, Dh)), jnp.float32)
    v_cur = jnp.asarray(rng.standard_normal((B, KVH, Dh)), jnp.float32)
    k_pages = jnp.asarray(
        rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32
    )
    pfx_pages = np.array([1, 2, 3], np.int32)
    table = np.zeros((B, MP), np.int32)
    next_p = 4
    for b in range(B):
        if b < 3:
            table[b, :n_pfx] = pfx_pages
            table[b, n_pfx:] = np.arange(
                next_p, next_p + (MP - n_pfx)
            )
            next_p += MP - n_pfx
        else:
            table[b] = np.arange(next_p, next_p + MP)
            next_p += MP
    past = np.array(
        [n_pfx * PS + 5, n_pfx * PS + 11, n_pfx * PS + 2, 17], np.int32
    )
    table = jnp.asarray(table)
    past_len = jnp.asarray(past)
    win = jnp.asarray(window, jnp.int32)

    ref = paged_decode_attention(
        q, k_pages, v_pages, table, past_len, k_cur, v_cur, win, None,
        interpret=True, cross_row=False,
    )
    pfx_len = jnp.asarray(
        [n_pfx * PS, n_pfx * PS, n_pfx * PS, 0], jnp.int32
    )
    pfx_cnt = jnp.asarray([n_pfx, n_pfx, n_pfx, 0], jnp.int32)
    m0, l0, acc0 = prefix_attention_carry_pallas(
        q, k_pages, v_pages, jnp.asarray(pfx_pages), pfx_len,
        past_len, win, interpret=True,
    )
    got = paged_decode_attention(
        q, k_pages, v_pages, table, past_len, k_cur, v_cur, win, None,
        interpret=True, cross_row=False,
        pfx_cnt=pfx_cnt, m0=m0, l0=l0, acc0=acc0,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_prefix_carry_supported_flags():
    """Shape gate for the in-place kernel: lane-aligned fused KV dim,
    sublane-aligned page size, float pool only (int8 KV rides the XLA
    gather fallback)."""
    from sutro_tpu.ops.pallas_paged import prefix_carry_supported

    q = jnp.zeros((2, 4, 128), jnp.float32)          # Dh lane-aligned
    good = jnp.zeros((8, 8, 256), jnp.float32)
    assert prefix_carry_supported(q, good)
    assert not prefix_carry_supported(
        jnp.zeros((2, 4, 16), jnp.float32),          # Dh = 16
        jnp.zeros((8, 8, 32), jnp.float32),
    )
    assert not prefix_carry_supported(
        q, jnp.zeros((8, 6, 256), jnp.float32)       # PS % 8 != 0
    )
    assert not prefix_carry_supported(
        q, good, k_scale=jnp.zeros((8, 8), jnp.float32)
    )
