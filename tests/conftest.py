"""Test bootstrap: force CPU with 8 virtual devices so TP/DP/EP sharding
logic runs multi-device in CI without TPUs (SURVEY §4 'lesson for the
build'). Must run before jax is imported anywhere."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# tests must neither populate a persistent cache under the real
# ~/.sutro nor latch the process-global cache dir to a pytest tmp
# SUTRO_HOME that gets deleted at teardown (engine/config.py
# enable_compile_cache; its own tests monkeypatch this off)
os.environ.setdefault("SUTRO_COMPILE_CACHE", "0")
# ... but the suite still wants compiled-program sharing: every
# ModelRunner builds fresh jit closures, so the scheduler+pallas
# region recompiles identical tiny-model programs dozens of times.
# A session-private cache dir is safe where enable_compile_cache's
# CPU opt-out is not — the SIGILL hazard there is CROSS-process
# (host-feature detection can differ between processes); here the
# one pytest process that wrote an entry is the only reader.
import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

_xla_cache_dir = tempfile.mkdtemp(prefix="sutro-test-xla-cache-")
atexit.register(shutil.rmtree, _xla_cache_dir, ignore_errors=True)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon environment pins jax.config.jax_platforms programmatically in
# sitecustomize (overriding the env var), so force CPU through the config
# API too — before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# threshold 0 so the sub-second tiny-model compiles actually persist
# (the 2.0 s production floor in enable_compile_cache would keep the
# cache empty for every program this suite builds)
jax.config.update("jax_compilation_cache_dir", _xla_cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from sutro_tpu.engine.config import EngineConfig  # noqa: E402
from sutro_tpu.engine.tokenizer import ByteTokenizer  # noqa: E402
from sutro_tpu.models.configs import MODEL_CONFIGS  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()[:8]


@pytest.fixture(scope="session")
def mesh_ecfg():
    """Tiny engine config for multi-device sharding tests."""
    return EngineConfig(
        kv_page_size=8, max_pages_per_seq=8, decode_batch_size=4,
        max_model_len=64, use_pallas=False, param_dtype="float32",
    )


@pytest.fixture(scope="module")
def monkeypatch_module():
    mp = pytest.MonkeyPatch()
    yield mp
    mp.undo()


@pytest.fixture(scope="session")
def tiny_ecfg() -> EngineConfig:
    return EngineConfig(
        kv_page_size=8,
        max_pages_per_seq=16,
        decode_batch_size=4,
        max_model_len=128,
        use_pallas=False,
        param_dtype="float32",
        activation_dtype="float32",
    )


@pytest.fixture(scope="session")
def byte_tok() -> ByteTokenizer:
    return ByteTokenizer(vocab_size=MODEL_CONFIGS["tiny-dense"].vocab_size)


@pytest.fixture(scope="session")
def tiny_runner(tiny_ecfg):
    from sutro_tpu.engine.runner import ModelRunner

    return ModelRunner(MODEL_CONFIGS["tiny-dense"], tiny_ecfg)


@pytest.fixture(scope="session")
def live_engine(tmp_path_factory):
    """ONE compiled tiny engine + HTTP daemon shared by test_sdk.py and
    test_serving.py (tier-1 wall time: two engine builds -> one). The
    geometry is the union of what both suites need: interactive tier on,
    batch defaults matching the old sdk fixture. Tests that mutate
    engine state must restore it (they do — see test_serving.py's
    drain/disable tests)."""
    mp = pytest.MonkeyPatch()
    home = tmp_path_factory.mktemp("shared-live-home")
    mp.setenv("SUTRO_HOME", str(home))
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.server import start_server_thread

    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", max_new_tokens=16,
        interactive_slots=2,
    )
    engine = LocalEngine(ecfg)
    server, thread, url = start_server_thread(engine)
    yield engine, url, str(home)
    from sutro_tpu.engine import faults

    faults.clear()
    server.shutdown()
    engine.close(timeout=10)
    mp.undo()


def make_requests(tok, texts, **kw):
    from sutro_tpu.engine.scheduler import GenRequest

    return [
        GenRequest(
            row_id=i, prompt_ids=np.array(tok.encode(t), np.int32), **kw
        )
        for i, t in enumerate(texts)
    ]


def free_low_port() -> int:
    """A port OUTSIDE the kernel's ephemeral range (32768+ on this
    host): bind-port-0 hands back an ephemeral port that any outgoing
    TCP connection on the box (background probes, other tests) can be
    assigned as its SOURCE port between our close() and the engine's
    bind — an observed EADDRINUSE flake once the suite ran with no
    retries. Low-range ports are never auto-assigned to clients, so
    the only residual race is another caller, made unlikely by
    randomization."""
    import random
    import socket

    for _ in range(64):
        cand = random.randrange(20000, 31000)
        with socket.socket() as s:
            try:
                s.bind(("127.0.0.1", cand))
            except OSError:
                continue
            return cand
    raise RuntimeError("no free low-range port found")
