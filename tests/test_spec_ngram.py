"""Prompt-lookup (n-gram) speculative decoding for greedy rows
(EngineConfig.spec_ngram_draft, VERDICT r3 next-step 7): drafts come
from the row's own prompt/output history and are verified in ONE
parallel forward; outputs must be IDENTICAL to the non-speculative
path (exact for greedy), with acceptance counters in the job stats."""

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
from sutro_tpu.models.configs import MODEL_CONFIGS


def _ecfg(**kw):
    base = dict(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32",
    )
    base.update(kw)
    return EngineConfig(**base)


# repetitive prompts so bigram lookups actually fire
TEXTS = [
    "the cat sat on the mat the cat sat on the",
    "abc abc abc abc abc abc",
    "one two one two one two one",
]


def _reqs(tok, texts=TEXTS, **kw):
    return [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(tok.encode(t), np.int32),
            **kw,
        )
        for i, t in enumerate(texts)
    ]


def _run(ecfg, tok, reqs):
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
    b = ContinuousBatcher(runner, stop_ids=tok.stop_ids())
    res = {}
    out = b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
    assert out == "completed"
    return b, res


def test_ngram_draft_lookup():
    from sutro_tpu.engine.scheduler import _Slot

    def slot(ids, out=()):
        s = _Slot(
            req=GenRequest(row_id=0, prompt_ids=np.array(ids, np.int32)),
            pages=[1, 2, 3, 4],
            pos=len(ids) + len(out),
            last_token=0,
        )
        s.out_ids = list(out)
        return s

    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg())
    b = ContinuousBatcher(runner, stop_ids=[0])
    # history ...5,6,7 ... 5,6 -> last bigram (5,6) matched earlier,
    # draft continues 7,8,9
    d = b._ngram_draft(slot([1, 5, 6, 7, 8, 9, 2, 5, 6]), 3)
    assert d is not None and d.tolist() == [7, 8, 9]
    # most RECENT occurrence wins
    d = b._ngram_draft(slot([5, 6, 1, 5, 6, 2, 9, 5, 6]), 2)
    assert d.tolist() == [2, 9]
    # no prior occurrence -> no draft
    assert b._ngram_draft(slot([1, 2, 3, 4, 5]), 4) is None
    # generated tokens join the searchable history
    d = b._ngram_draft(slot([1, 2, 9, 9], out=[3, 1, 2]), 2)
    assert d.tolist() == [9, 9]
    # draft capped by remaining page capacity (pages 4*8=32, pos 30)
    s = slot(list(range(20)) + [1, 5, 6, 7, 5, 6])
    s.pos = 30
    d = b._ngram_draft(s, 8)
    assert d is not None and len(d) == 1  # 32 - 30 - 1


def test_outputs_identical_spec_on_off(byte_tok):
    """Real-lookup run: outputs identical with the path enabled.
    (Random-weight models generate non-echoing bytes, so real lookups
    may rarely fire here — engagement exactness is pinned by the
    stubbed-draft test below, real echo behavior by the chip A/B.)"""
    kw = dict(max_new_tokens=16, temperature=0.0)
    b_on, on = _run(
        _ecfg(spec_ngram_draft=6), byte_tok, _reqs(byte_tok, **kw)
    )
    b_off, off = _run(_ecfg(), byte_tok, _reqs(byte_tok, **kw))
    assert set(on) == set(off)
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i
        assert on[i].finish_reason == off[i].finish_reason
    assert b_off.spec_drafted == 0


def _stub_drafts(monkeypatch):
    """Deterministic pseudo-random draft source: exactness of the
    verify-accept machinery must hold for ANY draft content (bad drafts
    cost speed, never correctness — each still yields the exact greedy
    bonus token at its first mismatch)."""
    from sutro_tpu.engine.scheduler import ContinuousBatcher

    real = ContinuousBatcher._ngram_draft

    def stub(self, s, K):
        cap = len(s.pages) * self.ecfg.kv_page_size - s.pos - 1
        K = min(K, cap)
        if K < 1:
            return None
        rng = np.random.default_rng(s.req.row_id * 1000 + s.pos)
        # half the time draft random garbage, half the time echo the
        # row's own recent tokens (more likely to match greedy loops)
        if rng.integers(2):
            hist = list(s.req.prompt_ids) + list(s.out_ids)
            d = np.asarray(hist[-K:], np.int32)
        else:
            d = rng.integers(
                1, self.runner.mcfg.vocab_size - 1, K
            ).astype(np.int32)
        return d

    monkeypatch.setattr(ContinuousBatcher, "_ngram_draft", stub)
    return real


def test_stubbed_drafts_exactness_and_counters(byte_tok, monkeypatch):
    """With a forced draft source the speculative path ENGAGES on every
    step — outputs must still be bit-identical to the plain path, and
    the acceptance counters must move."""
    _stub_drafts(monkeypatch)
    kw = dict(max_new_tokens=16, temperature=0.0)
    b_on, on = _run(
        _ecfg(spec_ngram_draft=6), byte_tok, _reqs(byte_tok, **kw)
    )
    assert b_on.spec_drafted > 0
    assert 0 <= b_on.spec_accepted <= b_on.spec_drafted
    monkeypatch.undo()
    _, off = _run(_ecfg(), byte_tok, _reqs(byte_tok, **kw))
    assert set(on) == set(off)
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i
        assert on[i].finish_reason == off[i].finish_reason


def test_mixed_draftless_rows_fall_through(byte_tok):
    """Rows with no repeating bigram produce no draft — the batch falls
    through to the normal paths and outputs stay identical."""
    texts = ["xyzw qprs tuvk", "mnop efgh ijkl"]  # no repeats
    kw = dict(max_new_tokens=10, temperature=0.0)
    b_on, on = _run(
        _ecfg(spec_ngram_draft=6), byte_tok, _reqs(byte_tok, texts, **kw)
    )
    _, off = _run(_ecfg(), byte_tok, _reqs(byte_tok, texts, **kw))
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i


def test_partial_draft_coverage_engages(byte_tok, monkeypatch):
    """One draftless row must NOT disable speculation for the batch:
    with >= half the active rows drafting, the verify dispatch runs and
    the draftless row rides along as a plain greedy step (draft_len 0).
    Outputs stay bit-identical to the plain path either way."""
    from sutro_tpu.engine.scheduler import ContinuousBatcher

    def stub(self, s, K):
        if s.req.row_id == 0:
            return None  # permanently draftless row
        cap = len(s.pages) * self.ecfg.kv_page_size - s.pos - 1
        K = min(K, cap)
        if K < 1:
            return None
        hist = list(s.req.prompt_ids) + list(s.out_ids)
        return np.asarray(hist[-K:], np.int32)

    monkeypatch.setattr(ContinuousBatcher, "_ngram_draft", stub)
    kw = dict(max_new_tokens=16, temperature=0.0)
    b_on, on = _run(
        _ecfg(spec_ngram_draft=6), byte_tok, _reqs(byte_tok, **kw)
    )
    assert b_on.spec_drafted > 0, "2/3 drafting rows must engage"
    monkeypatch.undo()
    _, off = _run(_ecfg(), byte_tok, _reqs(byte_tok, **kw))
    assert set(on) == set(off)
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i
        assert on[i].finish_reason == off[i].finish_reason


def test_failed_first_probe_does_not_lock_out(byte_tok, monkeypatch):
    """Regression: the pipelined-window queue refills to lookahead and
    drains one per iteration, so a standing `not pipe` gate would never
    re-open after one failed probe. The probe/backoff scheme must let a
    later probe drain the pipe and engage once drafts appear."""
    from sutro_tpu.engine.scheduler import ContinuousBatcher

    def stub(self, s, K):
        if self._step < 10:
            return None  # no drafts early: first probe (step 0) fails
        cap = len(s.pages) * self.ecfg.kv_page_size - s.pos - 1
        K = min(K, cap)
        if K < 1:
            return None
        hist = list(s.req.prompt_ids) + list(s.out_ids)
        return np.asarray(hist[-K:], np.int32)

    monkeypatch.setattr(ContinuousBatcher, "_ngram_draft", stub)
    kw = dict(max_new_tokens=64, temperature=0.0)
    ecfg = _ecfg(
        spec_ngram_draft=6, decode_multi_step=4, decode_lookahead=2
    )
    b_on, on = _run(ecfg, byte_tok, _reqs(byte_tok, **kw))
    assert b_on.spec_drafted > 0, (
        "speculation locked out after a failed first probe"
    )
    monkeypatch.undo()
    _, off = _run(
        _ecfg(decode_multi_step=4, decode_lookahead=2),
        byte_tok,
        _reqs(byte_tok, **kw),
    )
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i


def test_poor_acceptance_backs_off(byte_tok, monkeypatch):
    """Coverage engages the spec path, but ACCEPTANCE keeps it there:
    drafts that never match must trip the rolling-window exit (backoff
    set) instead of pinning the run on the host-synchronous verify
    dispatch, and outputs stay exact throughout."""
    from sutro_tpu.engine.scheduler import ContinuousBatcher

    def stub(self, s, K):
        cap = len(s.pages) * self.ecfg.kv_page_size - s.pos - 1
        K = min(K, cap)
        if K < 1:
            return None
        rng = np.random.default_rng(s.req.row_id * 7919 + s.pos)
        return rng.integers(
            1, self.runner.mcfg.vocab_size - 1, K
        ).astype(np.int32)

    monkeypatch.setattr(ContinuousBatcher, "_ngram_draft", stub)
    kw = dict(max_new_tokens=32, temperature=0.0)
    b_on, on = _run(
        _ecfg(spec_ngram_draft=6), byte_tok, _reqs(byte_tok, **kw)
    )
    assert b_on.spec_drafted > 0
    assert b_on._spec_backoff > 0, (
        "near-zero acceptance never triggered the exit"
    )
    monkeypatch.undo()
    _, off = _run(_ecfg(), byte_tok, _reqs(byte_tok, **kw))
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i


def test_engine_perf_records_acceptance_rate(tiny_ecfg, tmp_path, monkeypatch):
    """Job metrics carry the acceptance counters (the VERDICT's ask)."""
    import dataclasses

    _stub_drafts(monkeypatch)  # guarantee engagement on random weights
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine

    eng = LocalEngine(dataclasses.replace(tiny_ecfg, spec_ngram_draft=6))
    jid = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": TEXTS,
            "sampling_params": {
                "max_new_tokens": 16, "temperature": 0.0
            },
        }
    )
    import time

    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if eng.job_status(jid) in ("SUCCEEDED", "FAILED", "CANCELLED"):
            break
        time.sleep(0.05)
    assert eng.job_status(jid) == "SUCCEEDED"
    rec = eng.get_job(jid)
    spec = (rec.get("perf") or {}).get("spec_ngram")
    assert spec is not None, rec.get("perf")
    assert spec["drafted"] > 0
    assert 0.0 <= spec["acceptance_rate"] <= 1.0
