"""Engine-level multi-host DP: a real job row-sharded across two
LocalEngine processes (SURVEY §2.3 DP row, §5.8).

Three OS processes run the SAME 24-row greedy job: a dp=2 pair
(coordinator + worker, results merged over the TCP channel in
engine/dphost.py) and a single-host reference. The coordinator's
finalized outputs must equal the reference's exactly — proving the
strided shard + cross-process stream + order-preserving merge changes
nothing about results, only where rows execute."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


from tests.conftest import free_low_port as _free_port


def _spawn(tmp_path, name, extra_env):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    # engine processes don't need the virtual multi-device mesh
    env.pop("XLA_FLAGS", None)
    home = tmp_path / name
    home.mkdir()
    env["SUTRO_HOME"] = str(home)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, str(REPO / "tests" / "dp_child.py")],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )


def test_dp_job_across_two_engines_matches_single_host(tmp_path):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    procs = {
        "rank0": _spawn(
            tmp_path, "rank0",
            {"SUTRO_DP_WORLD": "2", "SUTRO_DP_RANK": "0",
             "SUTRO_DP_COORD": coord},
        ),
        "rank1": _spawn(
            tmp_path, "rank1",
            {"SUTRO_DP_WORLD": "2", "SUTRO_DP_RANK": "1",
             "SUTRO_DP_COORD": coord},
        ),
        "single": _spawn(tmp_path, "single", {}),
    }
    outs = {}
    try:
        for name, p in procs.items():
            out, _ = p.communicate(timeout=420)
            outs[name] = out
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
    all_logs = "\n".join(
        f"--- {n} (rc={p.returncode}) ---\n{outs.get(n, '<no output>')}"
        for n, p in procs.items()
    )
    for name, p in procs.items():
        assert p.returncode == 0, f"{name} failed\n{all_logs}"
    assert "DP_OK rank=0" in outs["rank0"], outs["rank0"]
    assert "DP_OK rank=1" in outs["rank1"], outs["rank1"]

    def results_of(out: str):
        for line in out.splitlines():
            if line.startswith("RESULTS "):
                return json.loads(line[len("RESULTS "):])
        raise AssertionError(f"no RESULTS line:\n{out}")

    dp_outputs = results_of(outs["rank0"])
    ref_outputs = results_of(outs["single"])
    assert len(dp_outputs) == 24
    # identical content AND order: the dp path changes where rows run,
    # never what they produce
    assert dp_outputs == ref_outputs

    def emb_of(out: str):
        for line in out.splitlines():
            if line.startswith("EMB "):
                return json.loads(line[len("EMB "):])
        raise AssertionError(f"no EMB line:\n{out}")

    import numpy as np

    dp_emb = np.array(emb_of(outs["rank0"]))
    ref_emb = np.array(emb_of(outs["single"]))
    assert dp_emb.shape == ref_emb.shape == (24, 4)
    # per-row embeddings are batch-composition independent (masked
    # attention; pooled head) — DP grouping must not change values
    np.testing.assert_allclose(dp_emb, ref_emb, rtol=1e-4, atol=1e-5)

    # -- distributed telemetry acceptance: one merged cross-process
    # timeline + a named doctor verdict on a real 2-process dp run ----
    def line_of(out: str, tag: str):
        for line in out.splitlines():
            if line.startswith(tag + " "):
                return json.loads(line[len(tag) + 1:])
        raise AssertionError(f"no {tag} line:\n{out}")

    teledoc = line_of(outs["rank0"], "TELEDOC")
    workers = teledoc["workers"]
    assert [w["rank"] for w in workers] == [1], workers
    w1 = workers[0]
    # rank 1 ran half the rows and shipped its timeline + counters
    # under the coordinator's trace (round 1 of this job)
    assert w1["round"] == 1 and w1["trace"].endswith("/r1")
    assert w1["counters"].get("rows_ok") == 12
    assert {"tokenize", "prefill", "decode_window", "dp_round"} <= set(
        w1["stages"]
    ), w1["stages"]
    # the merged document's stage set spans both processes
    assert "dp_round" in teledoc["stages"]

    doctor = line_of(outs["rank0"], "DOCTOR")
    assert doctor["verdict"] in (
        "insufficient_data", "straggler_worker", "io_bound",
        "host_bound_admit", "decode_below_roofline", "healthy",
    )
    assert doctor["verdict"] != "insufficient_data"
    assert doctor["partial"] is False and doctor["world"] == 2
    # per-worker stage attribution crossed the wire
    assert set(doctor["processes"]) == {"rank0", "rank1"}
    for p in doctor["processes"].values():
        assert p["spans"] > 0 and p["wall_s"] > 0
    assert doctor["processes"]["rank1"]["stages"]["decode_window"][
        "count"
    ] > 0
    assert doctor["evidence"], doctor


# ---------------------------------------------------------------------------
# channel-level tests (stub shards, no engines — fast)
# ---------------------------------------------------------------------------


def _world(port):
    from sutro_tpu.engine.dphost import DPWorld

    return (
        DPWorld(rank=0, world=2, host="127.0.0.1", port=port),
        DPWorld(rank=1, world=2, host="127.0.0.1", port=port),
    )


def _reqs(n):
    import numpy as np

    from sutro_tpu.engine.scheduler import GenRequest

    return [
        GenRequest(
            row_id=i, prompt_ids=np.zeros(1, np.int32), max_new_tokens=1
        )
        for i in range(n)
    ]


def _res(row_id):
    from sutro_tpu.engine.scheduler import GenResult

    return GenResult(
        row_id=row_id, token_ids=[7], cumulative_logprob=-0.5,
        finish_reason="stop", input_tokens=1,
    )


def test_channel_resume_filter_and_merge():
    """The coordinator ships its done-row set on hello; the worker
    filters its shard so already-merged rows are not regenerated."""
    import threading

    from sutro_tpu.engine.dphost import (
        run_dp_coordinator,
        run_dp_worker,
        shard_requests,
    )

    port = _free_port()
    cw, ww = _world(port)
    reqs = _reqs(8)
    worker_ran = []

    def coord_shard(shard, on_result, on_progress, should_cancel):
        for q in shard:
            on_result(_res(q.row_id))
        return "completed"

    def worker_shard(shard, on_result, on_progress, should_cancel):
        worker_ran.extend(q.row_id for q in shard)
        for q in shard:
            on_result(_res(q.row_id))
        return "completed"

    def worker_main():
        run_dp_worker(
            ww, worker_shard, shard_requests(reqs, 1, 2)
        )

    t = threading.Thread(target=worker_main)
    t.start()
    merged = {}
    outcome = run_dp_coordinator(
        cw, coord_shard, shard_requests(reqs, 0, 2),
        on_result=lambda r: merged.__setitem__(r.row_id, r),
        done_rows={1, 3},  # worker rows already in the partial store
    )
    t.join(timeout=120)
    assert outcome == "completed"
    assert worker_ran == [5, 7]  # 1 and 3 filtered by the resume set
    # coordinator merged its own shard + the worker's fresh rows
    assert set(merged) == {0, 2, 4, 6, 5, 7}
    assert merged[5].finish_reason == "stop"


def test_channel_worker_failure_fails_job():
    """A worker error (or non-completed outcome) must surface on the
    coordinator instead of finalizing with silently-missing rows."""
    import threading

    import pytest

    from sutro_tpu.engine.dphost import (
        run_dp_coordinator,
        run_dp_worker,
        shard_requests,
    )

    port = _free_port()
    cw, ww = _world(port)
    reqs = _reqs(4)

    def coord_shard(shard, on_result, on_progress, should_cancel):
        for q in shard:
            on_result(_res(q.row_id))
        return "completed"

    def worker_shard(shard, on_result, on_progress, should_cancel):
        raise RuntimeError("slice OOM")

    def worker_main():
        try:
            run_dp_worker(ww, worker_shard, shard_requests(reqs, 1, 2))
        except RuntimeError:
            pass  # the worker re-raises locally too

    t = threading.Thread(target=worker_main)
    t.start()
    with pytest.raises(RuntimeError, match="slice OOM"):
        run_dp_coordinator(
            cw, coord_shard, shard_requests(reqs, 0, 2),
            on_result=lambda r: None,
        )
    t.join(timeout=120)


def test_channel_worker_retry_replaces_connection():
    """A worker that reconnects with the same rank (retry after a
    handshake stall) must REPLACE its abandoned first connection, not
    consume a second worker slot — and the abandoned connection's EOF
    must not fail the otherwise-successful job."""
    import threading

    from sutro_tpu.engine.dphost import (
        _recv_lines,
        _send,
        run_dp_coordinator,
        run_dp_worker,
        shard_requests,
    )

    port = _free_port()
    cw, ww = _world(port)
    reqs = _reqs(4)
    merged = {}
    worker_outcome = {}
    stale_ready = threading.Event()

    def coord_main():
        worker_outcome["coord"] = run_dp_coordinator(
            cw,
            lambda shard, on_result, on_progress, should_cancel: (
                [on_result(_res(q.row_id)) for q in shard],
                "completed",
            )[1],
            shard_requests(reqs, 0, 2),
            on_result=lambda r: merged.__setitem__(r.row_id, r),
        )

    ct = threading.Thread(target=coord_main)
    ct.start()

    # abandoned first connection: hello + resume handshake completes,
    # then the socket goes quiet (still OPEN — the retry must supersede
    # it, after which the coordinator closes it)
    import time

    deadline = time.monotonic() + 120
    stale = None
    while stale is None:
        try:
            stale = socket.create_connection(
                ("127.0.0.1", port), timeout=15.0
            )
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)
    _send(stale, {"t": "hello", "rank": 1, "job": ""})
    first = next(_recv_lines(stale), None)
    assert first and first.get("t") == "resume"
    stale_ready.set()

    def worker_shard(shard, on_result, on_progress, should_cancel):
        for q in shard:
            on_result(_res(q.row_id))
        return "completed"

    worker_outcome["v"] = run_dp_worker(
        ww, worker_shard, shard_requests(reqs, 1, 2)
    )
    ct.join(timeout=120)
    assert not ct.is_alive()
    stale.close()
    assert worker_outcome["v"] == "completed"
    assert worker_outcome["coord"] == "completed"
    assert set(merged) == {0, 1, 2, 3}


def test_channel_stalled_worker_fails_resumably(monkeypatch):
    """A worker whose connection stays OPEN but never sends done must
    not wedge the coordinator forever: after SUTRO_DP_STALL_TIMEOUT of
    silence (post local-shard), the job fails with a stall error."""
    import threading
    import time

    import pytest

    from sutro_tpu.engine.dphost import (
        _recv_lines,
        _send,
        run_dp_coordinator,
        shard_requests,
    )

    monkeypatch.setenv("SUTRO_DP_STALL_TIMEOUT", "1")
    port = _free_port()
    cw, _ = _world(port)
    reqs = _reqs(4)

    def hung_worker():
        deadline = time.monotonic() + 120
        sock = None
        while sock is None:
            try:
                sock = socket.create_connection(
                    ("127.0.0.1", port), timeout=15.0
                )
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        _send(sock, {"t": "hello", "rank": 1, "job": ""})
        next(_recv_lines(sock), None)  # resume reply
        time.sleep(30)  # never send done (hung slice)
        sock.close()

    t = threading.Thread(target=hung_worker, daemon=True)
    t.start()
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="stalled"):
        run_dp_coordinator(
            cw,
            lambda shard, on_result, on_progress, should_cancel: "completed",
            shard_requests(reqs, 0, 2),
            on_result=lambda r: None,
        )
    # detected via the stall timeout (seconds), not the 420s accept path
    assert time.monotonic() - t0 < 30


def test_serve_resume_round_completes_requeued_workers(monkeypatch):
    """Resume of a fully-merged DP job: the coordinator serves a trivial
    round so re-queued workers finish as completed no-ops (their shard
    filters to empty) instead of timing out against an unbound port."""
    import threading

    from sutro_tpu.engine.dphost import (
        run_dp_worker,
        serve_resume_round,
        shard_requests,
    )

    monkeypatch.setenv("SUTRO_DP_RESUME_GRACE", "10")
    port = _free_port()
    cw, ww = _world(port)
    reqs = _reqs(4)
    worker_ran = []

    def worker_shard(shard, on_result, on_progress, should_cancel):
        worker_ran.extend(q.row_id for q in shard)
        for q in shard:
            on_result(_res(q.row_id))
        return "completed"

    outcome = {}

    def worker_main():
        outcome["v"] = run_dp_worker(
            ww, worker_shard, shard_requests(reqs, 1, 2)
        )

    t = threading.Thread(target=worker_main)
    t.start()
    serve_resume_round(cw, job_key="", done_rows={0, 1, 2, 3})
    t.join(timeout=120)
    assert not t.is_alive()
    assert outcome["v"] == "completed"
    assert worker_ran == []  # every row was already merged


def test_channel_cancel_propagates_to_worker():
    """Coordinator-side cancellation reaches a still-running worker
    shard through the channel, and both sides settle on 'cancelled'."""
    import threading
    import time

    from sutro_tpu.engine.dphost import (
        run_dp_coordinator,
        run_dp_worker,
        shard_requests,
    )

    port = _free_port()
    cw, ww = _world(port)
    reqs = _reqs(4)
    cancel_at = {"t": None}
    worker_outcome = {}

    def coord_shard(shard, on_result, on_progress, should_cancel):
        for q in shard:
            on_result(_res(q.row_id))
        cancel_at["t"] = time.monotonic()
        return "completed"  # local shard done; cancel fires while waiting

    def worker_shard(shard, on_result, on_progress, should_cancel):
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if should_cancel():
                return "cancelled"
            time.sleep(0.05)
        return "completed"  # would time the test out

    def worker_main():
        worker_outcome["v"] = run_dp_worker(
            ww, worker_shard, shard_requests(reqs, 1, 2)
        )

    t = threading.Thread(target=worker_main)
    t.start()

    def should_cancel():
        # cancel as soon as the local shard has finished
        return cancel_at["t"] is not None

    outcome = run_dp_coordinator(
        cw, coord_shard, shard_requests(reqs, 0, 2),
        on_result=lambda r: None,
        should_cancel=should_cancel,
    )
    t.join(timeout=180)
    assert not t.is_alive()
    assert outcome == "cancelled"
    assert worker_outcome["v"] == "cancelled"
