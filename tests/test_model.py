"""Model correctness tests across all four architecture families, plus the
critical prefill/decode consistency invariant: a token decoded step-by-step
through the paged KV cache must see the same logits as a full forward pass
over the whole sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models import transformer
from sutro_tpu.models.configs import MODEL_CONFIGS


@pytest.mark.parametrize(
    "name", ["tiny-dense", "tiny-moe", "tiny-oss", "tiny-emb"]
)
def test_forward_shapes(name):
    cfg = MODEL_CONFIGS[name]
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    B, T = 2, 12
    ids = jnp.zeros((B, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    vlen = jnp.array([T, 5], jnp.int32)
    out, hidden, (k, v) = transformer.forward(cfg, params, ids, pos, vlen)
    if cfg.head == "embedding":
        assert out.shape == (B, cfg.hidden_size)
        # embeddings are L2-normalized
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1), 1.0, rtol=1e-4
        )
    else:
        assert out.shape == (B, T, cfg.vocab_size)
    assert k.shape == (cfg.num_layers, B, T, cfg.num_kv_heads, cfg.head_dim)


def test_padding_invariance():
    """Logits at valid positions must not depend on padding content."""
    cfg = MODEL_CONFIGS["tiny-dense"]
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    T, n = 16, 7
    rng = np.random.default_rng(0)
    real = rng.integers(0, 256, size=n)
    ids1 = np.zeros((1, T), np.int32)
    ids2 = np.full((1, T), 123, np.int32)
    ids1[0, :n] = real
    ids2[0, :n] = real
    pos = jnp.arange(T, dtype=jnp.int32)[None]
    vlen = jnp.array([n], jnp.int32)
    l1, _, _ = transformer.forward(cfg, params, jnp.asarray(ids1), pos, vlen)
    l2, _, _ = transformer.forward(cfg, params, jnp.asarray(ids2), pos, vlen)
    np.testing.assert_allclose(
        np.asarray(l1[0, :n]), np.asarray(l2[0, :n]), atol=1e-4
    )


@pytest.mark.parametrize("name", ["tiny-dense", "tiny-oss"])
def test_prefill_decode_consistency(name):
    """Greedy decode through the paged cache == greedy continuation of full
    forward passes (the invariant that makes continuous batching safe)."""
    cfg = MODEL_CONFIGS[name]
    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=8, decode_batch_size=2,
        max_model_len=64, use_pallas=False, param_dtype="float32",
    )
    runner = ModelRunner(cfg, ecfg)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 256, size=13).astype(np.int32)

    # Reference: iterative full forwards, argmax continuation.
    params = runner.params
    seq = list(prompt)
    ref_tokens = []
    for _ in range(6):
        T = len(seq)
        ids = jnp.asarray(np.array(seq, np.int32)[None])
        pos = jnp.arange(T, dtype=jnp.int32)[None]
        logits, _, _ = transformer.forward(
            cfg, params, ids, pos, jnp.array([T], jnp.int32)
        )
        tok = int(jnp.argmax(logits[0, -1]))
        ref_tokens.append(tok)
        seq.append(tok)

    # Engine path: prefill into pages, then paged decode steps.
    table = np.zeros((ecfg.max_pages_per_seq,), np.int32)
    table[:4] = [1, 2, 3, 4]
    logits = runner.prefill(prompt, table)
    tok = int(np.argmax(logits))
    got = [tok]
    pos_len = len(prompt)
    for _ in range(5):
        toks, _ = runner.decode_step(
            np.array([tok, 0], np.int32),
            np.array([pos_len, 0], np.int32),
            np.stack([table, np.zeros_like(table)]),
            jax.random.PRNGKey(0),
            np.zeros(2, np.float32),  # temperature 0 => greedy
            np.ones(2, np.float32),
        )
        tok = int(toks[0])
        got.append(tok)
        pos_len += 1
    assert got == ref_tokens


def test_moe_dense_vs_ragged():
    from sutro_tpu.ops.moe import moe_mlp

    key = jax.random.PRNGKey(0)
    B, T, H, E, F, K = 2, 6, 16, 4, 32, 2
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (B, T, H))
    router = jax.random.normal(ks[1], (H, E)) * 0.1
    wg = jax.random.normal(ks[2], (E, H, F)) * 0.1
    wu = jax.random.normal(ks[3], (E, H, F)) * 0.1
    wd = jax.random.normal(ks[4], (E, F, H)) * 0.1
    dense = moe_mlp(x, router, wg, wu, wd, top_k=K, method="dense")
    ragged = moe_mlp(x, router, wg, wu, wd, top_k=K, method="ragged")
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(ragged), atol=2e-5
    )


def test_rope_rotation_property():
    """RoPE must make attention scores depend only on relative positions."""
    from sutro_tpu.models.transformer import apply_rope

    D = 8
    q = jnp.ones((1, 1, 1, D))
    k = jnp.ones((1, 1, 1, D)) * 0.5
    theta = jnp.float32(10000.0)

    def score(qp, kp):
        qr = apply_rope(q, jnp.array([[qp]], jnp.int32), theta)
        kr = apply_rope(k, jnp.array([[kp]], jnp.int32), theta)
        return float(jnp.sum(qr * kr))

    assert abs(score(5, 3) - score(10, 8)) < 1e-4
    assert abs(score(5, 3) - score(6, 3)) > 1e-6


def test_chunked_prefill_matches_unchunked():
    """Long prompts processed in fixed-size chunks (prefill_chunk) must
    produce the same logits and cache contents as one-shot prefill,
    including sliding-window + sink layers (tiny-oss)."""
    import jax
    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.engine.runner import ModelRunner

    for model in ("tiny-dense", "tiny-oss"):
        cfg = MODEL_CONFIGS[model]
        prompt = ((np.arange(50, dtype=np.int32) * 11) % 199).astype(np.int32)

        def run(chunk):
            ecfg = EngineConfig(
                kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
                max_model_len=128, use_pallas=False, param_dtype="float32",
                prefill_chunk=chunk,
            )
            r = ModelRunner(cfg, ecfg)
            table = np.zeros((16,), np.int32)
            table[:8] = np.arange(1, 9)
            logits = r.prefill(prompt, table)
            tok = int(np.argmax(logits))
            toks, _ = r.decode_step(
                np.array([tok, 0, 0, 0], np.int32),
                np.array([len(prompt), 0, 0, 0], np.int32),
                np.stack([table] + [np.zeros_like(table)] * 3),
                jax.random.PRNGKey(0),
                np.zeros(4, np.float32), np.ones(4, np.float32),
            )
            return logits, tok, int(toks[0])

        full = run(512)      # 50 < 512: single-shot path
        chunked = run(16)    # 4 chunks through the paged-past path
        np.testing.assert_allclose(full[0], chunked[0], atol=2e-4)
        assert full[1:] == chunked[1:]
