"""Tokenizer tests: byte-level round trips, specials, token_bytes contract
(the constrained-decoding FSM depends on token_bytes — engine/constrain/)."""

from sutro_tpu.engine.tokenizer import (
    ByteTokenizer,
    _GPT2_BYTE_DECODER,
)


def test_byte_roundtrip():
    tok = ByteTokenizer()
    text = "hello, TPU — ünïcødé!"
    assert tok.decode(tok.encode(text)) == text


def test_specials_atomic():
    tok = ByteTokenizer()
    ids = tok.encode("<|im_start|>user\nhi<|im_end|>")
    assert ids[0] == tok._special_to_id["<|im_start|>"]
    assert ids[-1] == tok.im_end_id
    # specials carry no bytes
    assert tok.token_bytes(tok.im_end_id) == b""
    assert tok.token_bytes(ord("a")) == b"a"


def test_render_chat_templates():
    tok = ByteTokenizer()
    chatml = tok.render_chat("hi", system="sys", template="chatml")
    assert chatml.startswith("<|im_start|>system\nsys<|im_end|>")
    assert chatml.endswith("<|im_start|>assistant\n")
    plain = tok.render_chat("hi", system="sys", template="plain")
    assert plain == "sys\n\nhi"
    gemma = tok.render_chat("hi", template="gemma")
    assert "<start_of_turn>model" in gemma
    llama = tok.render_chat("hi", template="llama3")
    assert llama.startswith("<|begin_of_text|>")


def test_gpt2_byte_decoder_complete():
    # bijective over all 256 byte values
    assert len(_GPT2_BYTE_DECODER) == 256
    assert sorted(_GPT2_BYTE_DECODER.values()) == list(range(256))
    # the canonical examples: 'Ġ' is space, '!' is itself
    assert _GPT2_BYTE_DECODER["Ġ"] == 0x20
    assert _GPT2_BYTE_DECODER["!"] == ord("!")


def test_stop_ids():
    tok = ByteTokenizer()
    assert tok.eos_id in tok.stop_ids()
    assert tok.im_end_id in tok.stop_ids()


def test_encode_batch_matches_per_row():
    tok = ByteTokenizer()
    texts = ["hello", "", "<|im_start|>user\nhey<|im_end|>", "é¿"]
    assert tok.encode_batch(texts) == [tok.encode(t) for t in texts]


def test_concat_safe_boundaries():
    tok = ByteTokenizer()
    # plain text tails cannot start a special
    assert tok.concat_safe("<|im_start|>user\n")
    assert tok.concat_safe("classify this:")
    # a tail that is a proper prefix of a special could merge across
    # the boundary — must be declared unsafe
    assert not tok.concat_safe("text<")
    assert not tok.concat_safe("x<|im_end")
    assert not tok.concat_safe("<|begin_of_")


def test_encode_chat_batch_bit_identical_all_templates():
    """The prefix-aware batched encode must produce EXACTLY the ids of
    per-row render_chat + encode — including rows that poke at the
    shell boundary (leading '<', empty row, specials inside)."""
    from sutro_tpu.engine.tokenizer import encode_chat_batch

    tok = ByteTokenizer()
    rows = [
        "plain row",
        "",
        "<|im_end|> sneaky",
        "<partial special tail<|im_en",
        "unicode ✓ row",
    ]
    for system in (None, "You are a terse classifier."):
        for template in ("chatml", "plain", "gemma", "llama3"):
            want = [
                tok.encode(
                    tok.render_chat(r, system=system, template=template)
                )
                for r in rows
            ]
            got = encode_chat_batch(tok, rows, system, template)
            assert got == want, (template, system)


def test_encode_chat_batch_threads_match_serial():
    from sutro_tpu.engine.tokenizer import encode_chat_batch

    tok = ByteTokenizer()
    rows = [f"row {i}" for i in range(64)]
    a = encode_chat_batch(tok, rows, "sys", "chatml")
    b = encode_chat_batch(tok, rows, "sys", "chatml", threads=4)
    assert a == b
