"""Pipeline parallelism (parallel/pipeline.py) on the 8-way virtual CPU
mesh: GPipe microbatch schedule parity with the plain scanned forward,
PP x TP composition, and runner-level prefill+decode parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models import transformer
from sutro_tpu.models.configs import MODEL_CONFIGS
from sutro_tpu.ops.shard_compat import HAS_NEW_SHARD_MAP
from sutro_tpu.parallel.mesh import make_mesh
from sutro_tpu.parallel.pipeline import (
    pipeline_forward,
    pp_param_shardings,
)


@pytest.mark.slow  # 16-28s/combo of multi-device XLA compiles: full
#                    parity stays pinned by the chunked full-suite run
@pytest.mark.parametrize("model", ["tiny-dense", "tiny-oss"])
@pytest.mark.parametrize("pp,tp,m", [(2, 1, 2), (2, 1, 4), (2, 2, 2)])
def test_pipeline_forward_parity(eight_devices, model, pp, tp, m):
    if tp > 1 and not HAS_NEW_SHARD_MAP:
        pytest.skip(
            "pp x tp needs partial-auto shard_map (jax.shard_map); "
            "this jax only emulates full-manual meshes"
        )
    cfg = MODEL_CONFIGS[model]
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    B, T = 4, 16
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    vl = jnp.asarray([16, 9, 16, 3], jnp.int32)
    ref, _, (k_ref, v_ref) = transformer.forward(cfg, params, ids, pos, vl)

    mesh = make_mesh(1, 1, tp, eight_devices[: pp * tp], pp=pp)
    sharded = jax.device_put(params, pp_param_shardings(params, mesh))
    out, _, (k, v) = pipeline_forward(
        cfg, sharded, ids, pos, vl, mesh, n_microbatches=m
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
    np.testing.assert_allclose(np.asarray(k), np.asarray(k_ref), atol=2e-4)
    np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), atol=2e-4)


def test_pipeline_validates_divisibility(eight_devices):
    cfg = MODEL_CONFIGS["tiny-dense"]
    params = transformer.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    mesh = make_mesh(1, 1, 1, eight_devices[:2], pp=2)
    ids = jnp.zeros((3, 16), jnp.int32)
    pos = jnp.zeros((3, 16), jnp.int32)
    vl = jnp.ones((3,), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward(cfg, params, ids, pos, vl, mesh, n_microbatches=2)


def test_pp_runner_generation_matches_single_device(
    eight_devices, mesh_ecfg
):
    """Greedy prefill+decode through the engine runner must be identical
    with the layer stack pipeline-sharded (pp=2) and pp x tp (2x2)."""
    if not HAS_NEW_SHARD_MAP:
        pytest.skip(
            "pp through the jitted runner needs partial-auto shard_map "
            "support (XLA:CPU rejects PartitionId on legacy jax)"
        )
    cfg = MODEL_CONFIGS["tiny-dense"]
    prompt = (np.arange(17, dtype=np.int32) * 5) % 199

    def run(mesh):
        runner = ModelRunner(cfg, mesh_ecfg, mesh=mesh)
        table = np.zeros((8,), np.int32)
        table[:4] = [1, 2, 3, 4]
        logits = runner.prefill(prompt, table)
        tok = int(np.argmax(logits))
        out = [tok]
        pos = len(prompt)
        for _ in range(3):
            toks, _ = runner.decode_step(
                np.array([tok, 0, 0, 0], np.int32),
                np.array([pos, 0, 0, 0], np.int32),
                np.stack([table] + [np.zeros((8,), np.int32)] * 3),
                jax.random.PRNGKey(0),
                np.zeros(4, np.float32),
                np.ones(4, np.float32),
            )
            tok = int(toks[0])
            out.append(tok)
            pos += 1
        return out

    single = run(None)
    assert run(make_mesh(1, 1, 1, eight_devices[:2], pp=2)) == single
    assert run(make_mesh(1, 1, 2, eight_devices[:4], pp=2)) == single


def test_pp_decode_stage_local_memory(eight_devices, mesh_ecfg):
    """Under pp=2 each device holds exactly 1/2 of every layer-stacked
    param leaf and 1/2 of the KV page pool — PP actually reduces decode
    residency (decode runs pipeline_decode, not a GSPMD all-gather)."""
    cfg = MODEL_CONFIGS["tiny-dense"]
    mesh = make_mesh(1, 1, 1, eight_devices[:2], pp=2)
    runner = ModelRunner(cfg, mesh_ecfg, mesh=mesh)
    wq = runner.params["layers"]["wq"]
    assert wq.sharding.spec[0] == "pipe"
    assert wq.addressable_shards[0].data.nbytes == wq.nbytes // 2
    kp = runner.cache.k_pages
    assert kp.addressable_shards[0].data.nbytes == kp.nbytes // 2
