"""Child process for tests/test_dphost.py.

One LocalEngine process per "pod slice". With SUTRO_DP_WORLD=2 the
engine row-shards the job across ranks (engine/dphost.py): rank 0
coordinates (owns the authoritative jobstore, merges streams), rank 1
streams its shard's results over the TCP channel. With SUTRO_DP_WORLD
unset the same job runs single-host — the parent compares the two
coordinators' outputs, which must match exactly (greedy decode is
per-row deterministic, and the merge is order-preserving).

Run via the parent test only — needs SUTRO_HOME (per-process store)
and, for DP ranks, SUTRO_DP_WORLD/SUTRO_DP_RANK/SUTRO_DP_COORD.
"""

import json
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

from sutro_tpu.engine.api import LocalEngine  # noqa: E402
from sutro_tpu.engine.config import EngineConfig  # noqa: E402

N_ROWS = 24


def main() -> None:
    rank = int(os.environ.get("SUTRO_DP_RANK", "0"))
    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32",
    )
    eng = LocalEngine(ecfg)
    jid = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": [f"dp row {i} text" for i in range(N_ROWS)],
            "sampling_params": {"max_new_tokens": 6, "temperature": 0.0},
        }
    )
    def await_done(job_id: str) -> None:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            s = eng.job_status(job_id)
            if s in ("SUCCEEDED", "FAILED", "CANCELLED"):
                break
            time.sleep(0.05)
        assert eng.job_status(job_id) == "SUCCEEDED", eng.job_status(
            job_id
        )

    await_done(jid)
    if rank == 0:
        res = eng.job_results(jid)
        assert len(res["outputs"]) == N_ROWS
        assert all(o is not None for o in res["outputs"])
        print("RESULTS " + json.dumps(res["outputs"]), flush=True)
        if os.environ.get("SUTRO_DP_WORLD"):
            # distributed telemetry: the coordinator's merged document
            # and the doctor's diagnosis of it (parent asserts shape)
            doc = eng.job_telemetry(jid, write=False)
            print(
                "TELEDOC "
                + json.dumps(
                    {
                        "workers": [
                            {
                                "rank": w.get("rank"),
                                "round": w.get("round"),
                                "trace": w.get("trace"),
                                "stages": sorted(
                                    {
                                        s["name"]
                                        for s in w.get("spans", [])
                                    }
                                ),
                                "counters": w.get("counters"),
                            }
                            for w in doc.get("workers", [])
                        ],
                        "stages": doc.get("stages"),
                    }
                ),
                flush=True,
            )
            print(
                "DOCTOR " + json.dumps(eng.diagnose_job(jid)),
                flush=True,
            )

    # embedding job through the same DP path (EmbResult channel)
    ejid = eng.submit_batch_inference(
        {
            "model": "tiny-emb",
            "inputs": [f"embed row {i}" for i in range(N_ROWS)],
        }
    )
    await_done(ejid)
    if rank == 0:
        res = eng.job_results(ejid)
        assert len(res["outputs"]) == N_ROWS
        dims = {len(v) for v in res["outputs"]}
        assert len(dims) == 1, dims
        print(
            "EMB "
            + json.dumps(
                [[float(x) for x in v[:4]] for v in res["outputs"]]
            ),
            flush=True,
        )
    print(f"DP_OK rank={rank}", flush=True)


if __name__ == "__main__":
    main()
