"""Int8 KV cache with per-token scales (EngineConfig.kv_quantize,
VERDICT r3 next-step 4): write_kv quantizes at the single write choke
point, the gather fallback and the Pallas paged kernel dequantize, and
the engine runs end-to-end with the quantized pool. Halves decode HBM
traffic and doubles page capacity; parity is numeric (int8 error), the
kernel-vs-fallback comparison is tight (identical quantized values)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.kvcache import (
    alloc_cache,
    gather_kv_layer,
    write_kv,
)
from sutro_tpu.models.configs import MODEL_CONFIGS
from sutro_tpu.ops.attention import chunk_attention
from sutro_tpu.ops.pallas_paged import paged_decode_attention


def _ecfg(**kw):
    base = dict(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", kv_quantize="int8",
    )
    base.update(kw)
    return EngineConfig(**base)


def test_write_then_gather_roundtrip_error_bound():
    """Quantize-dequantize error is bounded by half a step of each
    token's scale (amax/127)."""
    mcfg = MODEL_CONFIGS["tiny-dense"]
    ecfg = _ecfg()
    cache = alloc_cache(mcfg, ecfg, num_pages=9)
    L = mcfg.num_layers
    KVH, Dh = mcfg.num_kv_heads, mcfg.head_dim
    B, T = 2, 11
    rng = np.random.default_rng(0)
    k = jnp.asarray(rng.standard_normal((L, B, T, KVH, Dh)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((L, B, T, KVH, Dh)), jnp.float32)
    table = np.zeros((B, ecfg.max_pages_per_seq), np.int32)
    table[0, :2] = [1, 2]
    table[1, :2] = [3, 4]
    cache = write_kv(
        cache, k, v, jnp.asarray(table),
        jnp.zeros((B,), jnp.int32), jnp.full((B,), T, jnp.int32),
    )
    gk, gv = gather_kv_layer(
        cache.k_pages[0], cache.v_pages[0], jnp.asarray(table), KVH,
        k_scale_l=cache.k_scale[0], v_scale_l=cache.v_scale[0],
    )
    got = np.asarray(gk)[:, :T].reshape(B, T, KVH, Dh)
    want = np.asarray(k[0])
    tol = np.abs(want).reshape(B, T, -1).max(-1) / 127.0 * 0.5 + 1e-6
    assert (np.abs(got - want).reshape(B, T, -1).max(-1) <= tol).all()
    gotv = np.asarray(gv)[:, :T].reshape(B, T, KVH, Dh)
    wantv = np.asarray(v[0])
    tolv = np.abs(wantv).reshape(B, T, -1).max(-1) / 127.0 * 0.5 + 1e-6
    assert (np.abs(gotv - wantv).reshape(B, T, -1).max(-1) <= tolv).all()


def _quantized_case(rng, *, B=3, NH=4, KVH=2, Dh=16, PS=8, MP=6, NP=32):
    from sutro_tpu.engine.kvcache import _quantize_tokens

    q = jnp.asarray(rng.standard_normal((B, 1, NH, Dh)), jnp.float32)
    k_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    v_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    kf = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32)
    vf = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32)
    kq, ks = _quantize_tokens(kf)
    vq, vs = _quantize_tokens(vf)
    table = np.zeros((B, MP), np.int32)
    next_p = 1
    for b in range(B):
        table[b] = np.arange(next_p, next_p + MP)
        next_p += MP
    past_len = jnp.asarray(rng.integers(1, MP * PS, B), jnp.int32)
    return q, k_cur, v_cur, kq, ks, vq, vs, jnp.asarray(table), past_len


@pytest.mark.parametrize("window", [0, 5])
def test_paged_kernel_int8_matches_dequant_reference(window):
    """The Pallas kernel's in-kernel dequant (score/probability scaling
    per page slice) matches the XLA gather-dequant fallback over the
    SAME quantized values — tight tolerance, no quantization slack."""
    rng = np.random.default_rng(7)
    q, k_cur, v_cur, kq, ks, vq, vs, table, past_len = _quantized_case(rng)
    B = q.shape[0]
    win = jnp.asarray(window, jnp.int32)

    ref = chunk_attention(
        q, k_cur, v_cur,
        positions=past_len[:, None],
        valid_len=jnp.ones((B,), jnp.int32),
        past_k_pages=kq, past_v_pages=vq,
        past_k_scale=ks, past_v_scale=vs,
        page_table=table, past_len=past_len, window=win,
        use_pallas=False,
    )
    got = paged_decode_attention(
        q[:, 0], kq, vq, table, past_len, k_cur[:, 0], v_cur[:, 0],
        win, None, interpret=True, k_scale=ks, v_scale=vs,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref[:, 0]), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("kv_chunk", [2, 3])
def test_paged_kernel_int8_chunked(kv_chunk):
    """Chunked contiguous fetch with scale DMAs: per-page scale slices
    still land on the right score columns."""
    rng = np.random.default_rng(11)
    MP = 6
    q, k_cur, v_cur, kq, ks, vq, vs, table, past_len = _quantized_case(
        rng, MP=MP, NP=40
    )
    B = q.shape[0]
    win = jnp.asarray(0, jnp.int32)
    ref = chunk_attention(
        q, k_cur, v_cur,
        positions=past_len[:, None],
        valid_len=jnp.ones((B,), jnp.int32),
        past_k_pages=kq, past_v_pages=vq,
        past_k_scale=ks, past_v_scale=vs,
        page_table=table, past_len=past_len, window=win,
        use_pallas=False,
    )
    got = paged_decode_attention(
        q[:, 0], kq, vq, table, past_len, k_cur[:, 0], v_cur[:, 0],
        win, None, interpret=True, kv_chunk=kv_chunk,
        k_scale=ks, v_scale=vs,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref[:, 0]), atol=2e-5, rtol=2e-5
    )


def test_decode_attention_close_to_unquantized():
    """End-to-end numeric sanity: attention over an int8 cache is close
    to attention over the exact cache (int8 error only)."""
    from sutro_tpu.engine.kvcache import _quantize_tokens

    rng = np.random.default_rng(3)
    B, NH, KVH, Dh, PS, MP, NP = 2, 4, 2, 16, 8, 4, 16
    q = jnp.asarray(rng.standard_normal((B, 1, NH, Dh)), jnp.float32)
    k_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    v_cur = jnp.asarray(rng.standard_normal((B, 1, KVH, Dh)), jnp.float32)
    kf = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32)
    vf = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.float32)
    kq, ks = _quantize_tokens(kf)
    vq, vs = _quantize_tokens(vf)
    table = jnp.asarray(
        np.arange(1, 1 + B * MP, dtype=np.int32).reshape(B, MP)
    )
    past_len = jnp.asarray([MP * PS - 3, 7], jnp.int32)
    kw = dict(
        positions=past_len[:, None],
        valid_len=jnp.ones((B,), jnp.int32),
        page_table=table, past_len=past_len,
        window=jnp.asarray(0, jnp.int32), use_pallas=False,
    )
    exact = chunk_attention(
        q, k_cur, v_cur, past_k_pages=kf, past_v_pages=vf, **kw
    )
    quant = chunk_attention(
        q, k_cur, v_cur, past_k_pages=kq, past_v_pages=vq,
        past_k_scale=ks, past_v_scale=vs, **kw
    )
    np.testing.assert_allclose(
        np.asarray(quant), np.asarray(exact), atol=0.05, rtol=0.05
    )


def test_engine_end_to_end_int8_kv(byte_tok):
    """Full scheduler job over the quantized pool: every row completes
    with sane outputs, prefix cache and windows included."""
    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest

    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg())
    assert runner.cache.quantized
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    prefix = "SHARED SYSTEM PROMPT FOR EVERY ROW OF THIS JOB: "
    reqs = [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(
                byte_tok.encode(prefix + f"item {i}"), np.int32
            ),
            max_new_tokens=8,
            temperature=0.0,
        )
        for i in range(6)
    ]
    res = {}
    out = b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
    assert out == "completed"
    assert set(res) == set(range(6))
    for r in res.values():
        assert r.finish_reason in ("stop", "length")
        assert np.isfinite(r.cumulative_logprob)
    # greedy outputs should largely agree with the exact-cache engine
    # (tiny f32 model, small quantization error) — require majority
    # token agreement, not equality
    runner2 = ModelRunner(
        MODEL_CONFIGS["tiny-dense"], _ecfg(kv_quantize=None)
    )
    b2 = ContinuousBatcher(runner2, stop_ids=byte_tok.stop_ids())
    reqs2 = [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(
                byte_tok.encode(prefix + f"item {i}"), np.int32
            ),
            max_new_tokens=8,
            temperature=0.0,
        )
        for i in range(6)
    ]
    res2 = {}
    b2.run(reqs2, on_result=lambda r: res2.__setitem__(r.row_id, r))
    agree = sum(
        t1 == t2
        for i in res
        for t1, t2 in zip(res[i].token_ids, res2[i].token_ids)
    )
    total = sum(len(res2[i].token_ids) for i in res2)
    assert agree >= total * 0.5, f"{agree}/{total} tokens agree"


def test_int8_kv_under_tp_mesh_matches_single_device(eight_devices):
    """int8 KV under a dp x tp mesh: per-token scales are computed over
    the FULL fused KD axis (a cross-shard reduce under GSPMD), so they
    are shard-invariant and the scale pools replicate — greedy
    generation must match the single-device int8 cache exactly."""
    import jax

    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.parallel.mesh import make_mesh

    cfg = MODEL_CONFIGS["tiny-dense"]
    prompt = np.arange(11, dtype=np.int32) % 200

    def run(mesh):
        runner = ModelRunner(cfg, _ecfg(), mesh=mesh)
        assert runner.ecfg.kv_quantize == "int8", "gate must not strip"
        assert runner.cache.quantized
        table = np.zeros((16,), np.int32)
        table[:4] = [1, 2, 3, 4]
        logits = runner.prefill(prompt, table)
        tok = int(np.argmax(logits))
        out = [tok]
        pos = len(prompt)
        for _ in range(4):
            toks, _ = runner.decode_step(
                np.array([tok, 0, 0, 0], np.int32),
                np.array([pos, 0, 0, 0], np.int32),
                np.stack([table] + [np.zeros((16,), np.int32)] * 3),
                jax.random.PRNGKey(0),
                np.zeros(4, np.float32),
                np.ones(4, np.float32),
            )
            tok = int(toks[0])
            out.append(tok)
            pos += 1
        return out

    single = run(None)
    sharded = run(make_mesh(2, 1, 2, eight_devices[:4]))
    assert single == sharded


def test_int8_kv_under_pp_mesh_warns_and_strips(eight_devices):
    """Pipeline decode carries bare page pools (no scales): the gate
    must warn and fall back to the bf16 cache under pp only."""
    import warnings

    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.parallel.mesh import make_mesh

    cfg = MODEL_CONFIGS["tiny-dense"]
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        runner = ModelRunner(
            cfg, _ecfg(),
            mesh=make_mesh(1, 1, 2, eight_devices[:4], pp=2),
        )
    assert runner.ecfg.kv_quantize is None
    assert not runner.cache.quantized
    assert any("pipeline" in str(x.message) for x in w)
