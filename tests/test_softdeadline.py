"""sutro_tpu.engine.softdeadline: the un-wedgeable-queue primitive.

Each case runs a small subprocess (no jax import — the module is pure
stdlib) and asserts the exit discipline that chip_validation.py and
chip_day.sh rely on: rc=124 on deadline/TERM with a CLEAN unwind
(atexit-visible), teardown never aborted by the re-signal loop, and
inherited-SIG_IGN dispositions overridden (non-interactive shells
launch children with SIGINT ignored)."""

import signal
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def run_child(body: str, timeout: int = 60, preexec=None):
    code = (
        f"import sys; sys.path.insert(0, {str(REPO)!r})\n"
        "import atexit\n"
        "atexit.register(lambda: print('ATEXIT-RAN', flush=True))\n"
        + body
    )
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=timeout,
        preexec_fn=preexec,
    )


def test_deadline_interrupts_blocking_sleep_cleanly():
    r = run_child(
        "from sutro_tpu.engine.softdeadline import arm\n"
        "arm(1, 30)\n"
        "import time; time.sleep(60)\n"
        "print('NOT REACHED')\n"
    )
    assert r.returncode == 124, (r.returncode, r.stderr)
    assert "NOT REACHED" not in r.stdout
    # clean unwind: atexit hooks ran (a SIGKILL/os._exit path skips them)
    assert "ATEXIT-RAN" in r.stdout, (r.stdout, r.stderr)
    assert "clean unwind to exit 124" in r.stderr


def test_sigterm_takes_clean_path():
    r = run_child(
        "from sutro_tpu.engine.softdeadline import arm\n"
        "arm(300)\n"
        "import os, signal, threading, time\n"
        "threading.Timer(1, lambda: os.kill(os.getpid(),"
        " signal.SIGTERM)).start()\n"
        "time.sleep(60)\n"
    )
    assert r.returncode == 124, (r.returncode, r.stderr)
    assert "ATEXIT-RAN" in r.stdout


def test_normal_exit_unaffected():
    r = run_child(
        "from sutro_tpu.engine.softdeadline import arm\n"
        "arm(300)\n"
        "print('done')\n"
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "done" in r.stdout


def test_inherited_sigint_ignore_is_overridden():
    # non-interactive shells launch async-list children with SIGINT
    # ignored; Python preserves SIG_IGN, which would make the
    # watchdog's interrupt a silent no-op without arm()'s own handler
    def ignore_int():
        signal.signal(signal.SIGINT, signal.SIG_IGN)

    r = run_child(
        "import signal\n"
        "assert signal.getsignal(signal.SIGINT) is signal.SIG_IGN\n"
        "from sutro_tpu.engine.softdeadline import arm\n"
        "arm(1, 30)\n"
        "import time; time.sleep(60)\n"
        "print('NOT REACHED')\n",
        preexec=ignore_int,
    )
    assert r.returncode == 124, (r.returncode, r.stderr)
    assert "ATEXIT-RAN" in r.stdout


def test_slow_finally_teardown_not_aborted():
    # teardown longer than the 15s re-signal cadence must complete:
    # the watchdog stops re-signalling once the interrupt is delivered
    r = run_child(
        "from sutro_tpu.engine.softdeadline import arm\n"
        "arm(1, 40)\n"
        "import time\n"
        "try:\n"
        "    time.sleep(60)\n"
        "finally:\n"
        "    for _ in range(18): time.sleep(1)\n"
        "    print('TEARDOWN-DONE', flush=True)\n"
    )
    assert r.returncode == 124, (r.returncode, r.stderr)
    assert "TEARDOWN-DONE" in r.stdout, (r.stdout, r.stderr)


def test_second_sigint_during_teardown_is_swallowed():
    # _sigint must be idempotent after delivery: a re-signal (or stray
    # ^C) landing INSIDE a finally-block teardown must not raise a
    # second SystemExit and abort the cleanup the clean exit protects
    r = run_child(
        "from sutro_tpu.engine import softdeadline as sd\n"
        "sd.arm(1, 40)\n"
        "import os, signal, time\n"
        "try:\n"
        "    time.sleep(60)\n"
        "finally:\n"
        "    time.sleep(0.2)\n"
        "    os.kill(os.getpid(), signal.SIGINT)  # mid-teardown\n"
        "    time.sleep(0.5)\n"
        "    print('TEARDOWN-DONE', flush=True)\n"
    )
    assert r.returncode == 124, (r.returncode, r.stderr)
    assert "TEARDOWN-DONE" in r.stdout, (r.stdout, r.stderr)
    assert "ATEXIT-RAN" in r.stdout


def test_env_arming_and_bad_grace_fallback():
    r = run_child(
        "import os\n"
        "os.environ['SUTRO_SOFT_DEADLINE_S'] = '1'\n"
        "os.environ['SUTRO_SOFT_GRACE_S'] = 'not-a-number'\n"
        "from sutro_tpu.engine.softdeadline import arm_from_env\n"
        "arm_from_env()\n"
        "import time; time.sleep(60)\n"
    )
    assert r.returncode == 124, (r.returncode, r.stderr)
