"""Distributed telemetry (telemetry/distributed.py, OBSERVABILITY.md
"Distributed telemetry" / "Doctor").

Covers the tentpole end to end at the channel level (no engines — the
full 2-process engine acceptance lives in test_dphost.py):

1. wire pieces — trace context versioning, worker shard bounds,
   registry snapshot/delta math, coordinator ingestion + federation
   (worker-labelled series, overflow collapse, prom-text validity);
2. a real coordinator/worker round over localhost with telemetry
   riding the channel, including graceful degradation against
   old-frame peers in BOTH directions;
3. the bottleneck doctor — verdict taxonomy unit cases and the
   golden-pinned diagnosis of a deterministic merged document.
"""

import json
import threading
import time
from pathlib import Path

import pytest

from sutro_tpu import telemetry
from sutro_tpu.telemetry import distributed, doctor
from sutro_tpu.telemetry.registry import MetricsRegistry, snapshot_delta
from sutro_tpu.telemetry.spans import FlightRecorder, JobTelemetryStore

from tests.conftest import free_low_port as _free_port
from tests.test_telemetry import assert_valid_prometheus

DOCTOR_GOLDEN = Path(__file__).parent / "data" / "doctor_verdict.golden"


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset_for_tests()
    telemetry.set_enabled(True)
    yield
    telemetry.reset_for_tests()
    telemetry.set_enabled(True)


# ---------------------------------------------------------------------------
# wire pieces
# ---------------------------------------------------------------------------


def test_trace_context_versioned_and_disabled_off():
    ctx = distributed.trace_context("job-x", 3)
    assert ctx["v"] == distributed.WIRE_VERSION
    assert ctx["trace"] == "job-x/r3" and ctx["round"] == 3
    telemetry.set_enabled(False)
    assert distributed.trace_context("job-x", 4) is None


def test_worker_telemetry_rejects_foreign_wire_version():
    w = distributed.WorkerTelemetry("j", 1)
    assert w.begin({"v": distributed.WIRE_VERSION + 1}) is False
    assert w.payload() is None
    # no context at all (old coordinator) is equally inert
    w2 = distributed.WorkerTelemetry("j", 1)
    assert w2.begin(None) is False
    assert w2.payload() is None


def test_worker_payload_spans_bounded(monkeypatch):
    monkeypatch.setattr(distributed, "MAX_SHIP_SPANS", 16)
    rec = FlightRecorder(capacity=256)
    jobs = JobTelemetryStore()
    reg = MetricsRegistry()
    w = distributed.WorkerTelemetry(
        "j", 1, registry=reg, recorder=rec, jobs=jobs
    )
    assert w.begin(distributed.trace_context("j", 1)) is True
    for i in range(40):
        rec.record("accept", "j", time.monotonic(), 0.001, {"i": i})
    p = w.payload()
    # 40 spans + the dp_round envelope, capped at 16 newest
    assert len(p["spans"]) == 16
    assert p["spans_dropped"] == 25
    assert p["spans"][-1]["name"] == "dp_round"  # envelope recorded last
    assert p["v"] == distributed.WIRE_VERSION and p["rank"] == 1


def test_snapshot_delta_counters_hists_gauges():
    r = MetricsRegistry()
    c = r.counter("d_total", "x", labels=("k",))
    h = r.histogram("d_seconds", "x", buckets=(0.1, 1.0))
    g = r.gauge("d_gauge", "x")
    c.inc(5, "a")
    h.observe(0.05)
    g.set(1.0)
    before = r.export_snapshot()
    c.inc(2, "a")
    c.inc(1, "b")
    h.observe(5.0)
    g.set(42.0)
    d = snapshot_delta(before, r.export_snapshot())
    assert [["d_total", ["a"], 2.0], ["d_total", ["b"], 1.0]] == d[
        "counters"
    ]
    ((name, lv, acc),) = d["hists"]
    assert name == "d_seconds" and acc[-1] == 1.0  # one new observation
    assert ["d_gauge", [], 42.0] in d["gauges"]  # current value, not delta
    # a quiet registry ships an empty delta
    d2 = snapshot_delta(r.export_snapshot(), r.export_snapshot())
    assert d2["counters"] == [] and d2["hists"] == []


def test_ingest_remote_federates_with_worker_label():
    r = MetricsRegistry()
    c = r.counter("f_total", "x", labels=("k",))
    c.inc(10, "a")
    h = r.histogram("f_seconds", "x", buckets=(0.1, 1.0))
    h.observe(0.5)
    shard = {
        "counters": [["f_total", ["a"], 3.0]],
        "hists": [["f_seconds", [], [1.0, 0.0, 0.0, 0.05, 1.0]]],
        "gauges": [],
    }
    r.ingest_remote("1", shard)
    r.ingest_remote("1", shard)  # deltas ACCUMULATE per worker
    snap = r.collect()
    assert snap["f_total"]["labels"] == ["k", "worker"]
    assert snap["f_total"]["series"]["a,0"] == 10.0
    assert snap["f_total"]["series"]["a,1"] == 6.0
    assert snap["f_seconds"]["series"]["1"]["count"] == 2
    # fleet total = sum over worker series (prom convention)
    text = r.to_prometheus()
    assert_valid_prometheus(text)
    assert 'f_total{k="a",worker="0"} 10' in text
    assert 'f_total{k="a",worker="1"} 6' in text


def test_ingest_remote_skips_unknown_and_malformed():
    r = MetricsRegistry()
    r.counter("k_total", "x")
    r.ingest_remote(
        "1",
        {
            "counters": [
                ["unknown_total", [], 5.0],  # undeclared -> skipped
                ["k_total", ["extra"], 1.0],  # label arity mismatch
                ["k_total"],  # malformed triple
                ["k_total", [], 2.0],  # valid
            ],
            "hists": [["k_total", [], [1.0]]],  # wrong kind -> skipped
        },
    )
    snap = r.collect()
    assert snap["k_total"]["series"] == {"1": 2.0}


def test_ingest_remote_worker_cardinality_bounded():
    r = MetricsRegistry()
    r.counter("w_total", "x")
    for i in range(MetricsRegistry.MAX_WORKERS + 10):
        r.ingest_remote(str(i + 1), {"counters": [["w_total", [], 1.0]]})
    series = r.collect()["w_total"]["series"]
    assert "_overflow" in series
    assert series["_overflow"] == 10.0
    # bounded store: at most MAX_WORKERS + overflow + local
    assert len(series) <= MetricsRegistry.MAX_WORKERS + 2


def test_distributed_store_rounds_and_bounds():
    store = distributed.DistributedTelemetry(max_sections=4)
    assert store.next_round("j") == 1
    assert store.next_round("j") == 2
    payload = {
        "v": distributed.WIRE_VERSION,
        "rank": 1, "round": 1, "epoch_unix": telemetry.RECORDER.epoch_wall,
        "spans": [{"name": "accept", "t0_s": 1.0, "dur_s": 0.5}],
        "counters": {"rows_ok": 3},
        "registry": {},
    }
    assert store.ingest("j", 1, payload) is True
    # same (round, rank) replaces (a reconnect's retry), new rank adds
    assert store.ingest("j", 1, payload) is True
    assert store.ingest("j", 2, {**payload, "rank": 2}) is True
    secs = store.sections("j")
    assert [(s["round"], s["rank"]) for s in secs] == [(1, 1), (1, 2)]
    assert secs[0]["spans"][0]["t0_coord_s"] == pytest.approx(1.0, abs=1e-6)
    # wire-version drift and garbage degrade to False, never raise
    assert store.ingest("j", 3, {**payload, "v": 99}) is False
    assert store.ingest("j", 3, "not a dict") is False
    assert store.ingest("j", 3, {**payload, "round": "NaNsense"}) is False
    # section cap
    for rr in range(3, 9):
        store.ingest("j", rr, {**payload, "rank": rr})
    assert len(store.sections("j")) <= 4


# ---------------------------------------------------------------------------
# channel-level round with telemetry riding the frames
# ---------------------------------------------------------------------------


def _world(port):
    from sutro_tpu.engine.dphost import DPWorld

    return (
        DPWorld(rank=0, world=2, host="127.0.0.1", port=port),
        DPWorld(rank=1, world=2, host="127.0.0.1", port=port),
    )


def _reqs(n):
    import numpy as np

    from sutro_tpu.engine.scheduler import GenRequest

    return [
        GenRequest(
            row_id=i, prompt_ids=np.zeros(1, np.int32), max_new_tokens=1
        )
        for i in range(n)
    ]


def _res(row_id):
    from sutro_tpu.engine.scheduler import GenResult

    return GenResult(
        row_id=row_id, token_ids=[7], cumulative_logprob=-0.5,
        finish_reason="stop", input_tokens=1,
    )


def _run_round(worker_tele, tele_ctx, on_worker_tele, worker_spans=3):
    """One coordinator/worker round over localhost with stub shards;
    returns (outcome, merged row ids)."""
    from sutro_tpu.engine.dphost import (
        run_dp_coordinator,
        run_dp_worker,
        shard_requests,
    )

    port = _free_port()
    cw, ww = _world(port)
    reqs = _reqs(8)
    merged = {}

    def coord_shard(shard, on_result, on_progress, should_cancel):
        for q in shard:
            on_result(_res(q.row_id))
        return "completed"

    def worker_shard(shard, on_result, on_progress, should_cancel):
        for k in range(worker_spans):
            telemetry.RECORDER.record(
                "decode_window", "wjob", time.monotonic(), 0.004,
                {"batch": 8, "steps": 4, "avg_ctx": 64.0},
            )
        telemetry.TOKENIZE_ROWS_TOTAL.inc(float(len(shard)))
        for q in shard:
            on_result(_res(q.row_id))
        return "completed"

    out = {}

    def worker_main():
        out["w"] = run_dp_worker(
            ww, worker_shard, shard_requests(reqs, 1, 2),
            tele=worker_tele,
        )

    t = threading.Thread(target=worker_main)
    t.start()
    outcome = run_dp_coordinator(
        cw, coord_shard, shard_requests(reqs, 0, 2),
        on_result=lambda r: merged.__setitem__(r.row_id, r),
        tele_ctx=tele_ctx,
        on_worker_tele=on_worker_tele,
    )
    t.join(timeout=120)
    assert not t.is_alive()
    assert out["w"] == "completed"
    return outcome, set(merged)


def test_channel_round_ships_worker_shard():
    store = distributed.DistributedTelemetry()
    round_no = store.next_round("cjob")
    ctx = distributed.trace_context("cjob", round_no)
    got = []

    def on_worker_tele(rank, shard):
        got.append((rank, shard))
        store.ingest("cjob", rank, shard)

    outcome, merged = _run_round(
        distributed.WorkerTelemetry("wjob", 1), ctx, on_worker_tele
    )
    assert outcome == "completed" and merged == {0, 1, 2, 3, 4, 5, 6, 7}
    ((rank, shard),) = got
    assert rank == 1 and shard["trace"] == "cjob/r1"
    (sec,) = store.sections("cjob")
    names = [s["name"] for s in sec["spans"]]
    assert names.count("decode_window") == 3
    assert names[-1] == "dp_round"
    # the worker's registry delta federated into the live registry
    snap = telemetry.REGISTRY.collect()
    tok = snap["sutro_tokenize_rows_total"]
    assert tok["labels"][-1] == "worker"
    assert tok["series"]["1"] == 4.0
    # ingestion is itself observable
    assert snap["sutro_dp_events_total"]["series"]["tele_shard"] == 1
    assert_valid_prometheus(telemetry.REGISTRY.to_prometheus())


def test_channel_old_worker_degrades_to_partial_data():
    """Coordinator with telemetry vs a worker that ships nothing (old
    frame / SUTRO_TELEMETRY=0 there): the round completes, the document
    reports partial data and the doctor names the silent rank."""
    store = distributed.DistributedTelemetry()
    ctx = distributed.trace_context("cjob", store.next_round("cjob"))
    got = []
    outcome, merged = _run_round(None, ctx, lambda r, s: got.append(r))
    assert outcome == "completed" and len(merged) == 8
    assert got == [] and store.sections("cjob") == []
    doc = {
        "job_id": "cjob",
        "spans": [
            {"name": "dp_round", "t0_s": 0.0, "dur_s": 2.0,
             "attrs": {"world": 2}},
            {"name": "decode_window", "t0_s": 0.1, "dur_s": 1.5},
        ],
        "counters": {"rows_ok": 8},
    }
    diag = doctor.diagnose(doc)
    assert diag["partial"] is True and diag["missing_ranks"] == [1]
    assert any("rank(s) 1" in e for e in diag["evidence"])
    assert diag["verdict"] != "insufficient_data"


def test_channel_old_coordinator_worker_ships_nothing():
    """Worker with telemetry against a coordinator that sends no trace
    context (old frame): the worker's session stays inert and the round
    completes — no half-opened telemetry."""
    w = distributed.WorkerTelemetry("wjob", 1)
    outcome, merged = _run_round(w, None, None)
    assert outcome == "completed" and len(merged) == 8
    assert w.payload() is None


# ---------------------------------------------------------------------------
# doctor
# ---------------------------------------------------------------------------


def _span(name, t0, dur, **attrs):
    d = {"name": name, "job_id": "j", "t0_s": t0, "dur_s": dur}
    if attrs:
        d["attrs"] = attrs
    return d


_V5E = {
    "device_kind": "TPU v5 lite", "n_devices": 1,
    "param_bytes": 2_000_000_000, "n_params": 1_000_000_000,
    "num_layers": 24, "kv_heads": 8, "head_dim": 128,
    "kv_dtype_bytes": 2,
}


def test_doctor_straggler_worker():
    doc = {
        "job_id": "j",
        "spans": [
            _span("dp_round", 0.0, 10.0, world=3),
            _span("decode_window", 0.0, 2.0),
        ],
        "counters": {"rows_ok": 10},
        "workers": [
            {"rank": 1, "round": 1,
             "spans": [_span("decode_window", 0.0, 2.0)],
             "counters": {"rows_ok": 5}},
            {"rank": 2, "round": 1,
             "spans": [_span("decode_window", 0.0, 9.5)],
             "counters": {"rows_ok": 5}},
        ],
    }
    diag = doctor.diagnose(doc)
    assert diag["verdict"] == "straggler_worker"
    assert any("rank2" in e for e in diag["evidence"])
    assert diag["processes"]["rank2"]["wall_s"] == 9.5


def test_doctor_host_bound_admit():
    doc = {
        "job_id": "j",
        "spans": [
            _span("constraint_compile", 0.0, 4.0),
            _span("accept", 4.0, 1.0),
            _span("decode_window", 5.0, 1.0),
        ],
        "counters": {},
    }
    diag = doctor.diagnose(doc)
    assert diag["verdict"] == "host_bound_admit"
    assert any("constraint_compile" in e for e in diag["evidence"])


def test_doctor_io_bound():
    doc = {
        "job_id": "j",
        "spans": [
            _span("flush", 0.0, 3.0),
            _span("finalize", 3.0, 2.0),
            _span("decode_window", 5.0, 1.0),
            _span("tokenize", 6.0, 0.1),
        ],
        "counters": {},
    }
    assert doctor.diagnose(doc)["verdict"] == "io_bound"


def test_doctor_decode_below_roofline():
    # 8 rows x 4 steps in 80 ms => 400 tok/s on a v5e: far under the
    # HBM roofline for this byte budget
    doc = {
        "job_id": "j",
        "attrs": {"device": _V5E},
        "spans": [
            _span("decode_window", 0.0, 0.08, batch=8, steps=4,
                  avg_ctx=128.0)
            for _ in range(4)
        ],
        "counters": {"rows_ok": 8, "input_tokens": 1024,
                     "output_tokens": 256},
    }
    diag = doctor.diagnose(doc)
    assert diag["verdict"] == "decode_below_roofline"
    rl = diag["processes"]["rank0"]["roofline"]
    assert rl["graded_windows"] == 4
    assert rl["decode_pct_hbm_median"] < 40.0


def test_doctor_unknown_device_grades_omitted_not_fabricated():
    doc = {
        "job_id": "j",
        "attrs": {"device": {**_V5E, "device_kind": "cpu"}},
        "spans": [
            _span("decode_window", 0.0, 0.08, batch=8, steps=4)
        ],
        "counters": {},
    }
    diag = doctor.diagnose(doc)
    rl = diag["processes"]["rank0"]["roofline"]
    assert rl["graded_windows"] == 0 and "no roofline spec" in rl["reason"]
    assert diag["verdict"] == "healthy"


def test_doctor_golden_pinned():
    """THE deterministic merged document (2-worker dp job, straggling
    rank 2, graded v5e decode windows) and its diagnosis, pinned
    byte-for-byte. Regenerate with
    ``python tests/test_distributed_telemetry.py --regen-golden``."""
    assert DOCTOR_GOLDEN.exists(), (
        "golden missing (regen: python "
        "tests/test_distributed_telemetry.py --regen-golden)"
    )
    got = json.dumps(doctor.diagnose(**_golden_case()), indent=2) + "\n"
    assert got == DOCTOR_GOLDEN.read_text()


def _golden_case():
    doc = {
        "version": 2,
        "job_id": "job-golden",
        "counters": {"rows_ok": 23, "rows_quarantined": 1,
                     "input_tokens": 4800, "output_tokens": 1200},
        "attrs": {"device": dict(_V5E)},
        "spans": [
            _span("dp_round", 0.0, 8.0, world=3),
            _span("tokenize", 0.0, 0.2, rows=24),
            _span("prefill", 0.3, 0.5, tokens=1600, batch=8),
            _span("decode_window", 1.0, 0.05, batch=8, steps=16,
                  avg_ctx=220.0),
            _span("decode_window", 1.1, 0.05, batch=8, steps=16,
                  avg_ctx=236.0),
            _span("accept", 1.2, 0.01),
            _span("flush", 1.3, 0.02),
            _span("finalize", 7.5, 0.4),
        ],
        "workers": [
            {
                "rank": 1, "round": 1, "trace": "job-golden/r1",
                "epoch_unix": 100.0, "clock_offset_s": 0.25,
                "spans": [
                    _span("tokenize", 0.0, 0.2, rows=24),
                    _span("decode_window", 0.5, 0.05, batch=8,
                          steps=16, avg_ctx=228.0),
                    _span("dp_round", 0.0, 2.4, rank=1),
                ],
                "spans_dropped": 0,
                "counters": {"rows_ok": 8},
                "attrs": {"device": dict(_V5E)},
            },
            {
                "rank": 2, "round": 1, "trace": "job-golden/r1",
                "epoch_unix": 100.0, "clock_offset_s": -0.125,
                "spans": [
                    _span("tokenize", 0.0, 0.2, rows=24),
                    _span("decode_window", 0.5, 0.6, batch=8,
                          steps=16, avg_ctx=228.0),
                    _span("dp_round", 0.0, 7.9, rank=2),
                ],
                "spans_dropped": 0,
                "counters": {"rows_ok": 8},
                "attrs": {"device": dict(_V5E)},
            },
        ],
    }
    return {"doc": doc, "status": "SUCCEEDED", "num_rows": 24}


if __name__ == "__main__":
    import sys

    if "--regen-golden" in sys.argv:
        DOCTOR_GOLDEN.parent.mkdir(parents=True, exist_ok=True)
        DOCTOR_GOLDEN.write_text(
            json.dumps(doctor.diagnose(**_golden_case()), indent=2)
            + "\n"
        )
        print(f"wrote {DOCTOR_GOLDEN}")
