"""Eval templates: Bradley–Terry/Elo math (pure client-side, reference
evals.py:225-313) and input validation."""

import numpy as np
import pandas as pd
import pytest

from sutro_tpu.templates.evals import Rank


def test_elo_orders_clear_winner():
    rankings = [["a", "b", "c"]] * 10 + [["a", "c", "b"]] * 3
    df = Rank.elo(rankings)
    assert list(df["player"]) == ["a", "b", "c"]
    assert df["elo"].iloc[0] > df["elo"].iloc[1] > df["elo"].iloc[2]


def test_elo_symmetric_is_flat():
    rankings = [["a", "b"], ["b", "a"]] * 5
    df = Rank.elo(rankings)
    assert abs(df["elo"].iloc[0] - df["elo"].iloc[1]) < 1.0


def test_elo_tie_groups():
    # a always wins; b and c always tie behind a
    rankings = [["a", ["b", "c"]]] * 6
    df = Rank.elo(rankings)
    assert df["player"].iloc[0] == "a"
    b = df[df["player"] == "b"]["elo"].iloc[0]
    c = df[df["player"] == "c"]["elo"].iloc[0]
    assert abs(b - c) < 1.0


def test_elo_tie_break_is_deterministic_alphabetical():
    """Equal-strength players sort by name: the server-side elo stage
    recomputes the table on resume, so the ordering must be a pure
    function of the rankings — never dict/iteration order."""
    # z and y are perfectly symmetric; first-appearance order says z
    rankings = [["z", "y"], ["y", "z"]] * 3
    df = Rank.elo(rankings)
    assert list(df["player"]) == ["y", "z"]
    # three-way tie behind a clear winner: the tied tail is alphabetical
    df = Rank.elo([["w", ["c", "b", "d"]]] * 4)
    assert list(df["player"]) == ["w", "b", "c", "d"]
    # and the full frame is reproducible run to run
    pd.testing.assert_frame_equal(Rank.elo(rankings), Rank.elo(rankings))


def test_elo_json_string_rankings():
    df = Rank.elo(['["a","b"]', '["a","b"]', "not-json"])
    assert df["player"].iloc[0] == "a"


def test_elo_empty():
    df = Rank.elo([])
    assert len(df) == 0


def test_rank_validates_options():
    class Dummy(Rank):
        pass

    d = Dummy()
    with pytest.raises(ValueError, match="DataFrame"):
        d.rank(["not-a-df"], options=["a"], criteria="c")
    with pytest.raises(ValueError, match="not in DataFrame"):
        d.rank(
            pd.DataFrame({"a": ["1"]}), options=["a", "missing"], criteria="c"
        )


def test_rank_schema_is_true_permutation():
    """<=5 options: the ranking FSM accepts only permutations (each
    label exactly once) — repeats and omissions are rejected."""
    import json

    from sutro_tpu.engine.constrain import compile_schema

    from sutro_tpu.templates.evals import _ranking_schema

    options = ["a", "b", "c"]
    schema = {
        "type": "object",
        "properties": {"ranking": _ranking_schema(options)},
        "required": ["ranking"],
    }
    nfa = compile_schema(schema)

    def accepts(text):
        states = nfa.initial()
        for byte in text.encode():
            states = nfa.step(states, byte)
            if not states:
                return False
        return nfa.is_accepting(states)

    enc = lambda r: json.dumps({"ranking": r}, separators=(",", ":"))  # noqa: E731
    assert accepts(enc(["b", "a", "c"]))
    assert accepts(enc(["c", "b", "a"]))
    assert not accepts(enc(["a", "a", "b"]))   # repeat
    assert not accepts(enc(["a", "b"]))        # omission
    assert not accepts(enc(["a", "b", "d"]))   # unknown label
