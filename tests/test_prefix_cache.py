"""Shared-prefix KV caching (engine/scheduler._SharedPrefix, VERDICT r3
next-step 2): a job whose rows share a common token prefix — every
templated job does (/root/reference/sutro/templates/classification.py
builds one prompt shell for all rows) — prefills that prefix ONCE into
shared pages. Outputs must be bit-identical with the cache on and off,
prefill token counts must drop to prefix + suffixes, and the shared
pages must return to the pool on every exit path."""

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest
from sutro_tpu.models.configs import MODEL_CONFIGS

PREFIX = "You are a terse classifier. Decide the sentiment of this: "
TAILS = [
    "great!",
    "bad movie",
    "meh",
    "totally awesome ride",
    "x",
    "the worst thing ever made",
]


def _ecfg(**kw):
    base = dict(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32",
    )
    base.update(kw)
    return EngineConfig(**base)


def _reqs(tok, tails=TAILS, **kw):
    return [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(tok.encode(PREFIX + t), np.int32),
            **kw,
        )
        for i, t in enumerate(tails)
    ]


def _run(ecfg, tok, reqs, **run_kw):
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)
    b = ContinuousBatcher(runner, stop_ids=tok.stop_ids())
    res = {}
    outcome = b.run(
        reqs, on_result=lambda r: res.__setitem__(r.row_id, r), **run_kw
    )
    return b, outcome, res


def _expected_shared(tok, tails=TAILS, page=8):
    rows = [np.array(tok.encode(PREFIX + t), np.int32) for t in tails]
    lcp = min(len(r) for r in rows) - 1
    first = rows[0]
    for r in rows[1:]:
        neq = np.nonzero(first[:lcp] != r[:lcp])[0]
        if len(neq):
            lcp = int(neq[0])
    return (lcp // page) * page, rows


def test_outputs_bit_identical_greedy(byte_tok):
    _, _, on = _run(
        _ecfg(prefix_cache=True), byte_tok,
        _reqs(byte_tok, max_new_tokens=10, temperature=0.0),
    )
    _, _, off = _run(
        _ecfg(prefix_cache=False), byte_tok,
        _reqs(byte_tok, max_new_tokens=10, temperature=0.0),
    )
    assert set(on) == set(off) == set(range(len(TAILS)))
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i
        assert on[i].finish_reason == off[i].finish_reason


def test_outputs_identical_row_seeded_sampling(byte_tok):
    """Sampled generation with per-row seeds is batch-composition
    independent — the prefix cache must not change a single token."""
    kw = dict(max_new_tokens=8, temperature=0.9, top_p=0.9)
    reqs_on = _reqs(byte_tok, **kw)
    reqs_off = _reqs(byte_tok, **kw)
    for i, (a, b) in enumerate(zip(reqs_on, reqs_off)):
        a.row_seed = b.row_seed = i
    _, _, on = _run(_ecfg(prefix_cache=True), byte_tok, reqs_on)
    _, _, off = _run(_ecfg(prefix_cache=False), byte_tok, reqs_off)
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i


def test_prefill_tokens_drop_to_prefix_plus_suffixes(byte_tok):
    """The instrument the VERDICT asked for: prefill token count for an
    N-row templated job drops from sum(full prompts) to prefix +
    sum(suffixes) — the shared part is prefilled exactly once."""
    shared, rows = _expected_shared(byte_tok)
    assert shared >= 8  # the fixture really has a page-aligned prefix
    b_on, _, _ = _run(
        _ecfg(prefix_cache=True), byte_tok,
        _reqs(byte_tok, max_new_tokens=4, temperature=0.0),
    )
    b_off, _, _ = _run(
        _ecfg(prefix_cache=False), byte_tok,
        _reqs(byte_tok, max_new_tokens=4, temperature=0.0),
    )
    full = sum(len(r) for r in rows)
    assert b_off.prefill_tokens == full
    assert b_on.prefill_tokens == shared + sum(
        len(r) - shared for r in rows
    )
    assert b_on.prefill_tokens < full


def test_long_suffix_chunked_path(byte_tok):
    """Suffixes longer than prefill_chunk ride the chunked paged path
    starting at the shared offset — outputs still bit-identical."""
    tails = [
        "short one",
        "long tail " * 6,  # 60 chars > prefill_chunk=32
        "another long suffix " * 4,
    ]
    kw = dict(max_new_tokens=6, temperature=0.0)
    _, _, on = _run(
        _ecfg(prefix_cache=True, prefill_chunk=32), byte_tok,
        _reqs(byte_tok, tails=tails, **kw),
    )
    _, _, off = _run(
        _ecfg(prefix_cache=False, prefill_chunk=32), byte_tok,
        _reqs(byte_tok, tails=tails, **kw),
    )
    for i in on:
        assert on[i].token_ids == off[i].token_ids, i


def test_pages_all_freed_after_run(byte_tok):
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg())
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    before = b.free_page_count
    b.run(
        _reqs(byte_tok, max_new_tokens=4, temperature=0.0),
        on_result=lambda r: None,
    )
    assert b.free_page_count == before
    assert b._prefix is None


def test_yield_frees_prefix_and_resume_completes(byte_tok):
    """Preemption yield returns the shared pages too; the re-run
    (row-granular resume) rebuilds the prefix and completes."""
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg())
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    before = b.free_page_count
    reqs = _reqs(byte_tok, max_new_tokens=6, temperature=0.0)
    outcome = b.run(
        reqs, on_result=lambda r: None, should_yield=lambda: True
    )
    assert outcome == "yielded"
    assert b.free_page_count == before
    assert b._prefix is None
    res = {}
    outcome = b.run(
        _reqs(byte_tok, max_new_tokens=6, temperature=0.0),
        on_result=lambda r: res.__setitem__(r.row_id, r),
    )
    assert outcome == "completed"
    assert set(res) == set(range(len(TAILS)))
    assert b.free_page_count == before


def test_cancel_frees_prefix(byte_tok):
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg())
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    before = b.free_page_count
    calls = [0]

    def cancel():
        calls[0] += 1
        return calls[0] > 2

    outcome = b.run(
        _reqs(byte_tok, max_new_tokens=50),
        on_result=lambda r: None,
        should_cancel=cancel,
    )
    assert outcome == "cancelled"
    assert b.free_page_count == before
    assert b._prefix is None


def test_no_prefix_for_disjoint_prompts(byte_tok):
    """Rows with no common page-aligned prefix run exactly as before."""
    reqs = [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(byte_tok.encode(t), np.int32),
            max_new_tokens=4,
            temperature=0.0,
        )
        for i, t in enumerate(["alpha one", "beta two", "gamma three"])
    ]
    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], _ecfg())
    b = ContinuousBatcher(runner, stop_ids=byte_tok.stop_ids())
    res = {}
    b.run(reqs, on_result=lambda r: res.__setitem__(r.row_id, r))
    assert set(res) == {0, 1, 2}
    assert b.prefill_tokens == sum(
        len(byte_tok.encode(t))
        for t in ["alpha one", "beta two", "gamma three"]
    )


@pytest.mark.parametrize(
    "native",
    [
        False,
        # the native-allocator leg re-runs the whole prefix workload;
        # native/python parity also rides test_native_runtime.py, so
        # the combo is nightly, the python leg tier-1
        pytest.param(True, marks=pytest.mark.slow),
    ],
)
def test_native_and_python_paths_identical(
    byte_tok, monkeypatch, native
):
    """The prefix path through the C++ runtime (try_admit_pfx /
    alloc_pages) matches the pure-Python allocator bit-for-bit."""
    from sutro_tpu.engine import native_runtime

    if native and not native_runtime.is_available():
        pytest.skip("native toolchain unavailable")
    monkeypatch.setenv("SUTRO_NATIVE_RUNTIME", "1" if native else "0")
    native_runtime._lib = None
    native_runtime._lib_failed = False
    try:
        b, _, res = _run(
            _ecfg(prefix_cache=True), byte_tok,
            _reqs(byte_tok, max_new_tokens=10, temperature=0.0),
        )
        assert (b.native is not None) == native
        _, _, off = _run(
            _ecfg(prefix_cache=False), byte_tok,
            _reqs(byte_tok, max_new_tokens=10, temperature=0.0),
        )
        for i in res:
            assert res[i].token_ids == off[i].token_ids, i
        shared, rows = _expected_shared(byte_tok)
        assert b.prefill_tokens == shared + sum(
            len(r) - shared for r in rows
        )
        assert b.free_page_count == (
            b.native.free_count if native else b.allocator.free_count
        )
    finally:
        native_runtime._lib = None
        native_runtime._lib_failed = False
