"""Interactive serving tier (sutro_tpu/serving/): OpenAI-compatible
endpoints, SSE streaming, latency-priority admission beside batch,
disconnect cancellation, chaos sites, and graceful drain."""

import json
import threading

import pytest

from sutro_tpu.engine import faults
from sutro_tpu.interfaces import JobStatus


@pytest.fixture(scope="module")
def iserved(live_engine, monkeypatch_module):
    """Remote-backend SDK over the session-shared daemon (conftest
    ``live_engine``) — the engine and server are built once for this
    module AND test_sdk.py."""
    engine, url, home = live_engine
    monkeypatch_module.setenv("SUTRO_HOME", home)
    assert engine.gateway is not None
    from sutro_tpu.sdk import Sutro

    sdk = Sutro(api_key="test-key", base_url=url, backend="remote")
    yield sdk, engine, url
    faults.clear()


def _chat_body(prompt, **kw):
    body = {
        "model": "tiny-dense",
        "messages": [{"role": "user", "content": prompt}],
        "temperature": 0.0,
        "max_tokens": 6,
    }
    body.update(kw)
    return body


def _sse_objects(resp):
    """Parse an SSE response into (chunk dicts, saw_done)."""
    objs, done = [], False
    for line in resp.iter_lines():
        if not line or line.startswith(b":"):
            continue
        assert line.startswith(b"data: "), line
        data = line[len(b"data: "):]
        if data == b"[DONE]":
            done = True
            break
        objs.append(json.loads(data))
    return objs, done


def _delta_text(objs):
    return "".join(
        c["choices"][0]["delta"].get("content", "") for c in objs
    )


def test_chat_completion_shape(iserved):
    sdk, _, _ = iserved
    resp = sdk.do_request(
        "post", "v1/chat/completions", json=_chat_body("hello"), timeout=120
    )
    assert resp.status_code == 200
    out = resp.json()
    assert out["object"] == "chat.completion"
    assert out["model"] == "tiny-dense"
    choice = out["choices"][0]
    assert choice["index"] == 0
    assert choice["message"]["role"] == "assistant"
    assert isinstance(choice["message"]["content"], str)
    assert choice["finish_reason"] in ("stop", "length")
    usage = out["usage"]
    assert usage["prompt_tokens"] > 0 and usage["completion_tokens"] > 0
    assert usage["total_tokens"] == (
        usage["prompt_tokens"] + usage["completion_tokens"]
    )


def test_completions_endpoint_shape(iserved):
    sdk, _, _ = iserved
    resp = sdk.do_request(
        "post", "v1/completions",
        json={"model": "tiny-dense", "prompt": "once upon",
              "temperature": 0.0, "max_tokens": 4},
        timeout=120,
    )
    assert resp.status_code == 200
    out = resp.json()
    assert out["object"] == "text_completion"
    assert isinstance(out["choices"][0]["text"], str)
    assert out["usage"]["completion_tokens"] > 0


def test_bad_request_shapes(iserved):
    sdk, _, _ = iserved
    r = sdk.do_request("post", "v1/chat/completions",
                       json={"model": "tiny-dense", "messages": []})
    assert r.status_code == 400
    assert r.json()["error"]["type"] == "invalid_request_error"
    r = sdk.do_request("post", "v1/chat/completions",
                       json=_chat_body("x", model="no-such-model"))
    assert r.status_code == 404


def test_sse_stream_matches_nonstream(iserved):
    sdk, _, _ = iserved
    resp = sdk.do_request(
        "post", "v1/chat/completions",
        json=_chat_body("stream me", stream=True), stream=True, timeout=120,
    )
    assert resp.status_code == 200
    assert resp.headers["Content-Type"].startswith("text/event-stream")
    objs, done = _sse_objects(resp)
    assert done, "stream must end with data: [DONE]"
    assert all(o["object"] == "chat.completion.chunk" for o in objs)
    # first content chunk announces the assistant role
    first = next(o for o in objs if o["choices"][0]["delta"])
    assert first["choices"][0]["delta"].get("role") == "assistant"
    finals = [o for o in objs if o["choices"][0]["finish_reason"]]
    assert len(finals) == 1
    streamed = _delta_text(objs)
    assert streamed
    # deterministic (temperature=0): non-stream text is bit-identical
    out = sdk.do_request(
        "post", "v1/chat/completions", json=_chat_body("stream me"),
        timeout=120,
    ).json()
    assert out["choices"][0]["message"]["content"] == streamed


def test_constrained_stream_matches_batch_path(iserved):
    """response_format rides the same constrained-decode path as batch:
    greedy streaming output is bit-identical to a batch job of the same
    prompt + schema."""
    sdk, _, _ = iserved
    schema = {
        "type": "object",
        "properties": {"a": {"type": "integer"}},
        "required": ["a"],
    }
    body = _chat_body(
        "give a number", stream=True, max_tokens=24,
        response_format={
            "type": "json_schema",
            "json_schema": {"name": "out", "schema": schema},
        },
    )
    resp = sdk.do_request(
        "post", "v1/chat/completions", json=body, stream=True, timeout=300,
    )
    assert resp.status_code == 200
    objs, done = _sse_objects(resp)
    assert done
    streamed = _delta_text(objs)
    obj = json.loads(streamed)  # schema guarantee holds on the stream
    assert isinstance(obj["a"], int)
    jid = sdk.infer(
        ["give a number"], model="tiny-dense", output_schema=schema,
        sampling_params={"temperature": 0.0, "max_new_tokens": 24},
        stay_attached=False,
    )
    df = sdk.await_job_completion(jid, timeout=300)
    assert df["inference_result"][0] == streamed


def test_disconnect_cancels_and_frees_slots(iserved):
    sdk, engine, _ = iserved
    gw = engine.gateway
    from sutro_tpu.serving.openai import parse_request

    ir = gw.submit(parse_request(_chat_body("bye", stream=True), chat=True))
    # wait for the first token (the request holds a slot), then drop the
    # client: the should_cancel poll must tear the row down and free it
    for ev in ir.channel.events():
        if ev is not None and ev[0] == "token":
            break
        if ev is not None and ev[0] != "token":
            break
    ir.channel.cancel()
    deadline = threading.Event()
    for _ in range(100):
        if gw.active_count() == 0:
            break
        deadline.wait(0.2)
    assert gw.active_count() == 0
    # the tier still serves: slots were freed, not leaked
    out = sdk.do_request(
        "post", "v1/chat/completions", json=_chat_body("after"), timeout=120,
    )
    assert out.status_code == 200


def test_batch_job_coexists_with_interactive(iserved):
    """8 interactive requests stream while a batch job runs: the batch
    job SUCCEEDs with zero lost rows and every request completes."""
    sdk, _, _ = iserved
    jid = sdk.infer(
        [f"row {i}" for i in range(8)], model="tiny-dense",
        stay_attached=False,
    )
    results = [None] * 8
    def hit(i):
        r = sdk.do_request(
            "post", "v1/chat/completions",
            json=_chat_body(f"q{i}"), timeout=300,
        )
        results[i] = (r.status_code, r.json())
    threads = [threading.Thread(target=hit, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert all(r is not None and r[0] == 200 for r in results)
    assert all(
        r[1]["usage"]["completion_tokens"] > 0 for r in results
    )
    df = sdk.await_job_completion(jid, timeout=600)
    assert sdk.get_job_status(jid) == JobStatus.SUCCEEDED.value
    assert df is not None and len(df) == 8  # zero lost rows


def test_chaos_admit_rejects_503(iserved):
    sdk, _, _ = iserved
    faults.configure("serving.admit:error:times=1")
    try:
        r = sdk.do_request(
            "post", "v1/chat/completions", json=_chat_body("x"), timeout=120,
        )
        assert r.status_code == 503
        assert r.json()["error"]["type"] == "service_unavailable"
    finally:
        faults.clear()
    r = sdk.do_request(
        "post", "v1/chat/completions", json=_chat_body("x"), timeout=120,
    )
    assert r.status_code == 200


def test_chaos_midstream_drop_cancels_without_stalling_batch(iserved):
    sdk, engine, _ = iserved
    jid = sdk.infer(
        [f"b{i}" for i in range(4)], model="tiny-dense", stay_attached=False,
    )
    faults.configure("serving.stream:error:nth=1,times=1")
    try:
        resp = sdk.do_request(
            "post", "v1/chat/completions",
            json=_chat_body("doomed", stream=True), stream=True, timeout=120,
        )
        objs, done = _sse_objects(resp)
        assert done  # the injected drop still closes the stream cleanly
        assert objs == []  # dropped before the first frame reached us
    finally:
        faults.clear()
    gw = engine.gateway
    for _ in range(100):
        if gw.active_count() == 0:
            break
        threading.Event().wait(0.2)
    assert gw.active_count() == 0  # KV pages / slot freed
    df = sdk.await_job_completion(jid, timeout=600)
    assert len(df) == 4  # co-resident batch job unaffected


def test_stream_progress_end_record(iserved):
    """_stream_progress NDJSON now carries a terminal {"t":"end"} record
    and the SDK tolerates it (old consumers ignored unknown keys)."""
    sdk, _, _ = iserved
    jid = sdk.infer(["p"], model="tiny-dense", stay_attached=False)
    sdk.await_job_completion(jid, timeout=300, obtain_results=False)
    resp = sdk.do_request("get", f"stream-job-progress/{jid}", stream=True)
    lines = [json.loads(l) for l in resp.iter_lines() if l]
    assert lines, "progress stream must emit at least the end record"
    assert lines[-1]["t"] == "end"
    assert lines[-1]["status"] == JobStatus.SUCCEEDED.value
    # the SDK's iterator stops at the end record instead of choking
    updates = list(sdk._iter_progress(jid))
    assert all(u.get("t") != "end" for u in updates)


def test_sdk_chat_local_backend(iserved):
    _, engine, _ = iserved
    from sutro_tpu.sdk import Sutro

    local = Sutro(api_key="k", backend="tpu")
    local._engine = engine
    chunks = list(
        local.chat("hi there", model="tiny-dense", stream=True,
                   temperature=0.0, max_tokens=4)
    )
    assert chunks and chunks[-1]["choices"][0]["finish_reason"]
    out = local.chat("hi there", model="tiny-dense",
                     temperature=0.0, max_tokens=4)
    assert out["choices"][0]["message"]["content"] == "".join(
        c["choices"][0]["delta"].get("content", "") for c in chunks
    )


def test_interactive_disabled_404_and_batch_unaffected(iserved):
    sdk, engine, _ = iserved
    saved, engine.gateway = engine.gateway, None
    try:
        r = sdk.do_request(
            "post", "v1/chat/completions", json=_chat_body("x"))
        assert r.status_code == 404
        jid = sdk.infer(["plain"], model="tiny-dense", stay_attached=False)
        df = sdk.await_job_completion(jid, timeout=300)
        assert len(df) == 1
    finally:
        engine.gateway = saved


def test_graceful_drain(iserved):
    sdk, engine, _ = iserved
    gw = engine.gateway
    gw.begin_drain()
    try:
        r = sdk.do_request(
            "post", "v1/chat/completions", json=_chat_body("x"))
        assert r.status_code == 503
        assert gw.wait_idle(10.0)
    finally:
        gw.draining = False
    r = sdk.do_request(
        "post", "v1/chat/completions", json=_chat_body("x"), timeout=120)
    assert r.status_code == 200
