"""Priority-aware multi-job scheduling (reference two-priority
semantics, /root/reference/README.md:168-171): a priority-0
(interactive) job gets interactive latency over a running priority-1
batch. SAME-model p0 jobs now ATTACH to the running batch (cross-job
co-batching, tests/test_cobatch.py) instead of preempting it;
different-model p0 jobs still preempt at decode-step granularity — the
batch yields, the p0 job runs, then the batch resumes row-granularly
and still produces every output. This file asserts the user-visible
contract (p0 finishes first, p1 loses nothing) that holds either way."""

import time

from sutro_tpu.interfaces import JobStatus


def _wait(eng, job_id, *, until, timeout):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s = eng.job_status(job_id)
        if until(s):
            return s
        time.sleep(0.05)
    raise TimeoutError(
        f"job {job_id} stuck in {eng.job_status(job_id)}"
    )


def test_p0_preempts_running_p1(tiny_ecfg, tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine

    eng = LocalEngine(tiny_ecfg)
    p1 = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": [f"long batch row {i}" for i in range(12)],
            "sampling_params": {"max_new_tokens": 40},
            "job_priority": 1,
        }
    )
    _wait(eng, p1, until=lambda s: s == "RUNNING", timeout=90)

    p0 = eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": ["quick a", "quick b", "quick c"],
            "sampling_params": {"max_new_tokens": 4},
            "job_priority": 0,
        }
    )
    _wait(eng, p0, until=lambda s: JobStatus(s).is_terminal(), timeout=180)
    assert eng.job_status(p0) == "SUCCEEDED"
    # p0 finishing first proves interactive latency: same-model, so it
    # ATTACHED to p1's running session (co-batching) rather than
    # preempting it — p1 is still mid-run either way
    assert eng.job_status(p1) != "SUCCEEDED"

    _wait(eng, p1, until=lambda s: JobStatus(s).is_terminal(), timeout=300)
    assert eng.job_status(p1) == "SUCCEEDED"
    res1 = eng.job_results(p1)
    assert len(res1["outputs"]) == 12
    assert all(o is not None for o in res1["outputs"])
    res0 = eng.job_results(p0)
    assert len(res0["outputs"]) == 3
    assert all(o is not None for o in res0["outputs"])
