"""SLO enforcement control plane (engine/control.py): token-bucket
admission, the preemptive priority ladder, the closed-loop autotuner,
and the degradation contract — plus the structured INVALID_PRIORITY
rejection that replaced the old silent clamp (jobstore.check_quota).

Engine-level degradation under injected faults lives in test_chaos.py;
here the plane is driven directly so every policy branch is cheap and
deterministic."""

import dataclasses
import time
from types import SimpleNamespace

import numpy as np
import pytest

from sutro_tpu.engine import control as C
from sutro_tpu.engine import faults, softdeadline
from sutro_tpu.engine.config import EngineConfig


def _ecfg(**kw):
    base = dict(interactive_slots=1, decode_batch_size=64)
    base.update(kw)
    return SimpleNamespace(**base)


def _plane(spec="1", **kw):
    return C.ControlPlane(spec, ecfg=_ecfg(), **kw)


@pytest.fixture(autouse=True)
def _clean_faults_and_deadline(monkeypatch):
    monkeypatch.setattr(softdeadline, "_DEADLINE_AT", None)
    yield
    faults.clear()


# -- enablement rule ---------------------------------------------------


def test_resolve_spec_env_overrides_config(monkeypatch):
    monkeypatch.delenv("SUTRO_CONTROL", raising=False)
    assert C.resolve_spec(None) is None
    assert C.resolve_spec("off") is None
    assert C.resolve_spec("0") is None
    assert C.resolve_spec("1") == "1"
    assert C.resolve_spec("rows=5") == "rows=5"
    monkeypatch.setenv("SUTRO_CONTROL", "0")
    assert C.resolve_spec("1") is None  # env forces OFF
    monkeypatch.setenv("SUTRO_CONTROL", "rows=2")
    assert C.resolve_spec(None) == "rows=2"  # env forces ON


def test_parse_spec_defaults_and_kv():
    cfg = C.ControlConfig.parse("on")
    assert cfg.window_s == 60.0 and cfg.wait_s == 2.0
    cfg = C.ControlConfig.parse("rows=5,window=10,wait=0,sustain=3")
    assert cfg.rows == 5.0 and cfg.window_s == 10.0
    assert cfg.wait_s == 0.0 and cfg.sustain == 3
    with pytest.raises(ValueError, match="unknown control spec key"):
        C.ControlConfig.parse("bogus=1")
    with pytest.raises(ValueError, match="not k=v"):
        C.ControlConfig.parse("rows")


# -- token buckets -----------------------------------------------------


def test_token_bucket_take_refill_put():
    b = C.TokenBucket(10, window_s=10)  # 1 token/s
    t0 = 100.0
    assert b.try_take(10, t0)
    assert not b.try_take(1, t0)
    assert b.time_until(2, t0) == pytest.approx(2.0)
    assert b.time_until(11, t0) == float("inf")  # above capacity
    assert b.try_take(3, t0 + 3.0)  # refilled 3
    b.put(100)
    assert b.level == b.capacity  # put caps at capacity


def test_admit_batch_rejects_when_exhausted():
    p = _plane("rows=4,tokens=1000,wait=0,window=600")
    assert p.admit_batch("acme", 0, 4, 100.0, job_id="j1") is None
    assert p._drawn["j1"] == ("acme", 0, 4.0, 100.0)
    err = p.admit_batch("acme", 0, 4, 100.0, job_id="j2")
    assert err is not None and C.QUOTA_EXCEEDED in err
    assert "retry after" in err
    assert "j2" not in p._drawn
    assert p.snapshot()["rejections"] == 1


def test_admit_batch_bounded_wait_admits_after_refill():
    # capacity 4 per 0.4 s window -> 10 rows/s refill; draining then
    # asking for 2 more must block ~0.2 s inside the wait budget
    p = _plane("rows=4,tokens=1e9,wait=2,window=0.4")
    assert p.admit_batch("t", 0, 4, 1.0) is None
    t0 = time.monotonic()
    assert p.admit_batch("t", 0, 2, 1.0) is None
    assert time.monotonic() - t0 > 0.05


def test_admit_batch_need_above_capacity_rejects_immediately():
    p = _plane("rows=4,tokens=1e9,wait=5,window=60")
    t0 = time.monotonic()
    err = p.admit_batch("t", 0, 50, 1.0)
    assert err is not None and C.QUOTA_EXCEEDED in err
    assert time.monotonic() - t0 < 1.0  # inf wait: no pointless sleep


def test_wait_budget_respects_soft_deadline(monkeypatch):
    p = _plane("wait=10")
    assert p._wait_budget() == 10.0
    # armed deadline with guard headroom eaten: no waiting allowed
    monkeypatch.setattr(
        softdeadline, "_DEADLINE_AT",
        time.monotonic() + C.DEADLINE_GUARD_S - 1.0,
    )
    assert p._wait_budget() == 0.0


def test_tenant_and_priority_isolation():
    p = _plane("rows=2,tokens=1e9,wait=0,window=600")
    assert p.admit_batch("noisy", 0, 2, 1.0) is None
    assert p.admit_batch("noisy", 0, 1, 1.0) is not None  # exhausted
    # other tenant and other priority level are separate buckets
    assert p.admit_batch("victim", 0, 2, 1.0) is None
    assert p.admit_batch("noisy", 1, 2, 1.0) is None


def test_admit_interactive_immediate_429_no_wait():
    p = _plane("rows=1,tokens=1e9,wait=5,window=600")
    assert p.admit_interactive("t") is None
    t0 = time.monotonic()
    err = p.admit_interactive("t")
    assert err is not None and C.QUOTA_EXCEEDED in err
    assert time.monotonic() - t0 < 0.5  # never waits


def test_default_capacity_derives_from_quota_tables():
    p = _plane("1")  # no absolute rows/tokens -> quota / divisor
    from sutro_tpu.engine.jobstore import DEFAULT_QUOTAS

    b = p._bucket("t", 0)
    assert b["rows"].capacity == pytest.approx(
        max(1.0, DEFAULT_QUOTAS[0]["row_quota"] / 1000.0)
    )
    assert b["tokens"].capacity == pytest.approx(
        max(1.0, DEFAULT_QUOTAS[0]["token_quota"] / 1000.0)
    )


# -- terminal accounting ----------------------------------------------


def _rec(job_id, status, in_tok=0, out_tok=0):
    return SimpleNamespace(
        job_id=job_id, status=status,
        input_tokens=in_tok, output_tokens=out_tok,
    )


def test_on_terminal_refunds_token_overage():
    p = _plane("rows=10,tokens=1000,wait=0,window=600")
    assert p.admit_batch("t", 0, 2, 800.0, job_id="j") is None
    p.on_terminal(_rec("j", "SUCCEEDED", in_tok=100, out_tok=200))
    b = p._bucket("t", 0)
    # 800 reserved, 300 used -> 500 back: level 200 + 500 = 700
    assert b["tokens"].level == pytest.approx(700.0, abs=1.0)
    assert "j" not in p._drawn


def test_on_terminal_full_refund_for_job_that_never_ran():
    p = _plane("rows=10,tokens=1000,wait=0,window=600")
    assert p.admit_batch("t", 0, 4, 400.0, job_id="j") is None
    p.on_terminal(_rec("j", "FAILED"))
    b = p._bucket("t", 0)
    assert b["rows"].level == pytest.approx(10.0, abs=0.1)
    assert b["tokens"].level == pytest.approx(1000.0, abs=1.0)


# -- priority ladder ---------------------------------------------------


def _ctx(priority, seq, interactive=False):
    return SimpleNamespace(
        priority=priority, seq=seq, interactive=interactive
    )


def test_ladder_aging_promotes_waiting_job():
    p = _plane("aging=10")
    lad = p.ladder
    now = 1000.0
    old_p2, early_p0 = _ctx(2, 1), _ctx(0, 2)
    assert lad.effective_priority(old_p2, now) == 2
    # while the P2 job is young, an arriving P0 job outranks it
    assert lad.may_preempt(early_p0, old_p2, now)
    # 25 s later the P2 job has aged two levels (2 -> 0), so a NEWLY
    # arriving P0 flood can no longer preempt it
    late_p0 = _ctx(0, 3)
    assert lad.effective_priority(old_p2, now + 25) == 0
    assert not lad.may_preempt(late_p0, old_p2, now + 25)


def test_ladder_excludes_interactive_and_disabled_plane():
    p = _plane("1")
    lad = p.ladder
    assert not lad.may_preempt(_ctx(-1, 1), _ctx(1, 2), 0.0)
    assert not lad.may_preempt(_ctx(0, 1), _ctx(-1, 2), 0.0)
    assert lad.may_preempt(_ctx(0, 1), _ctx(1, 2), 0.0)
    p.enabled = False
    assert not lad.may_preempt(_ctx(0, 1), _ctx(1, 2), 0.0)
    assert not lad.active()


def test_ladder_deadline_veto(monkeypatch):
    p = _plane("1")
    assert p.ladder.may_preempt(_ctx(0, 1), _ctx(1, 2), 0.0)
    monkeypatch.setattr(
        softdeadline, "_DEADLINE_AT",
        time.monotonic() + C.DEADLINE_GUARD_S / 2,
    )
    assert not p.ladder.may_preempt(_ctx(0, 1), _ctx(1, 2), 0.0)


def test_ladder_forget_drops_aging_entry():
    p = _plane("1")
    ctx = _ctx(1, 7)
    p.ladder.effective_priority(ctx, 0.0)
    assert 7 in p.ladder._first_seen
    p.ladder.forget(ctx)
    assert 7 not in p.ladder._first_seen


def test_scheduler_priority_preemption_end_to_end(tiny_ecfg, byte_tok):
    """A P0 job attached mid-flight of a slot-saturating P1 job steals
    decode rows through the ladder (suspend/re-admit), finishes first,
    and the P1 job still completes EVERY row — preempted rows are
    re-queued, not lost."""
    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.engine.scheduler import ContinuousBatcher, JobCtx
    from sutro_tpu.models.configs import MODEL_CONFIGS
    from tests.conftest import make_requests

    runner = ModelRunner(MODEL_CONFIGS["tiny-dense"], tiny_ecfg)
    # no stop ids: every P1 row decodes its full 40 tokens, so the
    # batch stays saturated and a slot can ONLY come from preemption
    b = ContinuousBatcher(runner, stop_ids=())
    plane = C.ControlPlane("1", ecfg=dataclasses.replace(tiny_ecfg))
    b.ladder = plane.ladder

    got1, got0, done = {}, {}, []
    ctx1 = JobCtx(
        job_id="p1",
        pending=make_requests(
            byte_tok, [f"batch row {i}" for i in range(10)],
            max_new_tokens=40, temperature=0.0,
        ),
        on_result=lambda r: got1.__setitem__(r.row_id, r),
        priority=1, seq=0,
    )
    ctx0 = JobCtx(
        job_id="p0",
        pending=make_requests(
            byte_tok, ["quick a", "quick b"],
            max_new_tokens=4, temperature=0.0,
        ),
        on_result=lambda r: got0.__setitem__(r.row_id, r),
        priority=0, seq=1,
    )
    handed = []

    def poll_new():
        # attach only once EVERY slot is pinned by a decoding P1 row —
        # from then on a slot can only come from preemption (no stop
        # ids, so no row finishes before its 40-token budget)
        if (
            not handed
            and ctx1.stats["out"] >= 4
            and all(s is not None for s in b.slots)
        ):
            handed.append(True)
            return ctx0
        return None

    state = b.run_multi(
        [ctx1],
        on_job_done=lambda c, o: done.append((c.job_id, o)),
        poll_new=poll_new,
    )
    assert state == "completed"
    assert handed, "p0 was never attached"
    assert done[0] == ("p0", "completed")
    assert done[-1] == ("p1", "completed")
    assert len(got0) == 2 and len(got1) == 10  # zero lost rows
    # the ladder did its job: P1 decode rows were suspended (the
    # interactive path can't have done it — ctx0 is a plain batch job)
    assert ctx1.stats["preempted"] >= 1
    assert plane.snapshot()["preemptions"] == ctx1.stats["preempted"]
    # aging entries cleaned up at job finish
    assert plane.ladder._first_seen == {}


def test_scheduler_ladder_none_is_stock_path(tiny_runner, byte_tok):
    """Control off: batcher.ladder stays None and outputs are
    bit-identical to the pre-control scheduler (greedy oracle)."""
    from sutro_tpu.engine.scheduler import ContinuousBatcher
    from tests.conftest import make_requests

    texts = [f"det row {i}" for i in range(6)]
    outs = []
    for _ in range(2):
        b = ContinuousBatcher(tiny_runner, stop_ids=byte_tok.stop_ids())
        assert b.ladder is None
        res = {}
        b.run(
            make_requests(byte_tok, texts, max_new_tokens=8,
                          temperature=0.0),
            on_result=lambda r: res.__setitem__(r.row_id, r),
        )
        outs.append({i: r.token_ids for i, r in res.items()})
    assert outs[0] == outs[1]


# -- autotuner ---------------------------------------------------------


def _tick(p, verdict=None, firing=()):
    verdicts = (
        {"job-x": {"verdict": verdict}} if verdict else None
    )
    p.on_monitor_tick({}, [], verdicts, list(firing))


def test_autotuner_starved_grows_slots_with_hysteresis():
    p = _plane("sustain=2,cooldown=2,settle=3")
    e = p.ecfg
    _tick(p, verdict="interactive_starved")
    assert e.interactive_slots == 1  # one tick is not sustained
    _tick(p, verdict="interactive_starved")
    assert e.interactive_slots == 2  # acted, audit + cooldown set
    _tick(p, verdict="interactive_starved")
    _tick(p, verdict="interactive_starved")
    assert e.interactive_slots == 2  # cooldown holds
    audit = p.snapshot()["autotune"]["audit"]
    assert audit[-1]["knob"] == "interactive_slots"
    assert (audit[-1]["from"], audit[-1]["to"]) == (1, 2)
    assert audit[-1]["reason"] == "interactive_starved"
    # quiet spell: settle walks back toward baseline
    for _ in range(6):
        _tick(p)
    assert e.interactive_slots == 1


def test_autotuner_firing_rule_counts_as_starvation():
    """The monitor's stock interactive_ttft_p99 rule (no doctor needed)
    drives the same actuator."""
    p = _plane("sustain=1,cooldown=0")
    _tick(p, firing=["interactive_ttft_p99"])
    assert p.ecfg.interactive_slots == 2


def test_autotuner_slots_bounded_by_boost():
    p = _plane("sustain=1,cooldown=0,slots_boost=2")
    for _ in range(10):
        _tick(p, verdict="interactive_starved")
    assert p.ecfg.interactive_slots == 1 + 2  # base + slots_boost cap


def test_autotuner_roofline_grows_batch_hostbound_shrinks():
    p = _plane("sustain=1,cooldown=0")
    e = p.ecfg
    _tick(p, verdict="decode_below_roofline")
    assert e.decode_batch_size == 64 + 16  # step = base // 4
    for _ in range(20):
        _tick(p, verdict="decode_below_roofline")
    assert e.decode_batch_size == 128  # bounded at 2 * baseline
    # host-bound outranks roofline and walks it back down
    for _ in range(20):
        _tick(p, verdict="host_bound_admit")
    assert e.decode_batch_size == 8  # floor


def test_autotuner_counts_reset_when_signal_clears():
    p = _plane("sustain=2,cooldown=0")
    _tick(p, verdict="interactive_starved")
    _tick(p)  # gap resets the sustain counter
    _tick(p, verdict="interactive_starved")
    assert p.ecfg.interactive_slots == 1


# -- degradation contract ---------------------------------------------


def test_admit_fault_degrades_to_pass_through():
    p = _plane("rows=1,tokens=1,wait=0,window=600")
    assert p.admit_batch("t", 0, 1, 1.0) is None
    faults.configure("control.admit:error")
    # bucket is EMPTY, but the controller fault must admit, not reject
    assert p.admit_batch("t", 0, 50, 1e9) is None
    assert p.enabled is False
    assert "control.admit" in p.degraded_reason
    faults.clear()
    # stays pass-through: no recovery, no rejections, ladder off
    assert p.admit_batch("t", 0, 50, 1e9) is None
    assert p.admit_interactive("t") is None
    assert not p.ladder.active()
    assert p.snapshot()["enabled"] is False


def test_actuate_fault_degrades_to_pass_through():
    p = _plane("sustain=1,cooldown=0")
    faults.configure("control.actuate:error")
    _tick(p, verdict="interactive_starved")
    assert p.enabled is False
    assert "control.actuate" in p.degraded_reason
    faults.clear()
    _tick(p, verdict="interactive_starved")
    assert p.ecfg.interactive_slots == 1  # autotuner is off


def test_degrade_writes_failure_log_trail():
    logs = {}

    class Jobs:
        def append_failure_log(self, job_id, event):
            logs.setdefault(job_id, []).append(event)

    p = C.ControlPlane(
        "1", ecfg=_ecfg(), jobs=Jobs(),
        jobs_provider=lambda: [("job-running", "RUNNING")],
    )
    faults.configure("control.admit:error")
    assert p.admit_batch("t", 0, 1, 1.0, job_id="job-new") is None
    assert [e["event"] for e in logs["job-new"]] == ["control_degraded"]
    assert [e["event"] for e in logs["job-running"]] == ["control_degraded"]
    assert logs["job-new"][0]["site"] == "control.admit"


# -- monitor hook ------------------------------------------------------


def test_monitor_on_tick_hook_fires_and_unhooks_on_error():
    from sutro_tpu.telemetry.monitor import Monitor

    m = Monitor(interval_s=3600)
    calls = []
    m.on_tick = lambda stats, trans, verdicts, firing: calls.append(
        (stats, trans, verdicts, firing)
    )
    m.tick()
    assert len(calls) == 1
    stats, trans, verdicts, firing = calls[0]
    assert isinstance(stats, dict) and isinstance(firing, list)

    def boom(*a):
        raise RuntimeError("controller crashed")

    m.on_tick = boom
    m.tick()  # must not raise
    assert m.on_tick is None  # crashing hook is unhooked
    m.tick()


# -- structured INVALID_PRIORITY (was: silent clamp) -------------------


def test_jobstore_invalid_priority_rejected_not_clamped(tmp_path):
    from sutro_tpu.engine.jobstore import InvalidPriority, JobStore

    js = JobStore(root=tmp_path)
    n = len(js.get_quotas())
    assert js.validate_priority(0) == 0
    assert js.validate_priority(n - 1) == n - 1
    for bad in (-1, n, 99, "x", None, 2.5):
        with pytest.raises(InvalidPriority) as ei:
            js.validate_priority(bad)
        assert ei.value.status == 400
        assert ei.value.code == "INVALID_PRIORITY"
    # check_quota no longer clamps out-of-range priorities silently
    with pytest.raises(InvalidPriority):
        js.check_quota(99, 1, 1)
    err = js.check_quota(0, 10**9, 0)
    assert err and "quota" in err  # in-range behavior unchanged


# ------------------------------------------------ kv_tier_host_pages knob


class _FakeTierPool:
    def __init__(self):
        self.calls = []

    def set_host_budget(self, pages):
        self.calls.append(pages)
        return pages


def test_autotuner_kv_pressure_grows_host_tier_then_settles():
    pool = _FakeTierPool()
    p = C.ControlPlane(
        "sustain=2,cooldown=0,settle=2",
        ecfg=_ecfg(kv_tier_host_pages=1024),
        tier_pools=lambda: [pool],
    )
    e = p.ecfg
    _tick(p, verdict="kv_pressure")
    assert e.kv_tier_host_pages == 1024  # one tick is not sustained
    _tick(p, verdict="kv_pressure")
    assert e.kv_tier_host_pages == 1280  # +max(256, base // 4)
    assert pool.calls == [1280]  # pushed to the live pool
    audit = p.snapshot()["autotune"]["audit"]
    assert audit[-1]["knob"] == "kv_tier_host_pages"
    assert (audit[-1]["from"], audit[-1]["to"]) == (1024, 1280)
    assert audit[-1]["reason"] == "kv_pressure"
    # quiet spell: settle walks the budget back toward baseline
    _tick(p)
    _tick(p)
    assert e.kv_tier_host_pages == 1024
    assert pool.calls == [1280, 1024]


def test_autotuner_kv_host_pages_capped_at_4x_baseline():
    pool = _FakeTierPool()
    p = C.ControlPlane(
        "sustain=1,cooldown=0,settle=99",
        ecfg=_ecfg(kv_tier_host_pages=256),
        tier_pools=lambda: [pool],
    )
    for _ in range(16):
        _tick(p, verdict="kv_pressure")
    assert p.ecfg.kv_tier_host_pages == 4 * 256
    assert max(pool.calls) == 4 * 256


def test_autotuner_kv_push_failure_degrades_to_pass_through():
    class _Wedged:
        def set_host_budget(self, pages):
            raise RuntimeError("pool wedged")

    p = C.ControlPlane(
        "sustain=1,cooldown=0",
        ecfg=_ecfg(kv_tier_host_pages=512),
        tier_pools=lambda: [_Wedged()],
    )
    _tick(p, verdict="kv_pressure")
    assert not p.enabled
    assert "control.actuate" in p.degraded_reason
