"""Row-granular resume (SURVEY §5.3): cancelled/failed jobs re-queue and
skip rows already flushed to the partial store; a fresh engine process
resumes a job orphaned by a dead predecessor."""

import time

import pytest

from sutro_tpu.engine.api import LocalEngine
from sutro_tpu.interfaces import JobStatus


def _wait_terminal(eng, job_id, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if JobStatus(eng.job_status(job_id)).is_terminal():
            return JobStatus(eng.job_status(job_id))
        time.sleep(0.1)
    raise TimeoutError(job_id)


@pytest.fixture()
def eng(tiny_ecfg, tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    return LocalEngine(tiny_ecfg)


def test_resume_cancelled_job_skips_done_rows(eng):
    job_id = eng.submit_batch_inference(
        {"model": "tiny-dense", "inputs": [f"row {i}" for i in range(12)],
         "sampling_params": {"max_new_tokens": 100}}
    )
    # let at least one row finish, then cancel mid-run
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if eng.metrics.job(job_id).rows_completed >= 1:
            break
        time.sleep(0.05)
    eng.cancel_job(job_id)
    status = _wait_terminal(eng, job_id)
    if status == JobStatus.SUCCEEDED:
        pytest.skip("job raced to completion before cancel")
    # CANCELLING is terminal (reference semantics); the worker flips it
    # to CANCELLED once the batcher drains
    deadline = time.monotonic() + 60
    while (
        eng.job_status(job_id) == JobStatus.CANCELLING.value
        and time.monotonic() < deadline
    ):
        time.sleep(0.1)
    assert eng.job_status(job_id) == JobStatus.CANCELLED.value

    out = eng.resume_job(job_id)
    assert out["resumed"] is True
    # resume must SKIP the rows that already flushed before the cancel,
    # not regenerate them — prove it, don't assume it
    assert out["rows_already_done"] >= 1
    assert _wait_terminal(eng, job_id) == JobStatus.SUCCEEDED
    res = eng.job_results(job_id)
    # 12 rows in -> 12 ordered outputs (reference 1:1 contract)
    assert len(res["outputs"]) == 12
    assert all(o is not None for o in res["outputs"])


def test_resume_refuses_succeeded_and_active(eng):
    job_id = eng.submit_batch_inference(
        {"model": "tiny-dense", "inputs": ["a"],
         "sampling_params": {"max_new_tokens": 3}}
    )
    _wait_terminal(eng, job_id)
    out = eng.resume_job(job_id)
    assert out["resumed"] is False and "succeeded" in out["detail"]


def test_orphaned_running_job_resumes_in_fresh_engine(
    tiny_ecfg, tmp_path, monkeypatch
):
    """Simulate a daemon crash: job record says RUNNING, no worker owns
    it. A new engine process must be able to resume it."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    eng1 = LocalEngine(tiny_ecfg)
    job_id = eng1.submit_batch_inference(
        {"model": "tiny-dense", "inputs": ["x", "y"],
         "sampling_params": {"max_new_tokens": 3}}
    )
    _wait_terminal(eng1, job_id)
    # forge the crash: flip the durable record back to RUNNING and delete
    # the results file, as if the process died mid-job
    eng1.jobs.set_status(job_id, JobStatus.RUNNING)
    (eng1.jobs._dir(job_id) / "results.parquet").unlink()

    eng2 = LocalEngine(tiny_ecfg)  # fresh "process" over the same store
    out = eng2.resume_job(job_id)
    assert out["resumed"] is True
    assert _wait_terminal(eng2, job_id) == JobStatus.SUCCEEDED
    assert len(eng2.job_results(job_id)["outputs"]) == 2


def test_resume_skips_partial_rows_deterministically(
    tiny_ecfg, tmp_path, monkeypatch
):
    """Forge a FAILED job with one row already in the partial store: the
    resumed run must keep that row's output verbatim (it is skipped, not
    recomputed) and generate the rest."""
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    eng = LocalEngine(tiny_ecfg)
    job_id = eng.jobs.create(
        model="tiny-dense", engine_key="tiny-dense", num_rows=3,
        job_priority=0,
        sampling_params={"max_new_tokens": 4},
    ).job_id
    eng.jobs.write_inputs(job_id, ["a", "b", "c"])
    sentinel = "PRECOMPUTED-ROW-1"
    eng.jobs.flush_partial(
        job_id,
        [{"row_id": 1, "outputs": sentinel, "cumulative_logprobs": -1.0,
          "finish_reason": "stop"}],
    )
    eng.jobs.set_status(
        job_id, JobStatus.FAILED,
        failure_reason={"message": "simulated preemption"},
    )

    out = eng.resume_job(job_id)
    assert out["resumed"] is True and out["rows_already_done"] == 1
    assert _wait_terminal(eng, job_id) == JobStatus.SUCCEEDED
    res = eng.job_results(job_id)
    assert res["outputs"][1] == sentinel
    assert res["outputs"][0] is not None and res["outputs"][2] is not None
