"""Checkpoint loader round-trips over synthetic HF-layout safetensors.

Builds tiny checkpoints in the exact on-disk layouts HuggingFace ships
(per-expert Qwen-MoE layout vs gpt-oss fused+interleaved gate_up layout
with biases) and asserts the engine pytree comes back with the right
shapes, transposes, and bias splits."""

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.models.configs import MODEL_CONFIGS

safetensors_np = pytest.importorskip("safetensors.numpy")


def _save(tmp_path, tensors):
    safetensors_np.save_file(
        {k: v.astype(np.float32) for k, v in tensors.items()},
        str(tmp_path / "model.safetensors"),
    )


def _common_tensors(cfg, rng):
    t = {
        "model.embed_tokens.weight": rng.standard_normal(
            (cfg.vocab_size, cfg.hidden_size)
        ),
        "model.norm.weight": np.ones(cfg.hidden_size),
    }
    if not cfg.tie_embeddings:
        t["lm_head.weight"] = rng.standard_normal(
            (cfg.vocab_size, cfg.hidden_size)
        )
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        t[p + "input_layernorm.weight"] = np.ones(cfg.hidden_size)
        t[p + "post_attention_layernorm.weight"] = np.ones(cfg.hidden_size)
        t[p + "self_attn.q_proj.weight"] = rng.standard_normal(
            (cfg.q_size, cfg.hidden_size)
        )
        t[p + "self_attn.k_proj.weight"] = rng.standard_normal(
            (cfg.kv_size, cfg.hidden_size)
        )
        t[p + "self_attn.v_proj.weight"] = rng.standard_normal(
            (cfg.kv_size, cfg.hidden_size)
        )
        t[p + "self_attn.o_proj.weight"] = rng.standard_normal(
            (cfg.hidden_size, cfg.q_size)
        )
        if cfg.qk_norm:
            t[p + "self_attn.q_norm.weight"] = np.ones(cfg.head_dim)
            t[p + "self_attn.k_norm.weight"] = np.ones(cfg.head_dim)
        if cfg.attention_sink:
            t[p + "self_attn.sinks"] = rng.standard_normal(cfg.num_heads)
    return t


def test_dense_roundtrip(tmp_path):
    from sutro_tpu.engine.weights import load_checkpoint

    cfg = MODEL_CONFIGS["tiny-dense"]
    rng = np.random.default_rng(0)
    t = _common_tensors(cfg, rng)
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}.mlp."
        t[p + "gate_proj.weight"] = rng.standard_normal(
            (cfg.intermediate_size, cfg.hidden_size)
        )
        t[p + "up_proj.weight"] = rng.standard_normal(
            (cfg.intermediate_size, cfg.hidden_size)
        )
        t[p + "down_proj.weight"] = rng.standard_normal(
            (cfg.hidden_size, cfg.intermediate_size)
        )
    _save(tmp_path, t)

    params = load_checkpoint(str(tmp_path), cfg, EngineConfig(param_dtype="float32"))
    # HF [out, in] -> engine [in, out]
    np.testing.assert_allclose(
        np.asarray(params["layers"]["wq"][0]),
        t["model.layers.0.self_attn.q_proj.weight"].T,
        rtol=1e-6,
    )
    assert params["layers"]["w_gate"].shape == (
        cfg.num_layers, cfg.hidden_size, cfg.intermediate_size,
    )
    assert "lm_head" not in params  # tied embeddings


def test_gpt_oss_fused_layout_with_biases(tmp_path):
    """The fused gate_up_proj interleaves gate/up on the last axis; biases
    ship per expert and must be split the same way (code-review
    regression: biases were silently dropped)."""
    from sutro_tpu.engine.weights import load_checkpoint

    cfg = MODEL_CONFIGS["tiny-oss"]
    E, H, F = cfg.moe_experts, cfg.hidden_size, cfg.moe_intermediate_size
    rng = np.random.default_rng(1)
    t = _common_tensors(cfg, rng)
    gate = rng.standard_normal((cfg.num_layers, E, H, F))
    up = rng.standard_normal((cfg.num_layers, E, H, F))
    gate_b = rng.standard_normal((cfg.num_layers, E, F))
    up_b = rng.standard_normal((cfg.num_layers, E, F))
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}.mlp."
        fused = np.empty((E, H, 2 * F))
        fused[..., 0::2] = gate[i]
        fused[..., 1::2] = up[i]
        fused_b = np.empty((E, 2 * F))
        fused_b[..., 0::2] = gate_b[i]
        fused_b[..., 1::2] = up_b[i]
        t[p + "router.weight"] = rng.standard_normal((E, H))
        t[p + "router.bias"] = rng.standard_normal(E)
        t[p + "experts.gate_up_proj"] = fused
        t[p + "experts.gate_up_proj_bias"] = fused_b
        t[p + "experts.down_proj"] = rng.standard_normal((E, F, H))
        t[p + "experts.down_proj_bias"] = rng.standard_normal((E, H))
    _save(tmp_path, t)

    params = load_checkpoint(str(tmp_path), cfg, EngineConfig(param_dtype="float32"))
    lp = params["layers"]
    np.testing.assert_allclose(np.asarray(lp["we_gate"]), gate, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(lp["we_up"]), up, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(lp["we_gate_b"]), gate_b, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(lp["we_up_b"]), up_b, rtol=1e-6)
    assert lp["router_b"].shape == (cfg.num_layers, E)
    assert lp["we_down_b"].shape == (cfg.num_layers, E, H)

    # loaded params must run through the forward (bias keys line up with
    # what _mlp consumes)
    import jax.numpy as jnp

    from sutro_tpu.models import transformer

    ids = jnp.zeros((1, 4), jnp.int32)
    pos = jnp.arange(4, dtype=jnp.int32)[None]
    logits, _, _ = transformer.forward(
        cfg, params, ids, pos, jnp.asarray([4], jnp.int32)
    )
    assert np.isfinite(np.asarray(logits)).all()
