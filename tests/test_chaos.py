"""Seeded chaos suite: the automated CRASH_MATRIX (FAILURES.md).

Every scenario injects a deterministic fault plan (engine/faults.py)
into a REAL engine run and asserts three things the ROADMAP's scale
story needs: (1) the job reaches a terminal state within a wall-clock
bound — no hangs; (2) the partial store stays consistent (no duplicate
or dropped rows); (3) after clearing the plan, ``resume_job`` completes
the remainder and the surviving rows are bit-identical to an uninjected
run (greedy decode is row-deterministic regardless of batch
composition, proven by test_dphost's cross-process equality).

The dp-channel scenarios run the coordinator/worker in-process (same
harness as tests/test_dphost.py's channel tests).
"""

import socket
import threading
import time

import pytest

from sutro_tpu.engine import faults
from sutro_tpu.engine.api import LocalEngine
from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.interfaces import JobStatus

from tests.conftest import free_low_port as _free_port

TERMINAL_BOUND_S = 180  # every scenario must reach terminal within this


def _wait_terminal(eng, job_id, timeout=TERMINAL_BOUND_S):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = JobStatus(eng.job_status(job_id))
        if st.is_terminal() and st != JobStatus.CANCELLING:
            return st
        time.sleep(0.05)
    raise TimeoutError(f"{job_id} not terminal within {timeout}s")


@pytest.fixture()
def mkengine(tmp_path, monkeypatch):
    """Factory for fresh engines over fresh SUTRO_HOMEs. Each call may
    carry its own fault plan (installed at engine construction); the
    global plan is cleared afterwards so no fault leaks across tests."""
    engines = []
    counter = iter(range(100))

    def make(plan=None, row_retries=2, **kw):
        home = tmp_path / f"home{next(counter)}"
        home.mkdir()
        monkeypatch.setenv("SUTRO_HOME", str(home))
        base = dict(
            kv_page_size=8,
            max_pages_per_seq=16,
            decode_batch_size=4,
            max_model_len=128,
            use_pallas=False,
            param_dtype="float32",
            activation_dtype="float32",
            fault_plan=plan,
            row_retries=row_retries,
            io_retries=3,
            io_backoff_base=0.01,
            io_backoff_cap=0.05,
        )
        base.update(kw)
        eng = LocalEngine(EngineConfig(**base))
        engines.append(eng)
        return eng

    yield make
    faults.clear()
    for e in engines:
        e.close(timeout=5)


def _submit(eng, n_rows=12, max_new=5, schema=None, prio=0):
    payload = {
        "model": "tiny-dense",
        "inputs": [f"chaos row {i}" for i in range(n_rows)],
        "sampling_params": {
            "max_new_tokens": max_new,
            "temperature": 0.0,  # greedy => row-deterministic outputs
        },
        "job_priority": prio,
    }
    if schema is not None:
        payload["output_schema"] = schema
    return eng.submit_batch_inference(payload)


def _reference_outputs(mkengine, n_rows=12, max_new=5, schema=None):
    """Uninjected run over the same inputs: the bit-identity oracle."""
    eng = mkengine(plan=None)
    jid = _submit(eng, n_rows=n_rows, max_new=max_new, schema=schema)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    return eng.job_results(jid)["outputs"]


def _assert_no_dup_no_drop(eng, jid, n_rows):
    df = eng.jobs.read_results(jid)
    assert sorted(df["row_id"].tolist()) == list(range(n_rows))


# ---------------------------------------------------------------------------
# row-level failure domains
# ---------------------------------------------------------------------------


def test_poison_row_quarantined_job_succeeds(mkengine):
    """Scenario 1: a row that fails EVERY decode attempt is retried
    row_retries times, then quarantined — the job still SUCCEEDs with
    N-1 good rows + 1 error row, all recorded in failure_log[]."""
    n = 12
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(plan="row.decode:error:rows=3", row_retries=2)
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    res = eng.job_results(jid)
    assert len(res["outputs"]) == n
    assert res["outputs"][3] is None
    assert res["errors"][3] and "injected fault" in res["errors"][3]
    # every OTHER row is bit-identical to the uninjected run
    for i in range(n):
        if i != 3:
            assert res["outputs"][i] == ref[i], f"row {i} diverged"
    log = eng.jobs.get(jid).failure_log or []
    retries = [e for e in log if e["event"] == "row_retry"]
    quar = [e for e in log if e["event"] == "row_quarantined"]
    assert len(retries) == 2  # row_retries attempts before giving up
    assert [e["row_id"] for e in quar] == [3]
    _assert_no_dup_no_drop(eng, jid, n)


def test_transient_row_fault_retried_to_success(mkengine):
    """Scenario 2: a fault that fires ONCE costs one retry, zero rows —
    outputs are bit-identical to the uninjected run on every row."""
    n = 12
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(plan="row.decode:error:rows=2,times=1", row_retries=2)
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    res = eng.job_results(jid)
    assert res["outputs"] == ref
    assert "errors" not in res
    log = eng.jobs.get(jid).failure_log or []
    assert [e["event"] for e in log] == ["row_retry"]
    assert log[0]["row_id"] == 2


def test_constraint_compile_poison_row(mkengine):
    """Scenario 3: a per-row constraint-compile failure quarantines the
    row at admission; schema rows around it still emit valid JSON."""
    import json

    schema = {
        "type": "object",
        "properties": {"label": {"type": "string", "maxLength": 6}},
        "required": ["label"],
        "additionalProperties": False,
    }
    n = 6
    eng = mkengine(plan="constrain.compile:error:rows=1", row_retries=1)
    jid = _submit(eng, n_rows=n, max_new=40, schema=schema)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    res = eng.job_results(jid)
    assert res["outputs"][1] is None
    assert res["errors"][1]
    for i in range(n):
        if i != 1:
            json.loads(res["outputs"][i])  # schema guarantee holds
    log = eng.jobs.get(jid).failure_log or []
    assert any(
        e["event"] == "row_quarantined" and e["row_id"] == 1 for e in log
    )


def test_tokenizer_encode_poison_row(mkengine):
    """Scenario 4: a row whose tokenize raises never reaches the
    scheduler — quarantined up front, the rest of the job unharmed."""
    n = 8
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(plan="tokenizer.encode:error:rows=0")
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    res = eng.job_results(jid)
    assert res["outputs"][0] is None and res["errors"][0]
    assert res["outputs"][1:] == ref[1:]


def test_single_poison_row_in_256_row_job(mkengine):
    """Acceptance criterion verbatim: one poison row in a 256-row job
    yields SUCCEEDED with 255 good rows + 1 error-column row, with the
    quarantine recorded in failure_log[]."""
    n = 256
    eng = mkengine(
        plan="row.decode:error:rows=77",
        row_retries=1,
        decode_batch_size=8,
    )
    jid = _submit(eng, n_rows=n, max_new=4)
    assert _wait_terminal(eng, jid, timeout=600) == JobStatus.SUCCEEDED
    res = eng.job_results(jid)
    assert len(res["outputs"]) == n
    good = [o for i, o in enumerate(res["outputs"]) if i != 77]
    assert all(o is not None for o in good) and len(good) == n - 1
    assert res["outputs"][77] is None
    assert res["errors"][77]
    log = eng.jobs.get(jid).failure_log or []
    assert any(
        e["event"] == "row_quarantined" and e["row_id"] == 77
        for e in log
    )
    _assert_no_dup_no_drop(eng, jid, n)


# ---------------------------------------------------------------------------
# jobstore transient / torn I/O
# ---------------------------------------------------------------------------


def test_flush_transient_ioerror_retried(mkengine):
    """Scenario 5: two transient flush failures are retried with
    backoff and logged; the job completes with every row intact."""
    n = 12
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(plan="jobstore.flush_partial:ioerror:times=2")
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    assert eng.job_results(jid)["outputs"] == ref
    log = eng.jobs.get(jid).failure_log or []
    io = [e for e in log if e["event"] == "io_retry"]
    assert len(io) == 2
    assert all(e["site"] == "jobstore.flush_partial" for e in io)


def test_flush_persistent_ioerror_fails_then_resumes(mkengine):
    """Scenario 6: a PERSISTENT store fault exhausts the bounded
    retries and fails the job (no hang) — then a resume with the fault
    cleared completes, bit-identical to an uninjected run."""
    n = 12
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(plan="jobstore.flush_partial:ioerror")
    jid = _submit(eng, n_rows=n)
    t0 = time.monotonic()
    assert _wait_terminal(eng, jid) == JobStatus.FAILED
    assert time.monotonic() - t0 < TERMINAL_BOUND_S
    rec = eng.jobs.get(jid)
    assert "injected ioerror" in rec.failure_reason["message"]
    assert any(
        e["event"] == "job_failed" for e in rec.failure_log or []
    )
    faults.clear()
    out = eng.resume_job(jid)
    assert out["resumed"] is True
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    assert eng.job_results(jid)["outputs"] == ref
    _assert_no_dup_no_drop(eng, jid, n)


def test_torn_chunk_quarantined_and_store_readable(mkengine):
    """Scenario 7: a crash mid-flush leaves a torn chunk at its final
    name. Reads skip + quarantine it to partial/.corrupt/ and the job
    still finishes with full results (the retry landed a good copy)."""
    n = 12
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(plan="jobstore.flush_partial:torn:times=1")
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    assert eng.job_results(jid)["outputs"] == ref
    log = eng.jobs.get(jid).failure_log or []
    assert any(e["event"] == "io_retry" for e in log)
    assert any(
        e["event"] == "torn_chunk_quarantined" for e in log
    )
    corrupt = eng.jobs._partial_dir(jid) / ".corrupt"
    assert corrupt.is_dir() and any(corrupt.iterdir())


def test_torn_chunk_direct_store_read(mkengine):
    """Satellite unit check: garbage bytes under a chunk name must not
    break read_partial_meta/read_partial — skip, quarantine, log."""
    eng = mkengine()
    rec = eng.jobs.create(
        model="tiny-dense", engine_key="tiny-dense", num_rows=2
    )
    eng.jobs.flush_partial(
        rec.job_id,
        [{"row_id": 0, "outputs": "ok", "cumulative_logprobs": 0.0,
          "gen_tokens": 1, "finish_reason": "stop"}],
    )
    bad = eng.jobs._partial_dir(rec.job_id) / "b00000000-s00000099.parquet"
    bad.write_bytes(b"PAR1 this is not a parquet file")
    meta = eng.jobs.read_partial_meta(rec.job_id)
    assert meta == {0: "stop"}
    assert not bad.exists()  # moved to .corrupt/
    assert (bad.parent / ".corrupt" / bad.name).exists()
    # second read: quarantine is idempotent, store still clean
    assert eng.jobs.read_partial(rec.job_id).keys() == {0}
    log = eng.jobs.get(rec.job_id).failure_log or []
    assert any(e["event"] == "torn_chunk_quarantined" for e in log)


# ---------------------------------------------------------------------------
# device-level faults + resume bit-identity
# ---------------------------------------------------------------------------


def test_decode_oom_fails_job_then_resume_bit_identical(mkengine):
    """Scenario 8: a simulated device OOM mid-decode fails the job
    resumably; rows flushed before the fault are NOT regenerated, and
    post-resume results equal an uninjected run bit for bit."""
    n = 12
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(plan="runner.decode:oom:nth=2,times=1")
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.FAILED
    rec = eng.jobs.get(jid)
    assert "RESOURCE_EXHAUSTED" in rec.failure_reason["message"]
    assert any(
        e["event"] == "job_failed" and "RESOURCE_EXHAUSTED" in e["error"]
        for e in rec.failure_log or []
    )
    faults.clear()
    out = eng.resume_job(jid)
    assert out["resumed"] is True
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    assert eng.job_results(jid)["outputs"] == ref
    _assert_no_dup_no_drop(eng, jid, n)


def test_prefill_error_fails_job_then_resume(mkengine):
    """Scenario 9: same contract for a prefill-time device error."""
    n = 8
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(plan="runner.prefill:error:nth=1,times=1")
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.FAILED
    faults.clear()
    assert eng.resume_job(jid)["resumed"] is True
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    assert eng.job_results(jid)["outputs"] == ref


def test_crash_mid_finalize_resume_no_dup_no_drop(mkengine):
    """Scenario 10 (satellite): kill between the last partial flush and
    the results merge — record says RUNNING, partial store complete, no
    results.parquet. Resume must neither duplicate nor drop rows and
    reproduce the pre-crash outputs exactly."""
    n = 10
    eng = mkengine()
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    before = eng.job_results(jid)["outputs"]
    # forge the crash point: results gone, status frozen mid-job
    (eng.jobs._dir(jid) / "results.parquet").unlink()
    eng.jobs.set_status(jid, JobStatus.RUNNING)
    out = eng.resume_job(jid)
    assert out["resumed"] is True
    assert out["rows_already_done"] == n  # nothing regenerates
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    after = eng.job_results(jid)["outputs"]
    assert after == before
    _assert_no_dup_no_drop(eng, jid, n)


# ---------------------------------------------------------------------------
# dp channel liveness (in-process coordinator/worker harness)
# ---------------------------------------------------------------------------


def _world(port):
    from sutro_tpu.engine.dphost import DPWorld

    return (
        DPWorld(rank=0, world=2, host="127.0.0.1", port=port),
        DPWorld(rank=1, world=2, host="127.0.0.1", port=port),
    )


def _reqs(n):
    import numpy as np

    from sutro_tpu.engine.scheduler import GenRequest

    return [
        GenRequest(row_id=i, prompt_ids=np.array([1, 2], np.int32))
        for i in range(n)
    ]


def _res(row_id):
    from sutro_tpu.engine.scheduler import GenResult

    return GenResult(
        row_id=row_id, token_ids=[7], cumulative_logprob=0.0,
        finish_reason="stop", input_tokens=2,
    )


def test_dp_worker_hang_fails_round_in_bounded_time(monkeypatch):
    """Scenario 11: a worker that hangs before ``done`` (heartbeat
    silenced, as a truly hung process would be) is declared stalled by
    the coordinator's watchdog within the stall bound — DURING the
    round, partials intact for resume."""
    from sutro_tpu.engine.dphost import (
        run_dp_coordinator, run_dp_worker, shard_requests,
    )

    monkeypatch.setenv("SUTRO_DP_STALL_TIMEOUT", "1")
    monkeypatch.setenv("SUTRO_DP_HEARTBEAT", "0.2")
    faults.configure("dphost.worker_done:hang:delay=30")
    try:
        port = _free_port()
        cw, ww = _world(port)
        reqs = _reqs(4)

        def shard_fn(shard, on_result, on_progress, should_cancel):
            for q in shard:
                on_result(_res(q.row_id))
            return "completed"

        t = threading.Thread(
            target=lambda: run_dp_worker(
                ww, shard_fn, shard_requests(reqs, 1, 2)
            ),
            daemon=True,  # hangs by design; the coordinator must not
        )
        t.start()
        merged = {}
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="stalled"):
            run_dp_coordinator(
                cw, shard_fn, shard_requests(reqs, 0, 2),
                on_result=lambda r: merged.__setitem__(r.row_id, r),
            )
        assert time.monotonic() - t0 < 30  # stall bound, not accept bound
        # the coordinator's shard landed before the failure: partials
        # stay for row-granular resume
        assert set(merged) >= {0, 2}
    finally:
        faults.clear()


def test_dp_worker_crash_before_done_detected(monkeypatch):
    """Scenario 12: a worker that dies without ``done`` (hard crash, no
    err message) fails the round with a connection-loss error."""
    from sutro_tpu.engine.dphost import (
        run_dp_coordinator, run_dp_worker, shard_requests,
    )

    faults.configure("dphost.worker_done:crash")
    try:
        port = _free_port()
        cw, ww = _world(port)
        reqs = _reqs(4)

        def shard_fn(shard, on_result, on_progress, should_cancel):
            for q in shard:
                on_result(_res(q.row_id))
            return "completed"

        def worker_main():
            try:
                run_dp_worker(ww, shard_fn, shard_requests(reqs, 1, 2))
            except Exception:
                pass  # the injected crash re-raises locally too

        t = threading.Thread(target=worker_main, daemon=True)
        t.start()
        with pytest.raises(
            RuntimeError,
            match="connection lost|disconnected before done",
        ):
            run_dp_coordinator(
                cw, shard_fn, shard_requests(reqs, 0, 2),
                on_result=lambda r: None,
            )
        t.join(timeout=60)
    finally:
        faults.clear()


def test_truncated_frame_surfaced_not_swallowed():
    """Scenario 13 (satellite): a connection dropped MID-FRAME raises
    TruncatedFrameError instead of silently discarding the tail."""
    from sutro_tpu.engine.dphost import TruncatedFrameError, _recv_lines

    a, b = socket.socketpair()
    try:
        b.sendall(b'{"t":"res","row_id":1}\n{"t":"res","row')  # torn
        b.close()
        lines = _recv_lines(a)
        first = next(lines)
        assert first["row_id"] == 1
        with pytest.raises(TruncatedFrameError, match="mid-frame"):
            next(lines)
    finally:
        a.close()


def test_worker_socket_drop_mid_stream_fails_round(monkeypatch):
    """Scenario 14: an injected mid-stream socket drop (torn frame on
    the wire) is reported by the coordinator as a worker fault."""
    from sutro_tpu.engine.dphost import (
        run_dp_coordinator, run_dp_worker, shard_requests,
    )

    faults.configure("dphost.send:drop:nth=2")
    try:
        port = _free_port()
        cw, ww = _world(port)
        reqs = _reqs(8)

        def shard_fn(shard, on_result, on_progress, should_cancel):
            for q in shard:
                on_result(_res(q.row_id))
            return "completed"

        def worker_main():
            try:
                run_dp_worker(ww, shard_fn, shard_requests(reqs, 1, 2))
            except Exception:
                pass  # injected drop re-raises locally

        t = threading.Thread(target=worker_main, daemon=True)
        t.start()
        with pytest.raises(RuntimeError, match="worker"):
            run_dp_coordinator(
                cw, shard_fn, shard_requests(reqs, 0, 2),
                on_result=lambda r: None,
            )
        t.join(timeout=60)
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# monitor failure domain
# ---------------------------------------------------------------------------


def test_monitor_fault_degrades_to_disabled_job_succeeds(
    mkengine, monkeypatch
):
    """Fault site ``telemetry.monitor``: a sampler tick that raises
    must degrade the live monitor to disabled — it never takes the
    job down. The job runs to SUCCEEDED with a consistent store while
    the monitor thread exits with the failure recorded."""
    from sutro_tpu import telemetry

    monkeypatch.setenv("SUTRO_MONITOR_INTERVAL", "0.02")
    monkeypatch.delenv("SUTRO_MONITOR", raising=False)
    telemetry.set_enabled(True)
    eng = mkengine(plan="telemetry.monitor:error:times=1")
    assert eng.monitor is not None

    jid = _submit(eng, n_rows=8, max_new=4)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    _assert_no_dup_no_drop(eng, jid, 8)

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and eng.monitor.failed is None:
        time.sleep(0.02)
    assert eng.monitor.failed is not None, (
        "injected tick error never degraded the monitor"
    )
    assert not eng.monitor.running
    # the degradation is visible on the published document, not silent
    doc = eng.monitor_doc()
    assert doc["degraded"] and not doc["running"]


# ---------------------------------------------------------------------------
# fault plan mechanics
# ---------------------------------------------------------------------------


def test_fault_plan_parse_and_determinism():
    plan = faults.parse_plan(
        "seed=7;row.decode:error:rows=1|3,times=2;"
        "jobstore.flush_partial:ioerror:nth=2"
    )
    assert plan.seed == 7
    assert len(plan.specs) == 2
    # row matcher + times bound
    assert plan.fire("row.decode", row=0) is None
    assert plan.fire("row.decode", row=1) is not None
    assert plan.fire("row.decode", row=3) is not None
    assert plan.fire("row.decode", row=1) is None  # times=2 consumed
    # nth: first matching call passes, second fires
    assert plan.fire("jobstore.flush_partial") is None
    assert plan.fire("jobstore.flush_partial") is not None

    # probabilistic clauses replay identically for the same seed
    a = faults.parse_plan("seed=3;row.decode:error:p=0.5")
    b = faults.parse_plan("seed=3;row.decode:error:p=0.5")
    seq_a = [a.fire("row.decode", row=0) is not None for _ in range(64)]
    seq_b = [b.fire("row.decode", row=0) is not None for _ in range(64)]
    assert seq_a == seq_b
    assert any(seq_a) and not all(seq_a)


def test_fault_plan_malformed_raises():
    with pytest.raises(ValueError):
        faults.parse_plan("row.decode:error:rows")
    with pytest.raises(ValueError):
        faults.parse_plan("a:b:c:d")


def test_retry_transient_bounded_and_backed_off(monkeypatch):
    sleeps = []
    monkeypatch.setattr(faults.time, "sleep", sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("blip")
        return "ok"

    events = []
    out = faults.retry_transient(
        flaky, attempts=4, base=0.1, cap=10.0,
        on_retry=lambda a, d, e: events.append((a, d)),
        what="t",
    )
    assert out == "ok" and calls["n"] == 3
    assert len(sleeps) == 2 and len(events) == 2
    # exponential growth modulo the deterministic jitter in [0.5, 1.5)
    assert 0.05 <= sleeps[0] < 0.15 and 0.1 <= sleeps[1] < 0.3

    calls["n"] = 0

    def always():
        calls["n"] += 1
        raise OSError("down")

    with pytest.raises(OSError):
        faults.retry_transient(always, attempts=3, base=0.01, what="t2")
    assert calls["n"] == 3  # bounded


# ---------------------------------------------------------------------------
# control plane: degradation is pass-through, never a failed job
# ---------------------------------------------------------------------------


def test_control_admit_fault_degrades_to_pass_through(mkengine):
    """A controller crash inside admission must not reject OR fail the
    job: the plane flips to pass-through, the triggering job records
    ``control_degraded`` and still SUCCEEDs with bit-identical outputs
    and zero lost rows — even though its buckets were sized to reject
    everything."""
    n = 8
    ref = _reference_outputs(mkengine, n_rows=n)
    eng = mkengine(
        plan="control.admit:error",
        control="rows=1,tokens=1,wait=0,window=600",
    )
    assert eng.control is not None and eng.control.enabled
    jid = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    assert eng.control.enabled is False
    assert "control.admit" in eng.control.degraded_reason
    res = eng.job_results(jid)
    assert res["outputs"] == ref  # pass-through is bit-identical
    log = eng.jobs.get(jid).failure_log or []
    degr = [e for e in log if e["event"] == "control_degraded"]
    assert degr and degr[0]["site"] == "control.admit"
    _assert_no_dup_no_drop(eng, jid, n)
    # degraded plane keeps admitting: a second job sails through the
    # "empty" buckets
    jid2 = _submit(eng, n_rows=n)
    assert _wait_terminal(eng, jid2) == JobStatus.SUCCEEDED


def test_control_actuate_fault_degrades_to_pass_through(mkengine):
    """A controller crash in the autotuner tick disables the WHOLE
    plane (buckets and ladder included); jobs keep succeeding."""
    eng = mkengine(plan="control.actuate:error", control="1")
    assert eng.control is not None
    eng.control.on_monitor_tick({}, [], None, [])
    assert eng.control.enabled is False
    assert "control.actuate" in eng.control.degraded_reason
    assert not eng.control.ladder.active()
    jid = _submit(eng, n_rows=4)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    _assert_no_dup_no_drop(eng, jid, 4)


def test_control_quota_rejection_and_tenant_isolation(mkengine):
    """The enforcement path itself: a noisy tenant exhausting its
    bucket gets a structured QUOTA_EXCEEDED failure (job record, not an
    exception), while a victim tenant on the same engine still admits
    and succeeds."""
    eng = mkengine(control="rows=4,tokens=1e9,wait=0,window=600")
    p1 = {
        "model": "tiny-dense",
        "inputs": [f"noisy {i}" for i in range(4)],
        "sampling_params": {"max_new_tokens": 4, "temperature": 0.0},
        "tenant": "noisy",
    }
    jid = eng.submit_batch_inference(dict(p1))
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    # bucket drained: the tenant's next submit fails FAST and structured
    jid2 = eng.submit_batch_inference(dict(p1))
    assert _wait_terminal(eng, jid2, timeout=30) == JobStatus.FAILED
    rec = eng.jobs.get(jid2)
    assert rec.failure_reason["code"] == "QUOTA_EXCEEDED"
    assert "QUOTA_EXCEEDED" in rec.failure_reason["message"]
    assert any(
        e["event"] == "admission_rejected"
        for e in (rec.failure_log or [])
    )
    # the victim tenant is untouched
    p2 = dict(p1, tenant="victim", inputs=["victim row"])
    jid3 = eng.submit_batch_inference(p2)
    assert _wait_terminal(eng, jid3) == JobStatus.SUCCEEDED
    snap = eng.control.snapshot()
    assert snap["rejections"] >= 1
    assert "noisy/p0" in snap["buckets"]


def test_control_disabled_is_zero_cost_and_bit_identical(
    mkengine, monkeypatch
):
    """The off contract: SUTRO_CONTROL=0 beats EngineConfig.control, the
    engine never builds a ControlPlane, and batch outputs are
    bit-identical to a control-on engine with headroom (the control
    path must not perturb scheduling when it admits)."""
    n = 8
    ref = _reference_outputs(mkengine, n_rows=n)  # stock engine

    monkeypatch.setenv("SUTRO_CONTROL", "0")
    eng_off = mkengine(control="1")  # env forces OFF despite config
    assert eng_off.control is None
    jid = _submit(eng_off, n_rows=n)
    assert _wait_terminal(eng_off, jid) == JobStatus.SUCCEEDED
    assert eng_off.job_results(jid)["outputs"] == ref

    monkeypatch.delenv("SUTRO_CONTROL")
    eng_on = mkengine(control="1")  # defaults: ample headroom
    assert eng_on.control is not None
    jid = _submit(eng_on, n_rows=n)
    assert _wait_terminal(eng_on, jid) == JobStatus.SUCCEEDED
    assert eng_on.job_results(jid)["outputs"] == ref
    assert eng_on.control._drawn == {}  # terminal accounting settled


# ---------------------------------------------------------------------------
# stage-graph DAG faults (engine/stagegraph.py)
# ---------------------------------------------------------------------------

_DAG_STAGES = [
    {"name": "gen", "kind": "map",
     "sampling_params": {"max_new_tokens": 8}},
    {"name": "score", "kind": "map", "after": ["gen"],
     "prompt_template": "score this: {input}",
     "sampling_params": {"max_new_tokens": 4}},
]


def _submit_graph(eng, n_rows=8):
    return eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": [f"chaos row {i}" for i in range(n_rows)],
            "sampling_params": {"temperature": 0.0, "max_new_tokens": 8},
            "job_priority": 0,
            "stages": _DAG_STAGES,
        }
    )


def _graph_reference(mkengine, n_rows=8):
    eng = mkengine(plan=None)
    jid = _submit_graph(eng, n_rows=n_rows)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    return eng.job_results(jid)["outputs"]


def test_stage_flush_fault_resume_replays_only_missing_chunks(mkengine):
    """Scenario: a PERSISTENT partial-store fault scoped to the
    DOWNSTREAM stage (the ``job=`` matcher keys on the nested stage job
    id) fails the DAG after the upstream stage completed. Resume with
    the fault cleared replays ONLY the missing stage's chunks — the
    completed gen stage's chunk files are byte-for-byte untouched — and
    the final results are bit-identical with zero lost/duplicated rows."""
    from sutro_tpu.engine.stagegraph import stage_job_id

    n = 8
    ref = _graph_reference(mkengine, n_rows=n)
    eng = mkengine(plan="jobstore.flush_partial:ioerror:job=stages/score")
    jid = _submit_graph(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.FAILED
    rec = eng.jobs.get(jid)
    assert "injected ioerror" in rec.failure_reason["message"]
    # the fault never touched the upstream stage: its rows are durable
    gen_id = stage_job_id(jid, "gen")
    gen_dir = eng.jobs._partial_dir(gen_id)
    snap = {
        p.name: (p.stat().st_mtime_ns, p.stat().st_size)
        for p in gen_dir.iterdir()
    }
    assert snap  # gen flushed chunks before the DAG died
    faults.clear()
    out = eng.resume_job(jid)
    assert out["resumed"] is True
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    assert eng.job_results(jid)["outputs"] == ref
    # resume replayed ONLY the missing (score) chunks: every gen chunk
    # file survives with the same mtime and size — never re-decoded,
    # never re-flushed
    assert {
        p.name: (p.stat().st_mtime_ns, p.stat().st_size)
        for p in gen_dir.iterdir()
    } == snap
    _assert_no_dup_no_drop(eng, jid, n)
    _assert_no_dup_no_drop(eng, gen_id, n)
    _assert_no_dup_no_drop(eng, stage_job_id(jid, "score"), n)


def test_stage_row_decode_fault_quarantines_in_that_stage(mkengine):
    """Scenario: a poison row in the DOWNSTREAM stage of a DAG is
    quarantined THERE (row-level failure domain per stage): the parent
    job still SUCCEEDs, the quarantine is attributed to the score stage
    in the durable rollup, and every other row is bit-identical."""
    from sutro_tpu.engine.stagegraph import stage_job_id

    n = 8
    ref = _graph_reference(mkengine, n_rows=n)
    eng = mkengine(
        plan="row.decode:error:rows=2,job=stages/score", row_retries=1
    )
    jid = _submit_graph(eng, n_rows=n)
    assert _wait_terminal(eng, jid) == JobStatus.SUCCEEDED
    res = eng.job_results(jid)
    assert res["outputs"][2] is None
    assert res["errors"][2] and "injected fault" in res["errors"][2]
    for i in range(n):
        if i != 2:
            assert res["outputs"][i] == ref[i], f"row {i} diverged"
    state = eng.jobs.get(jid).stages_state
    assert state["gen"]["quarantined"] == 0
    assert state["score"]["quarantined"] == 1
    log = eng.jobs.get(stage_job_id(jid, "score")).failure_log or []
    assert any(
        e["event"] == "row_quarantined" and e["row_id"] == 2 for e in log
    )
    _assert_no_dup_no_drop(eng, jid, n)
