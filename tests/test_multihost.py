"""Two-process multi-host smoke: the mesh layer's DCN story, executed.

parallel/mesh.py claims the same mesh spans all hosts after
``init_distributed()`` and that the outermost ``data`` axis is the one
that crosses hosts (SURVEY §5.8). This test runs it for real: two OS
processes, each with 4 virtual CPU devices, form one 8-device dp=2/tp=4
mesh and run collectives whose ``data``-axis hop crosses the process
boundary (tests/multihost_child.py). Everything the engine needs from
multi-host — distributed init, global array construction, cross-host
psum — executes, not just compiles.
"""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


from tests.conftest import free_low_port as _free_port


def test_two_process_mesh_collectives():
    import jax
    import pytest

    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        pytest.skip(
            "cross-process collectives on the CPU backend need jax >= "
            "0.5 (XLA:CPU gloo collectives); this jax raises "
            "'Multiprocess computations aren't implemented on the CPU "
            "backend'"
        )
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get(
            "PYTHONPATH", ""
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(REPO / "tests" / "multihost_child.py")],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
        )
    # collect BOTH before asserting: an early assert would leak the
    # sibling blocked in jax.distributed.initialize
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"MULTIHOST_OK process={pid}" in out, out
