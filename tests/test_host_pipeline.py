"""Streaming host pipeline at ~2k-row scale (stub runner, no device):
the chunked partial store + merge-on-read finalization must (a) yield
bit-identical, row-ordered results vs the in-memory assembly it
replaced, (b) keep row-granular flush/resume recovery, and (c) bound
peak materialized result rows by the chunk size."""

import time

import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.interfaces import JobStatus

N_ROWS = 2048
MAX_NEW = 12
CHUNK_ROWS = 256


class _StubRunner:
    """Device-free ModelRunner stand-in for the unconstrained pipelined
    path (mirrors benchmarks/profile_host_overhead._StubRunner)."""

    def __init__(self, ecfg, vocab):
        class _M:
            vocab_size = vocab

        self.ecfg = ecfg
        self.mcfg = _M()
        self.vocab = vocab
        self.sp = 1
        self.pp = 1
        self.num_pages = (
            1 + ecfg.decode_batch_size * ecfg.max_pages_per_seq
        )
        self._rng = np.random.default_rng(0)

    def prefill_batch(self, prompts, tables):
        return np.zeros((len(prompts), self.vocab), np.float32)

    def prefill_batch_at(self, rows, page_tables, starts):
        return np.zeros((len(rows), self.vocab), np.float32)

    def prefill(self, prompt, table, start=0):
        return np.zeros((self.vocab,), np.float32)

    def merge_last(self, prev_last, refresh_mask, refresh_vals):
        return np.where(
            np.asarray(refresh_mask, bool),
            np.asarray(refresh_vals, np.int32),
            np.asarray(prev_last, np.int32),
        )

    def decode_multi_async(
        self, last, past_len, tables, rng, temp, top_p, steps,
        top_k=None, pfx=None,
    ):
        B = last.shape[0]
        toks = self._rng.integers(
            1, self.vocab, (steps, B), dtype=np.int64
        ).astype(np.int32)
        logps = np.full((steps, B), -1.0, np.float32)
        return toks, logps

    decode_multi = None  # force the pipelined async path

    def decode_step(
        self, last, past_len, tables, rng, temp, top_p,
        top_k=None, allowed=None, row_seeds=None, penalties=None,
        pfx=None,
    ):
        B = last.shape[0]
        toks = self._rng.integers(
            1, self.vocab, (B,), dtype=np.int64
        ).astype(np.int32)
        return toks, np.full((B,), -1.0, np.float32)


def _stub_ecfg():
    return EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=8,
        decode_batch_size=64,
        max_model_len=128,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=4,
        decode_lookahead=2,
        max_new_tokens=MAX_NEW,
    )


@pytest.fixture()
def stub_eng(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    monkeypatch.setenv("SUTRO_RESULT_CHUNK", str(CHUNK_ROWS))
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    eng = LocalEngine(_stub_ecfg())

    def _get_runner(engine_key, mcfg):
        cached = eng._runner_cache.get(engine_key)
        if cached is not None:
            return cached
        runner = _StubRunner(eng.ecfg, vocab=mcfg.vocab_size)
        tok = ByteTokenizer(vocab_size=mcfg.vocab_size)
        eng._runner_cache[engine_key] = (runner, tok)
        return runner, tok

    eng._get_runner = _get_runner
    return eng


def _wait_terminal(eng, job_id, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if JobStatus(eng.job_status(job_id)).is_terminal():
            return JobStatus(eng.job_status(job_id))
        time.sleep(0.02)
    raise TimeoutError(job_id)


def _submit(eng, n_rows=N_ROWS):
    return eng.submit_batch_inference(
        {
            "model": "tiny-dense",
            "inputs": [f"review {i}: pretty good" for i in range(n_rows)],
            "system_prompt": "classify the sentiment",
            "sampling_params": {
                "max_new_tokens": MAX_NEW, "temperature": 0.7
            },
        }
    )


def test_streamed_results_bit_identical_to_in_memory_assembly(stub_eng):
    """results.parquet written by the merge-on-read streamed path must
    equal, bit for bit and in row order, what the old whole-job
    in-memory assembly produces from the same partial store."""
    job_id = _submit(stub_eng)
    assert _wait_terminal(stub_eng, job_id) == JobStatus.SUCCEEDED
    res = stub_eng.job_results(
        job_id, include_cumulative_logprobs=True
    )
    assert len(res["outputs"]) == N_ROWS
    assert all(o is not None for o in res["outputs"])

    # reference: the legacy assembly rule over the full partial store
    rows = stub_eng.jobs.read_partial(job_id)
    assert set(rows) == set(range(N_ROWS))
    df = stub_eng.jobs.read_results(job_id)
    assert list(df["row_id"]) == list(range(N_ROWS))  # row-ordered
    for i in range(N_ROWS):
        assert df["outputs"].iloc[i] == rows[i]["outputs"], i
        assert float(df["cumulative_logprobs"].iloc[i]) == float(
            rows[i]["cumulative_logprobs"]
        ), i
        assert int(df["gen_tokens"].iloc[i]) == int(
            rows[i]["gen_tokens"]
        ), i
        assert df["finish_reason"].iloc[i] == rows[i]["finish_reason"], i


def test_partial_flush_resume_stays_row_granular(stub_eng):
    """Cancel mid-run, then resume: rows already flushed to the chunked
    partial store are skipped (their bytes survive verbatim), the rest
    regenerate, and the final job is complete and ordered."""
    job_id = _submit(stub_eng)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if stub_eng.metrics.job(job_id).rows_completed >= CHUNK_ROWS:
            break
        time.sleep(0.005)
    stub_eng.cancel_job(job_id)
    status = _wait_terminal(stub_eng, job_id)
    if status == JobStatus.SUCCEEDED:
        pytest.skip("job raced to completion before cancel")
    deadline = time.monotonic() + 60
    while (
        stub_eng.job_status(job_id) == JobStatus.CANCELLING.value
        and time.monotonic() < deadline
    ):
        time.sleep(0.02)
    assert stub_eng.job_status(job_id) == JobStatus.CANCELLED.value

    flushed = {
        i: r
        for i, r in stub_eng.jobs.read_partial(job_id).items()
        if r.get("finish_reason") != "cancelled"
    }
    assert flushed, "cancel landed before any flush; nothing to verify"
    out = stub_eng.resume_job(job_id)
    assert out["resumed"] is True
    assert out["rows_already_done"] == len(flushed)
    assert _wait_terminal(stub_eng, job_id) == JobStatus.SUCCEEDED
    df = stub_eng.jobs.read_results(job_id)
    assert list(df["row_id"]) == list(range(N_ROWS))
    assert all(o is not None for o in df["outputs"])
    for i, r in flushed.items():
        # flushed rows were skipped, not regenerated
        assert df["outputs"].iloc[i] == r["outputs"], i


def test_peak_materialized_rows_bounded_by_chunk(stub_eng):
    """Neither the flush path nor finalization may materialize more
    than a chunk of result rows at once: flushes are bounded by the
    engine's flush batch, finalize buckets by SUTRO_RESULT_CHUNK."""
    from sutro_tpu.engine import api as api_mod

    peaks = {"flush": 0, "finalize": 0}
    jobs = stub_eng.jobs
    orig_flush = jobs.flush_partial
    orig_write = jobs.write_results_streamed

    def flush_spy(jid, rows):
        peaks["flush"] = max(peaks["flush"], len(rows))
        orig_flush(jid, rows)

    def write_spy(jid, num_rows, on_chunk=None):
        def chunk_spy(df):
            peaks["finalize"] = max(peaks["finalize"], len(df))
            if on_chunk is not None:
                on_chunk(df)

        orig_write(jid, num_rows, on_chunk=chunk_spy)

    jobs.flush_partial = flush_spy
    jobs.write_results_streamed = write_spy
    try:
        job_id = _submit(stub_eng)
        assert _wait_terminal(stub_eng, job_id) == JobStatus.SUCCEEDED
    finally:
        jobs.flush_partial = orig_flush
        jobs.write_results_streamed = orig_write

    assert 0 < peaks["flush"] <= api_mod._PARTIAL_FLUSH_EVERY
    assert 0 < peaks["finalize"] <= CHUNK_ROWS
    # the partial store is chunked on disk too — no monolithic file
    assert not (jobs._dir(job_id) / "partial.parquet").exists()
    assert len(jobs._partial_chunks(job_id)) >= N_ROWS // CHUNK_ROWS
