"""Contract-layer tests: data prep, schema normalization, model catalog.

Models the reference's TestPrepareInputData / input-validation coverage
(/root/reference/tests/test_sdk.py:326-334, 787-804) but kept green —
SURVEY §4 notes the reference suite is stale by design.
"""

import pandas as pd
import pytest
from pydantic import BaseModel

from sutro_tpu.common import (
    MODEL_CATALOG,
    do_dataframe_column_concatenation,
    normalize_output_schema,
    prepare_input_data,
)
from sutro_tpu.models.configs import MODEL_CONFIGS


def test_list_passthrough():
    assert prepare_input_data(["a", "b", 3]) == ["a", "b", "3"]


def test_dataframe_requires_column():
    df = pd.DataFrame({"x": ["a", "b"]})
    with pytest.raises(ValueError, match="column"):
        prepare_input_data(df)


def test_dataframe_column():
    df = pd.DataFrame({"x": ["a", "b"], "y": [1, 2]})
    assert prepare_input_data(df, column="x") == ["a", "b"]


def test_column_concatenation_with_separators():
    df = pd.DataFrame({"title": ["t1", "t2"], "body": ["b1", "b2"]})
    out = do_dataframe_column_concatenation(df, ["title", ": ", "body"])
    assert out == ["t1: b1", "t2: b2"]


def test_dataset_id_passthrough():
    assert prepare_input_data("dataset-abc123") == "dataset-abc123"


def test_csv_and_parquet(tmp_path):
    df = pd.DataFrame({"c": ["r1", "r2"]})
    csv = tmp_path / "f.csv"
    df.to_csv(csv, index=False)
    assert prepare_input_data(str(csv), column="c") == ["r1", "r2"]
    pq = tmp_path / "f.parquet"
    df.to_parquet(pq)
    assert prepare_input_data(str(pq), column="c") == ["r1", "r2"]


def test_txt(tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("l1\nl2\n\n")
    assert prepare_input_data(str(p)) == ["l1", "l2"]


def test_unsupported_input():
    with pytest.raises(ValueError):
        prepare_input_data(42)


def test_normalize_output_schema_pydantic():
    class S(BaseModel):
        sentiment: str
        score: int

    js = normalize_output_schema(S)
    assert js["properties"]["sentiment"]["type"] == "string"
    assert normalize_output_schema(None) is None
    assert normalize_output_schema({"type": "object"}) == {"type": "object"}
    with pytest.raises(ValueError):
        normalize_output_schema("not-a-schema")


def test_catalog_maps_to_engine_configs():
    # every public (non-Function) model resolves to a real engine config
    for name, meta in MODEL_CATALOG.items():
        assert meta["engine_key"] in MODEL_CONFIGS, name


def test_catalog_no_duplicates():
    # the reference's duplicate "llama-3.3-70b" literal is not reproduced
    names = list(MODEL_CATALOG)
    assert len(names) == len(set(names))


def test_compile_cache_optout_and_respect(monkeypatch):
    """enable_compile_cache: SUTRO_COMPILE_CACHE=0 disables; an
    explicit user cache dir is respected (not overwritten)."""
    import jax

    from sutro_tpu.engine import config as cfgmod

    monkeypatch.setattr(cfgmod, "_CACHE_ENABLED", False)
    monkeypatch.setenv("SUTRO_COMPILE_CACHE", "0")
    before = jax.config.jax_compilation_cache_dir
    cfgmod.enable_compile_cache()
    assert cfgmod._CACHE_ENABLED is False
    assert jax.config.jax_compilation_cache_dir == before

    monkeypatch.delenv("SUTRO_COMPILE_CACHE")
    monkeypatch.setattr(cfgmod, "_CACHE_ENABLED", False)
    jax.config.update("jax_compilation_cache_dir", "/tmp/user-chosen")
    try:
        cfgmod.enable_compile_cache()
        assert (
            jax.config.jax_compilation_cache_dir == "/tmp/user-chosen"
        )
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_metrics_bus_conflates_slow_subscribers():
    """The progress bus must hold O(1) pending state per subscriber: a
    consumer that never drains cannot accumulate an unbounded queue,
    and when it finally reads it sees the LATEST progress plus the
    monotonically merged token totals, then the finish sentinel."""
    from sutro_tpu.engine.metrics import JobMetrics

    jm = JobMetrics()
    it = jm.subscribe()
    first = next(it)  # snapshot
    assert first == {"update_type": "progress", "result": 0}
    # thousands of producer updates while the consumer sleeps
    for i in range(5000):
        jm.progress(i)
        jm.tokens({"output_tokens": i})
    jm.tokens({"input_tokens": 77})
    sub = jm._subscribers[0]
    assert sub.progress == 4999  # conflated, not queued
    assert sub.tokens["output_tokens"] == 4999
    assert sub.tokens["input_tokens"] == 77  # partials merged
    jm.finish()
    updates = list(it)
    kinds = [u["update_type"] for u in updates]
    assert kinds.count("progress") == 1
    assert updates[kinds.index("progress")]["result"] == 4999


def test_metrics_bus_final_update_beats_sentinel():
    """A progress update published just before finish must still be
    delivered — pending state drains before the done flag is honored."""
    from sutro_tpu.engine.metrics import JobMetrics

    jm = JobMetrics()
    it = jm.subscribe()
    next(it)
    jm.progress(41)
    jm.progress(42)
    jm.finish()
    updates = list(it)
    assert {"update_type": "progress", "result": 42} in updates


def test_batched_progress_rule():
    from sutro_tpu.engine.metrics import BatchedProgress, JobMetrics

    jm = JobMetrics()
    seen = []
    orig = jm.progress
    jm.progress = lambda n: (seen.append(n), orig(n))
    bp = BatchedProgress(jm, every_rows=10)
    for i in range(25):
        bp.update(i)
    bp.flush(25)
    assert seen == [9, 19, 25]  # one publish per 10 rows + terminal
