"""Engine-lifetime radix prefix store (engine/prefixstore.py).

Cross-JOB KV reuse: the store keeps template-shell pages warm across
batcher sessions so the second job (or co-batched, resumed, or
interactive request) with the same shell prefills only its novel tail.
The contract under test, in order of importance:

1. ``SUTRO_PREFIX_STORE=0`` / no store => bit-identical to today's
   per-job path (batch, co-batch, resume, interactive).
2. Store on => the second identical-template job's prefill token count
   drops by the warm shell (the ISSUE's >= 2x shared-shell bar).
3. Page accounting is exact: pinned nodes never evict, eviction under
   admission pressure loses zero rows, releasing a store returns every
   page (a fresh batcher's free count equals the pristine pool).
4. Fault site ``prefixstore.lookup`` degrades to a plain miss.
"""

import numpy as np
import pytest

from sutro_tpu.engine import faults
from sutro_tpu.engine.kvcache import PageAllocator
from sutro_tpu.engine.prefixstore import PrefixStore
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest, JobCtx

PREFIX = "You are a terse classifier. Decide the sentiment of this: "
TAILS = ["great!", "bad movie", "meh", "totally awesome ride"]


def _toks(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(1, 200, size=(n,)).astype(np.int32)


# ---------------------------------------------------------------------
# radix tree units (no model)
# ---------------------------------------------------------------------


def test_lookup_extend_release_roundtrip():
    s = PrefixStore(8)
    t = _toks(20)
    miss = s.lookup_pin(t)
    assert miss.tokens == 0 and miss.pages == []
    h = s.empty_handle()
    assert s.extend(h, t[:16], [3, 4])
    assert h.pages == [3, 4] and h.tokens == 16
    # the whole page-aligned head is warm; the ragged 4 tokens are not
    hit = s.lookup_pin(t)
    assert hit.tokens == 16 and hit.pages == [3, 4]
    assert s.peek(t) == 16
    assert s.n_pages == 2
    s.release(h)
    s.release(hit)
    assert s.hits == 1 and s.misses == 1 and s.tokens_saved == 16


def test_partial_match_pins_only_matched_path():
    s = PrefixStore(4)
    a = _toks(12, seed=1)
    h = s.empty_handle()
    assert s.extend(h, a, [10, 11, 12])
    s.release(h)
    # diverges after the first page
    b = np.concatenate([a[:4], _toks(8, seed=2)])
    hit = s.lookup_pin(b)
    assert hit.tokens == 4 and hit.pages == [10]
    # extend grafts the divergent run as a sibling branch
    assert s.extend(hit, b[4:12], [20, 21])
    assert s.n_pages == 5
    s.release(hit)
    again = s.lookup_pin(b)
    assert again.pages == [10, 20, 21]
    s.release(again)


def test_extend_length_mismatch_raises():
    s = PrefixStore(8)
    with pytest.raises(ValueError):
        s.extend(s.empty_handle(), _toks(16), [1, 2, 3])


def test_extend_racer_declines_and_caller_keeps_pages():
    s = PrefixStore(8)
    t = _toks(16, seed=3)
    h1 = s.empty_handle()
    assert s.extend(h1, t, [5, 6])
    # a second session prefilled the same run concurrently: its extend
    # must decline so the caller frees its own (duplicate) pages
    h2 = s.empty_handle()
    assert not s.extend(h2, t, [7, 8])
    assert h2.pages == [] and s.n_pages == 2
    s.release(h1)


def test_lru_eviction_order_and_leaf_only():
    s = PrefixStore(4)
    t = _toks(12, seed=4)
    h = s.empty_handle()
    assert s.extend(h, t, [1, 2, 3])  # chain 1 -> 2 -> 3
    s.release(h)
    # deepest leaf goes first; evicting it exposes its parent
    assert s.evict(2) == [3, 2]
    assert s.n_pages == 1
    # a fresh branch touched LATER evicts after the stale root page
    u = np.concatenate([t[:4], _toks(4, seed=5)])
    h2 = s.lookup_pin(u)  # touches node 1
    assert s.extend(h2, u[4:], [9])
    s.release(h2)
    # leaves are 9 (stamp newer) and ... 1 is interior; only 9 is a
    # leaf until it goes, then 1
    assert s.evict(10) == [9, 1]
    assert s.n_pages == 0
    assert s.evictions == 4


def test_pinned_nodes_never_evict():
    s = PrefixStore(8)
    t = _toks(24, seed=6)
    h = s.empty_handle()
    assert s.extend(h, t, [1, 2, 3])
    # handle still held: nothing may evict, however large the demand
    assert s.evict(100) == []
    assert s.n_pages == 3
    s.release(h)
    assert len(s.evict(100)) == 3


def test_peek_does_not_touch_lru_or_counters():
    s = PrefixStore(8)
    a, b = _toks(8, seed=7), _toks(8, seed=8)
    ha, hb = s.empty_handle(), s.empty_handle()
    assert s.extend(ha, a, [1]) and s.extend(hb, b, [2])
    s.release(ha)
    s.release(hb)
    hits, misses = s.hits, s.misses
    for _ in range(5):
        assert s.peek(a) == 8  # would re-stamp node 1 if it touched
    assert (s.hits, s.misses) == (hits, misses)
    # LRU order unchanged: 1 is still older than 2
    assert s.evict(1) == [1]


def test_close_drops_tree_and_refuses_extends():
    s = PrefixStore(8)
    t = _toks(16, seed=9)
    h = s.empty_handle()
    assert s.extend(h, t, [1, 2])
    s.close()
    assert s.n_pages == 0
    assert s.lookup_pin(t).tokens == 0
    assert not s.extend(s.empty_handle(), t, [3, 4])
    assert s.peek(t) == 0


def test_refcounts_under_concurrent_handles():
    s = PrefixStore(8)
    t = _toks(32, seed=10)
    h = s.empty_handle()
    assert s.extend(h, t, [1, 2, 3, 4])
    handles = [s.lookup_pin(t) for _ in range(3)]
    s.release(h)
    assert s.evict(100) == []  # three pins outstanding
    for x in handles[:-1]:
        s.release(x)
    assert s.evict(100) == []  # one pin outstanding
    s.release(handles[-1])
    assert sorted(s.evict(100)) == [1, 2, 3, 4]
    # double release is a no-op, never an underflow
    s.release(handles[-1])


def test_page_allocator_reserve_atomic():
    a = PageAllocator(num_pages=8)
    free0 = a.free_count
    a.reserve([2, 5])
    assert a.free_count == free0 - 2
    # not-free id => KeyError and NO partial mutation
    with pytest.raises(KeyError):
        a.reserve([3, 5])
    assert a.free_count == free0 - 2
    with pytest.raises(KeyError):
        a.reserve([4, 4])  # duplicate
    assert a.free_count == free0 - 2
    a.free([2, 5])
    assert a.free_count == free0


# ---------------------------------------------------------------------
# scheduler integration (tiny model; one session runner shared)
# ---------------------------------------------------------------------


def _reqs(tok, tails=TAILS, **kw):
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("temperature", 0.0)
    return [
        GenRequest(
            row_id=i,
            prompt_ids=np.array(tok.encode(PREFIX + t), np.int32),
            **kw,
        )
        for i, t in enumerate(tails)
    ]


def _batcher(runner, tok, store=None):
    return ContinuousBatcher(
        runner, stop_ids=tok.stop_ids(), prefix_store=store
    )


def _run(b, reqs, **kw):
    res = {}
    out = b.run(
        reqs, on_result=lambda r: res.__setitem__(r.row_id, r), **kw
    )
    return out, {i: r.token_ids for i, r in res.items()}


def _alloc_pages(b, n):
    """Allocate a page block on whichever allocator the batcher runs
    (native runtime or the pure-Python fallback)."""
    if b.native is not None:
        pages = b.native.alloc_pages(n)
        assert pages is not None
        return pages
    return b.allocator.alloc(n)


def test_second_job_prefills_tail_only_and_bit_identical(
    tiny_runner, byte_tok
):
    """The ISSUE's shared-shell bar: the second of two identical-
    template jobs pays >= 2x fewer prefill tokens, with outputs
    bit-identical to the storeless engine."""
    store = PrefixStore(8)
    b1 = _batcher(tiny_runner, byte_tok, store)
    pristine = b1.free_page_count
    _, r1 = _run(b1, _reqs(byte_tok))
    paid1 = b1.prefill_tokens
    assert store.n_pages > 0
    # the store's pages are out of the session free list, not leaked
    assert b1.free_page_count == pristine - store.n_pages

    b2 = _batcher(tiny_runner, byte_tok, store)
    assert b2.free_page_count == pristine - store.n_pages
    _, r2 = _run(b2, _reqs(byte_tok))
    assert b2.prefill_tokens <= paid1 / 2, (paid1, b2.prefill_tokens)
    assert store.hits >= 1 and store.tokens_saved > 0

    b_off = _batcher(tiny_runner, byte_tok)  # kill switch: no store
    _, r_off = _run(b_off, _reqs(byte_tok))
    assert r1 == r2 == r_off


def test_cobatched_jobs_share_store_pages_bit_identical(
    tiny_runner, byte_tok
):
    """Two co-batched jobs with the SAME shell: the second pins the
    first's freshly inserted pages (same session!) and outputs match
    the storeless co-batch."""

    def cobatch(store):
        b = _batcher(tiny_runner, byte_tok, store)
        ga, gb = {}, {}
        st = b.run_multi(
            [
                JobCtx(
                    job_id="A", pending=_reqs(byte_tok),
                    on_result=lambda r: ga.__setitem__(r.row_id, r),
                    priority=1, seq=0,
                ),
                JobCtx(
                    job_id="B",
                    pending=_reqs(byte_tok, tails=["x", "yy", "zzz"]),
                    on_result=lambda r: gb.__setitem__(r.row_id, r),
                    priority=1, seq=1,
                ),
            ],
            on_job_done=lambda c, o: None,
        )
        assert st == "completed"
        return (
            {i: r.token_ids for i, r in ga.items()},
            {i: r.token_ids for i, r in gb.items()},
            b,
        )

    store = PrefixStore(8)
    on_a, on_b, b_on = cobatch(store)
    off_a, off_b, b_off = cobatch(None)
    assert on_a == off_a and on_b == off_b
    assert b_on.prefill_tokens < b_off.prefill_tokens
    # both jobs done: every node unpinned again
    assert store.evict(10_000), "store should hold evictable pages"


def test_resume_after_yield_on_fresh_batcher_bit_identical(
    tiny_runner, byte_tok
):
    """Preemption yield, then resume on a FRESH batcher (the crash /
    requeue path): the new session re-reserves the store's pages and
    the warm re-run matches the storeless outputs."""
    store = PrefixStore(8)
    b1 = _batcher(tiny_runner, byte_tok, store)
    pristine = b1.free_page_count
    out, _ = _run(b1, _reqs(byte_tok), should_yield=lambda: True)
    assert out == "yielded"
    # yielded rows freed their pages; the store keeps the shell
    assert b1.free_page_count == pristine - store.n_pages

    b2 = _batcher(tiny_runner, byte_tok, store)
    out, r2 = _run(b2, _reqs(byte_tok))
    assert out == "completed"
    assert set(r2) == set(range(len(TAILS)))

    b_off = _batcher(tiny_runner, byte_tok)
    _, r_off = _run(b_off, _reqs(byte_tok))
    assert r2 == r_off


def test_sampled_outputs_identical_with_row_seeds(
    tiny_runner, byte_tok
):
    """Row-seeded sampling is batch-composition independent — a warm
    store must not change a single sampled token."""
    store = PrefixStore(8)
    kw = dict(max_new_tokens=6, temperature=0.9, top_p=0.9)

    def seeded():
        reqs = _reqs(byte_tok, **kw)
        for i, r in enumerate(reqs):
            r.row_seed = i
        return reqs

    _run(_batcher(tiny_runner, byte_tok, store), seeded())  # seed it
    _, warm = _run(_batcher(tiny_runner, byte_tok, store), seeded())
    _, off = _run(_batcher(tiny_runner, byte_tok), seeded())
    assert warm == off


# ---------------------------------------------------------------------
# chaos: eviction racing admission, fault degradation, close()
# ---------------------------------------------------------------------


def test_eviction_races_admission_pinned_never_evict(
    tiny_runner, byte_tok
):
    """Bloat the store until the pool can't admit, then run a real job:
    admission pressure must evict unpinned LRU pages (zero lost rows),
    while a concurrently pinned path survives untouched."""
    store = PrefixStore(8)
    b = _batcher(tiny_runner, byte_tok, store)
    pristine = b.free_page_count
    # hand almost the whole pool to the store (distinct fake shells),
    # exactly as a long engine lifetime would
    n_bloat = pristine - 4
    pages = _alloc_pages(b, n_bloat)
    h = store.empty_handle()
    assert store.extend(h, _toks(8 * n_bloat, seed=11), pages)
    store.release(h)
    # pin one path: these pages must survive the pressure below
    pinned = store.lookup_pin(_toks(8 * n_bloat, seed=11)[:16])
    assert len(pinned.pages) == 2
    assert b.free_page_count == 4

    out, res = _run(b, _reqs(byte_tok))
    assert out == "completed"
    assert set(res) == set(range(len(TAILS)))  # zero lost rows
    assert store.evictions > 0
    assert all(p in store.owned_pages() for p in pinned.pages)
    store.release(pinned)
    # conservation: session pages all came back; store pages stayed out
    assert b.free_page_count == pristine - store.n_pages


def test_lookup_fault_degrades_to_miss(tiny_runner, byte_tok):
    """Fault site prefixstore.lookup: the job pays full prefill but
    completes with bit-identical outputs — a store crash never fails
    a job and never loses a row."""
    store = PrefixStore(8)
    _, r_warm = _run(
        _batcher(tiny_runner, byte_tok, store), _reqs(byte_tok)
    )
    faults.configure("prefixstore.lookup:error")
    try:
        b = _batcher(tiny_runner, byte_tok, store)
        out, r_faulted = _run(b, _reqs(byte_tok))
        assert out == "completed"
        # degraded to a miss: the shell was re-prefilled in full
        assert b.prefill_tokens > 0
    finally:
        faults.clear()
    assert r_faulted == r_warm
    _, r_off = _run(_batcher(tiny_runner, byte_tok), _reqs(byte_tok))
    assert r_faulted == r_off


def test_close_returns_every_page_to_fresh_batcher(
    tiny_runner, byte_tok
):
    """The teardown contract: after close(), a new batcher over the
    surviving pool reserves nothing — free count returns to the
    pristine pool size (no page leaked to a dead tree)."""
    store = PrefixStore(8)
    b1 = _batcher(tiny_runner, byte_tok, store)
    pristine = b1.free_page_count
    _run(b1, _reqs(byte_tok))
    assert store.n_pages > 0
    store.close()
    b2 = _batcher(tiny_runner, byte_tok, store)
    assert b2.free_page_count == pristine
    # the closed store stays inert but harmless for the whole session
    out, res = _run(b2, _reqs(byte_tok))
    assert out == "completed" and len(res) == len(TAILS)
    assert b2.free_page_count == pristine


def test_mismatched_page_size_store_is_ignored(tiny_runner, byte_tok):
    """A store whose page geometry doesn't match the batcher's pool is
    detached entirely — the session runs the storeless per-job path
    with nothing reserved and nothing leaked."""
    store = PrefixStore(16)  # batcher pool uses kv_page_size=8
    b = _batcher(tiny_runner, byte_tok, store)
    pristine = b.free_page_count
    out, res = _run(b, _reqs(byte_tok))
    assert out == "completed" and len(res) == len(TAILS)
    assert b.free_page_count == pristine  # nothing reserved or leaked


# ---------------------------------------------------------------------
# engine level: kill switch + interactive warm path (shared fixture)
# ---------------------------------------------------------------------


def test_engine_kill_switch_resolution(live_engine, monkeypatch):
    eng, _url, _home = live_engine
    key = "tiny-dense"
    monkeypatch.setenv("SUTRO_PREFIX_STORE", "0")
    assert eng._prefix_store_for(key) is None
    monkeypatch.setenv("SUTRO_PREFIX_STORE", "off")
    assert eng._prefix_store_for(key) is None
    monkeypatch.delenv("SUTRO_PREFIX_STORE", raising=False)
    store = eng._prefix_store_for(key)
    assert store is not None
    assert eng._prefix_store_for(key) is store  # one per engine key
    # warm-token probe is total: cold store, unknown key, raw ids
    assert eng.prefix_warm_tokens("no-such-key", [1, 2, 3]) == 0


def test_interactive_repeat_request_hits_warm_prefix(live_engine):
    """Second identical /v1/completions call: same text at temp 0, and
    the gateway's submit-time probe sees the warm shell seeded by the
    first (the interactive leg of the bit-identity matrix)."""
    import json
    import urllib.request

    eng, url, _home = live_engine
    body = json.dumps(
        {
            "model": "tiny-dense",
            "prompt": PREFIX + "this is a wonderful product, truly",
            "max_tokens": 8,
            "temperature": 0.0,
        }
    ).encode()

    def post():
        req = urllib.request.Request(
            url + "/v1/completions", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    first = post()
    second = post()
    t1 = first["choices"][0]["text"]
    t2 = second["choices"][0]["text"]
    assert t1 == t2  # warm KV is bit-identical to cold prefill
    store = eng._prefix_stores.get("tiny-dense")
    if store is not None:  # gateway probe saw the first call's shell
        assert store.hits >= 1
