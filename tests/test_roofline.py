"""Bench-record self-grading (engine/roofline.py, VERDICT r3 weak #5):
analytic bytes-per-step / roofline fractions computed from the model
config, present in every bench record."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from sutro_tpu.engine import roofline

REPO = Path(__file__).resolve().parent.parent


def test_hw_specs_lookup():
    assert roofline.hw_specs("TPU v5 lite") == (819.0, 197.0)
    assert roofline.hw_specs("TPU v4") == (1228.0, 275.0)
    assert roofline.hw_specs("cpu") is None
    assert roofline.hw_specs("") is None


def test_decode_bytes_per_step_arithmetic():
    # params + B * (ctx+1) * L*2*KVH*Dh*kv_bytes
    b = roofline.decode_bytes_per_step(
        param_bytes=1_000_000,
        batch=4,
        avg_ctx=99,
        num_layers=2,
        kv_heads=2,
        head_dim=8,
        kv_dtype_bytes=2,
    )
    assert b == 1_000_000 + 4 * (2 * 2 * 2 * 8 * 2) * 100


def test_grade_decode_fraction():
    # choose numbers so the fraction is exactly 50%: bytes/step = 819e9
    # bytes/s at 1 step/s would be 100%; run at 0.5 step/s
    g = roofline.grade_decode(
        32.0,  # tok/s at batch 64 -> 0.5 steps/s
        batch=64,
        bytes_per_step=819.0e9,
        device_kind="TPU v5 lite",
    )
    assert g["pct_hbm_roofline"] == pytest.approx(50.0)
    assert g["hbm_gb_s"] == 819.0
    # unknown hardware: grade omitted, never fabricated
    g2 = roofline.grade_decode(
        32.0, batch=64, bytes_per_step=1e9, device_kind="cpu"
    )
    assert g2["pct_hbm_roofline"] is None


def test_grade_prefill_mfu():
    # 2 * 1e9 params * tok_s / (197e12) => choose tok_s for mfu=10%
    tok_s = 0.10 * 197e12 / (2 * 1e9)
    g = roofline.grade_prefill(
        tok_s, n_params=1_000_000_000, device_kind="TPU v5 lite"
    )
    assert g["mfu_prefill"] == pytest.approx(10.0)
    assert (
        roofline.grade_prefill(1.0, n_params=1, device_kind="x")[
            "mfu_prefill"
        ]
        is None
    )


def test_param_bytes_counts_quantized_width():
    import numpy as np

    params = {
        "w": np.zeros((4, 4), np.int8),
        "s": np.zeros((4,), np.float32),
    }
    assert roofline.param_bytes_of(params) == 16 + 16
    assert roofline.param_count_of(params) == 20


@pytest.mark.slow
def test_bench_record_carries_grading_fields(tmp_path):
    """bench.py's printed line and record carry the self-grading fields
    (None off-TPU — unknown hardware is never graded against a made-up
    roofline)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    # run from tmp so the baseline file write does not touch the repo
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');\n"
        "import runpy, sys; sys.argv=['bench.py'];\n"
        f"runpy.run_path({str(REPO / 'bench.py')!r}, run_name='__main__')"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=tmp_path,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert "pct_hbm_roofline" in line
    assert "mfu_prefill" in line
    assert line["pct_hbm_roofline"] is None  # cpu: unknown hardware
