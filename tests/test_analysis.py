"""graftlint (sutro_tpu.analysis): rule fixtures (true positive, true
negative, suppressed), the self-scan baseline gate, injection
sensitivity on the real tree, and the engine fixes the passes drove
(narrowed excepts, bounded teardown)."""

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from sutro_tpu.analysis import core
from sutro_tpu.analysis.callgraph import PackageIndex
from sutro_tpu.analysis.core import run_passes

REPO = Path(__file__).resolve().parent.parent
BASELINE = REPO / "sutro_tpu" / "analysis" / "baseline.json"


def scan(src: str, name: str = "m", path: str = "m.py"):
    idx = PackageIndex()
    idx.add_source(path, src, name)
    active, suppressed = core.apply_suppressions(idx, run_passes(idx))
    return active, suppressed


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- locks


def test_lock_order_inversion_flagged():
    active, _ = scan(
        """
import threading
class S:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
    def f(self):
        with self.a_lock:
            with self.b_lock:
                pass
    def g(self):
        with self.b_lock:
            self.h()
    def h(self):
        with self.a_lock:
            pass
"""
    )
    assert "lock-order" in rules_of(active)
    (f,) = [f for f in active if f.rule == "lock-order"]
    assert "S.a_lock" in f.message and "S.b_lock" in f.message


def test_consistent_lock_order_clean():
    active, _ = scan(
        """
import threading
class S:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
    def f(self):
        with self.a_lock:
            with self.b_lock:
                pass
    def g(self):
        with self.a_lock:
            with self.b_lock:
                pass
"""
    )
    assert "lock-order" not in rules_of(active)


def test_cross_function_inversion_on_shared_object():
    active, _ = scan(
        """
import threading
class Bus:
    def __init__(self):
        self._lock = threading.Lock()
    def a(self, jm):
        with self._lock:
            with jm.lock:
                pass
    def b(self, jm):
        with jm.lock:
            with self._lock:
                pass
"""
    )
    assert "lock-order" in rules_of(active)


def test_blocking_call_under_lock_direct_and_interprocedural():
    active, _ = scan(
        """
import threading, time
def helper():
    time.sleep(1)
def f():
    lock = threading.Lock()
    with lock:
        helper()
"""
    )
    found = [f for f in active if f.rule == "lock-blocking-call"]
    assert found and "time.sleep" in found[0].message
    assert "call chain" in found[0].message


def test_blocking_call_outside_lock_clean():
    active, _ = scan(
        """
import threading, time
def f():
    lock = threading.Lock()
    with lock:
        pass
    time.sleep(1)
"""
    )
    assert "lock-blocking-call" not in rules_of(active)


def test_blocking_call_suppressed():
    active, suppressed = scan(
        """
import threading, time
def f():
    lock = threading.Lock()
    with lock:
        time.sleep(1)  # graftlint: disable=lock-blocking-call
"""
    )
    assert "lock-blocking-call" not in rules_of(active)
    assert "lock-blocking-call" in rules_of(suppressed)


def test_thread_join_under_lock_blocks_string_join_does_not():
    active, _ = scan(
        """
import threading
def f():
    lock = threading.Lock()
    t = threading.Thread(target=f, daemon=True)
    t.start()
    with lock:
        t.join(timeout=5)
        s = ",".join(["a", "b"])
"""
    )
    found = [f for f in active if f.rule == "lock-blocking-call"]
    assert len(found) == 1 and "t.join" in found[0].message


def test_callback_under_lock_flagged_and_clean_outside():
    active, _ = scan(
        """
import threading
def f(on_result):
    lock = threading.Lock()
    with lock:
        on_result(1)
    on_result(2)
"""
    )
    found = [f for f in active if f.rule == "lock-callback"]
    assert len(found) == 1 and found[0].line == 6


def test_reentrant_lock_acquisition_flagged_rlock_clean():
    active, _ = scan(
        """
import threading
def bad():
    lock = threading.Lock()
    with lock:
        with lock:
            pass
def fine():
    r = threading.RLock()
    with r:
        with r:
            pass
"""
    )
    found = [f for f in active if f.rule == "lock-reentrant"]
    assert len(found) == 1 and "bad.lock" in found[0].message


def test_nested_def_under_lock_not_treated_as_running():
    active, _ = scan(
        """
import threading, time
def f():
    lock = threading.Lock()
    with lock:
        def later():
            time.sleep(1)
        return later
"""
    )
    assert "lock-blocking-call" not in rules_of(active)


# -------------------------------------------------------------- jitpure


def test_jit_host_sync_flagged():
    active, _ = scan(
        """
import functools
import jax
import numpy as np
@functools.partial(jax.jit, static_argnames=("n",))
def step(x, n):
    y = np.asarray(x)
    m = int(n)
    k = float(x)
    return y
"""
    )
    msgs = [f.message for f in active if f.rule == "jit-host-sync"]
    assert any("np.asarray" in m for m in msgs)
    assert any("float(x)" in m for m in msgs)  # traced param
    assert not any("int(n)" in m for m in msgs)  # static param


def test_numpy_outside_jit_clean():
    active, _ = scan(
        """
import numpy as np
def host_side(x):
    return np.asarray(x)
"""
    )
    assert "jit-host-sync" not in rules_of(active)


def test_pallas_kernel_nondeterminism_flagged():
    active, _ = scan(
        """
import functools
import time
from jax.experimental import pallas as pl
def _kernel(x_ref, o_ref):
    t = time.time()
    o_ref[...] = x_ref[...]
def op(x):
    k = functools.partial(_kernel)
    return pl.pallas_call(k)(x)
"""
    )
    assert "jit-nondeterminism" in rules_of(active)


def test_sched_nondeterminism_flagged_monotonic_clean():
    active, _ = scan(
        """
import time
class ContinuousBatcher:
    def run_multi(self, jobs):
        self._step()
    def _step(self):
        a = time.monotonic()
        b = time.time()
        return a, b
""",
        name="engine.scheduler",
        path="engine/scheduler.py",
    )
    found = [f for f in active if f.rule == "sched-nondeterminism"]
    assert len(found) == 1 and "time.time" in found[0].message


def test_sched_rule_scoped_to_scheduler_modules():
    active, _ = scan(
        """
import time
class ContinuousBatcher:
    def run_multi(self, jobs):
        return time.time()
""",
        name="engine.other",
        path="engine/other.py",
    )
    assert "sched-nondeterminism" not in rules_of(active)


# -------------------------------------------------------------- hygiene


def test_thread_hygiene_matrix():
    active, _ = scan(
        """
import threading
def f():
    a = threading.Thread(target=f, daemon=True)
    a.start()
    b = threading.Thread(target=f)
    b.start()
    b.join(timeout=5)
    c = threading.Thread(target=f)
    c.start()
    c.join()
    d = threading.Thread(target=f)
    d.start()
"""
    )
    by_rule = {}
    for f in active:
        by_rule.setdefault(f.rule, []).append(f)
    assert [f.key for f in by_rule.get("thread-unbounded-join", [])] == [
        "c"
    ]
    assert [f.key for f in by_rule.get("thread-unjoined", [])] == ["d"]


def test_silent_except_shapes():
    active, suppressed = scan(
        """
import logging
logger = logging.getLogger(__name__)
def swallow_pass():
    try:
        pass
    except Exception:
        pass
def swallow_default():
    try:
        pass
    except Exception:
        return {}
def narrowed_ok():
    try:
        pass
    except ValueError:
        pass
def logged_ok():
    try:
        pass
    except Exception:
        logger.warning("x")
def blessed():
    try:
        pass
    except Exception:  # graftlint: disable=silent-except
        pass
"""
    )
    silent = [f for f in active if f.rule == "silent-except"]
    assert {f.symbol.split(":")[-1] for f in silent} == {
        "swallow_pass",
        "swallow_default",
    }
    assert "silent-except" in rules_of(suppressed)


def test_unbounded_retry_matrix():
    active, suppressed = scan(
        """
import time
def unbounded_constant_sleep(op):
    while True:
        try:
            return op()
        except OSError:
            time.sleep(1)
def bounded_no_backoff(op):
    for attempt in range(5):
        try:
            return op()
        except OSError:
            time.sleep(1)
def bounded_backoff_ok(op):
    for attempt in range(5):
        try:
            return op()
        except OSError:
            time.sleep(0.1 * 2 ** attempt)
def deadline_guard_ok(op, delay):
    deadline = time.monotonic() + 5
    while True:
        try:
            return op()
        except OSError:
            if time.monotonic() >= deadline:
                raise
            delay *= 2
            time.sleep(delay)
def service_loop_not_retry(q):
    while True:
        try:
            q.get()
        except Exception:
            q.log()
def terminal_handler_not_retry(op):
    while True:
        try:
            return op()
        except OSError:
            raise
def blessed(op):
    while True:  # graftlint: disable=unbounded-retry
        try:
            return op()
        except OSError:
            time.sleep(1)
"""
    )
    found = {
        f.symbol.split(":")[-1]: f
        for f in active
        if f.rule == "unbounded-retry"
    }
    assert set(found) == {
        "unbounded_constant_sleep", "bounded_no_backoff"
    }, found
    assert "bound" in found["unbounded_constant_sleep"].key
    assert "backoff" in found["bounded_no_backoff"].key
    assert "unbounded-retry" in rules_of(suppressed)


def test_unbounded_retry_engine_fixes_hold():
    """The engine's own retry loops must satisfy the rule they drove:
    faults.retry_transient (bounded + exponential backoff) and the dp
    worker reconnect loop (deadline-bounded + backoff)."""
    idx = PackageIndex()
    for rel in ("engine/faults.py", "engine/dphost.py"):
        p = REPO / "sutro_tpu" / rel
        idx.add_file(p, rel)
    active, _ = core.apply_suppressions(idx, run_passes(idx))
    assert "unbounded-retry" not in rules_of(active), [
        f.render() for f in active
    ]


# -------------------------------------- baseline & suppression mechanics


def test_baseline_count_semantics():
    src_two = """
def f():
    try:
        pass
    except Exception:
        pass
    try:
        pass
    except Exception:
        pass
"""
    active, _ = scan(src_two)
    base = core.baseline_counts(active)
    new, stale = core.compare_baseline(active, base)
    assert not new and not stale
    # a third identical finding in the same function is NEW
    active3, _ = scan(
        src_two
        + """
    try:
        pass
    except Exception:
        pass
"""
    )
    new, _ = core.compare_baseline(active3, base)
    assert len(new) == 1


# ------------------------------------------------- self-scan & CLI gate


def test_self_scan_matches_committed_baseline():
    active, _suppressed, _ = core.analyze([str(REPO / "sutro_tpu")])
    # findings are path-keyed relative to the repo root in CI; re-key
    # the absolute scan the same way
    for f in active:
        f.path = str(Path(f.path).relative_to(REPO).as_posix())
    baseline = core.load_baseline(BASELINE)
    new, stale = core.compare_baseline(active, baseline)
    assert not new, [f.render() for f in new]
    assert not stale, stale
    # pin the accepted-debt count: growing it needs a conscious
    # baseline regeneration in the same commit
    assert len(active) == sum(baseline.values()) == 18


def run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "sutro_tpu.analysis", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=300,
    )


def test_cli_gate_green_on_tree():
    res = run_cli(["sutro_tpu"], cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 new" in res.stdout


def test_cli_unknown_rule_and_missing_path():
    assert run_cli(["--rules", "nope"], cwd=REPO).returncode == 2
    assert run_cli(["no/such/dir"], cwd=REPO).returncode == 2


def _copy_tree(tmp_path: Path) -> Path:
    dst = tmp_path / "sutro_tpu"
    shutil.copytree(
        REPO / "sutro_tpu",
        dst,
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    return dst


def test_injected_wall_clock_in_decode_path_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    sched = dst / "engine" / "scheduler.py"
    src = sched.read_text()
    anchor = "self._prep_pump(order)"
    assert anchor in src
    src = src.replace(
        anchor, anchor + "\n                _wall = time.time()", 1
    )
    sched.write_text(src)
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "sched-nondeterminism" in res.stdout


def test_injected_lock_inversion_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    metrics = dst / "engine" / "metrics.py"
    metrics.write_text(
        metrics.read_text()
        + """

def _injected_a(bus, jm):
    with bus._lock:
        with jm.lock:
            pass


def _injected_b(bus, jm):
    with jm.lock:
        with bus._lock:
            pass
"""
    )
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "lock-order" in res.stdout


def test_write_baseline_roundtrip(tmp_path):
    dst = _copy_tree(tmp_path)
    bl = tmp_path / "bl.json"
    res = run_cli(
        ["sutro_tpu", "--baseline", str(bl), "--write-baseline"],
        cwd=tmp_path,
    )
    assert res.returncode == 0
    data = json.loads(bl.read_text())
    assert data["tool"] == "graftlint" and data["counts"]
    res = run_cli(["sutro_tpu", "--baseline", str(bl)], cwd=tmp_path)
    assert res.returncode == 0


def test_json_report_shape():
    res = run_cli(
        ["sutro_tpu", "--no-baseline", "--format", "json"], cwd=REPO
    )
    assert res.returncode == 1  # findings exist without a baseline
    data = json.loads(res.stdout)
    assert data["tool"] == "graftlint"
    assert all(
        {"rule", "path", "line", "message", "fingerprint"}
        <= set(f)
        for f in data["findings"]
    )


# ----------------------------------------- engine fixes the pass drove


def test_datasets_corrupt_meta_logged_not_swallowed(tmp_path, caplog):
    from sutro_tpu.engine.datasets import DatasetStore

    store = DatasetStore(root=tmp_path)
    ds = store.create()
    (tmp_path / ds / ".meta.json").write_text("{not json")
    with caplog.at_level("WARNING", logger="sutro_tpu.engine.datasets"):
        listed = store.list_datasets()
    assert [d["dataset_id"] for d in listed] == [ds]
    assert any("unreadable .meta.json" in r.message for r in caplog.records)


def test_datasets_bad_schema_file_logged(tmp_path, caplog):
    from sutro_tpu.engine.datasets import DatasetStore

    store = DatasetStore(root=tmp_path)
    ds = store.create()
    (tmp_path / ds / "broken.parquet").write_bytes(b"not a parquet")
    with caplog.at_level("WARNING", logger="sutro_tpu.engine.datasets"):
        listed = store.list_datasets()
    assert listed[0]["schema"] == {}
    assert any("cannot read parquet schema" in r.message for r in caplog.records)


def test_jobstore_corrupt_record_skipped_with_log(tmp_path, caplog):
    from sutro_tpu.engine.jobstore import JobStore

    store = JobStore(root=tmp_path)
    good = store.create(model="m", num_rows=1)
    bad = tmp_path / "job-deadbeef"
    bad.mkdir()
    (bad / "record.json").write_text("{torn")
    with caplog.at_level("WARNING", logger="sutro_tpu.engine.jobstore"):
        listed = store.list_jobs()
    assert [r["job_id"] for r in listed] == [good.job_id]
    assert any("unreadable job record" in r.message for r in caplog.records)


def test_fsm_cpp_failure_classified_and_fallback_works(monkeypatch, caplog):
    import sutro_tpu.engine.constrain.cpp as cpp_mod
    from sutro_tpu.engine.constrain import TokenTable, compile_schema
    from sutro_tpu.engine.constrain.fsm import MaskCache
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    def boom(*a, **k):
        raise RuntimeError("simulated native failure")

    monkeypatch.setattr(cpp_mod, "CppMasker", boom)
    tok = ByteTokenizer(vocab_size=512)
    nfa = compile_schema(
        {
            "type": "object",
            "properties": {"x": {"type": "integer"}},
            "required": ["x"],
        }
    )
    with caplog.at_level("DEBUG", logger="sutro_tpu.engine.constrain.fsm"):
        cache = MaskCache(nfa, TokenTable(tok))
    assert cache._cpp is None
    assert any(
        "CppMasker init failed" in r.message for r in caplog.records
    )
    mask = cache.mask(nfa.initial())
    assert mask.any()  # pure-python walk still serves masks


def test_read_results_gated_on_terminal_status(tmp_path):
    """The finalize window (results.parquet renamed, SUCCEEDED not yet
    flipped) must be invisible: results serve only at SUCCEEDED."""
    import pandas as pd

    from sutro_tpu.engine.jobstore import JobStore
    from sutro_tpu.interfaces import JobStatus

    store = JobStore(root=tmp_path)
    rec = store.create(model="m", num_rows=1)
    store.set_status(rec.job_id, JobStatus.RUNNING)
    pd.DataFrame({"row_id": [0], "outputs": ["x"]}).to_parquet(
        tmp_path / rec.job_id / "results.parquet"
    )
    with pytest.raises(FileNotFoundError, match="status=RUNNING"):
        store.read_results(rec.job_id)
    store.set_status(rec.job_id, JobStatus.SUCCEEDED)
    assert store.read_results(rec.job_id)["outputs"].tolist() == ["x"]


def test_engine_close_joins_worker(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.engine.config import EngineConfig

    eng = LocalEngine(EngineConfig())
    assert eng._worker.is_alive()
    assert eng.close(timeout=10.0) is True
    assert not eng._worker.is_alive()


def test_reset_engine_closes_previous_singleton(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine import api as api_mod

    eng = api_mod.get_engine()
    worker = eng._worker
    api_mod.reset_engine()
    worker.join(timeout=10.0)
    assert not worker.is_alive()
