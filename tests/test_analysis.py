"""graftlint (sutro_tpu.analysis): rule fixtures (true positive, true
negative, suppressed), the self-scan baseline gate, injection
sensitivity on the real tree, and the engine fixes the passes drove
(narrowed excepts, bounded teardown)."""

import json
import shutil
import subprocess
import sys
import time
from pathlib import Path

import pytest

from sutro_tpu.analysis import core
from sutro_tpu.analysis.callgraph import PackageIndex
from sutro_tpu.analysis.core import run_passes

REPO = Path(__file__).resolve().parent.parent
BASELINE = REPO / "sutro_tpu" / "analysis" / "baseline.json"


def scan(src: str, name: str = "m", path: str = "m.py"):
    idx = PackageIndex()
    idx.add_source(path, src, name)
    active, suppressed = core.apply_suppressions(idx, run_passes(idx))
    return active, suppressed


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- locks


def test_lock_order_inversion_flagged():
    active, _ = scan(
        """
import threading
class S:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
    def f(self):
        with self.a_lock:
            with self.b_lock:
                pass
    def g(self):
        with self.b_lock:
            self.h()
    def h(self):
        with self.a_lock:
            pass
"""
    )
    assert "lock-order" in rules_of(active)
    (f,) = [f for f in active if f.rule == "lock-order"]
    assert "S.a_lock" in f.message and "S.b_lock" in f.message


def test_consistent_lock_order_clean():
    active, _ = scan(
        """
import threading
class S:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()
    def f(self):
        with self.a_lock:
            with self.b_lock:
                pass
    def g(self):
        with self.a_lock:
            with self.b_lock:
                pass
"""
    )
    assert "lock-order" not in rules_of(active)


def test_cross_function_inversion_on_shared_object():
    active, _ = scan(
        """
import threading
class Bus:
    def __init__(self):
        self._lock = threading.Lock()
    def a(self, jm):
        with self._lock:
            with jm.lock:
                pass
    def b(self, jm):
        with jm.lock:
            with self._lock:
                pass
"""
    )
    assert "lock-order" in rules_of(active)


def test_blocking_call_under_lock_direct_and_interprocedural():
    active, _ = scan(
        """
import threading, time
def helper():
    time.sleep(1)
def f():
    lock = threading.Lock()
    with lock:
        helper()
"""
    )
    found = [f for f in active if f.rule == "lock-blocking-call"]
    assert found and "time.sleep" in found[0].message
    assert "call chain" in found[0].message


def test_blocking_call_outside_lock_clean():
    active, _ = scan(
        """
import threading, time
def f():
    lock = threading.Lock()
    with lock:
        pass
    time.sleep(1)
"""
    )
    assert "lock-blocking-call" not in rules_of(active)


def test_blocking_call_suppressed():
    active, suppressed = scan(
        """
import threading, time
def f():
    lock = threading.Lock()
    with lock:
        time.sleep(1)  # graftlint: disable=lock-blocking-call
"""
    )
    assert "lock-blocking-call" not in rules_of(active)
    assert "lock-blocking-call" in rules_of(suppressed)


def test_thread_join_under_lock_blocks_string_join_does_not():
    active, _ = scan(
        """
import threading
def f():
    lock = threading.Lock()
    t = threading.Thread(target=f, daemon=True)
    t.start()
    with lock:
        t.join(timeout=5)
        s = ",".join(["a", "b"])
"""
    )
    found = [f for f in active if f.rule == "lock-blocking-call"]
    assert len(found) == 1 and "t.join" in found[0].message


def test_callback_under_lock_flagged_and_clean_outside():
    active, _ = scan(
        """
import threading
def f(on_result):
    lock = threading.Lock()
    with lock:
        on_result(1)
    on_result(2)
"""
    )
    found = [f for f in active if f.rule == "lock-callback"]
    assert len(found) == 1 and found[0].line == 6


def test_reentrant_lock_acquisition_flagged_rlock_clean():
    active, _ = scan(
        """
import threading
def bad():
    lock = threading.Lock()
    with lock:
        with lock:
            pass
def fine():
    r = threading.RLock()
    with r:
        with r:
            pass
"""
    )
    found = [f for f in active if f.rule == "lock-reentrant"]
    assert len(found) == 1 and "bad.lock" in found[0].message


def test_nested_def_under_lock_not_treated_as_running():
    active, _ = scan(
        """
import threading, time
def f():
    lock = threading.Lock()
    with lock:
        def later():
            time.sleep(1)
        return later
"""
    )
    assert "lock-blocking-call" not in rules_of(active)


# -------------------------------------------------------------- jitpure


def test_jit_host_sync_flagged():
    active, _ = scan(
        """
import functools
import jax
import numpy as np
@functools.partial(jax.jit, static_argnames=("n",))
def step(x, n):
    y = np.asarray(x)
    m = int(n)
    k = float(x)
    return y
"""
    )
    msgs = [f.message for f in active if f.rule == "jit-host-sync"]
    assert any("np.asarray" in m for m in msgs)
    assert any("float(x)" in m for m in msgs)  # traced param
    assert not any("int(n)" in m for m in msgs)  # static param


def test_numpy_outside_jit_clean():
    active, _ = scan(
        """
import numpy as np
def host_side(x):
    return np.asarray(x)
"""
    )
    assert "jit-host-sync" not in rules_of(active)


def test_pallas_kernel_nondeterminism_flagged():
    active, _ = scan(
        """
import functools
import time
from jax.experimental import pallas as pl
def _kernel(x_ref, o_ref):
    t = time.time()
    o_ref[...] = x_ref[...]
def op(x):
    k = functools.partial(_kernel)
    return pl.pallas_call(k)(x)
"""
    )
    assert "jit-nondeterminism" in rules_of(active)


def test_sched_nondeterminism_flagged_monotonic_clean():
    active, _ = scan(
        """
import time
class ContinuousBatcher:
    def run_multi(self, jobs):
        self._step()
    def _step(self):
        a = time.monotonic()
        b = time.time()
        return a, b
""",
        name="engine.scheduler",
        path="engine/scheduler.py",
    )
    found = [f for f in active if f.rule == "sched-nondeterminism"]
    assert len(found) == 1 and "time.time" in found[0].message


def test_sched_rule_scoped_to_scheduler_modules():
    active, _ = scan(
        """
import time
class ContinuousBatcher:
    def run_multi(self, jobs):
        return time.time()
""",
        name="engine.other",
        path="engine/other.py",
    )
    assert "sched-nondeterminism" not in rules_of(active)


# -------------------------------------------------------------- hygiene


def test_thread_hygiene_matrix():
    active, _ = scan(
        """
import threading
def f():
    a = threading.Thread(target=f, daemon=True)
    a.start()
    b = threading.Thread(target=f)
    b.start()
    b.join(timeout=5)
    c = threading.Thread(target=f)
    c.start()
    c.join()
    d = threading.Thread(target=f)
    d.start()
"""
    )
    by_rule = {}
    for f in active:
        by_rule.setdefault(f.rule, []).append(f)
    assert [f.key for f in by_rule.get("thread-unbounded-join", [])] == [
        "c"
    ]
    assert [f.key for f in by_rule.get("thread-unjoined", [])] == ["d"]


def test_silent_except_shapes():
    active, suppressed = scan(
        """
import logging
logger = logging.getLogger(__name__)
def swallow_pass():
    try:
        pass
    except Exception:
        pass
def swallow_default():
    try:
        pass
    except Exception:
        return {}
def narrowed_ok():
    try:
        pass
    except ValueError:
        pass
def logged_ok():
    try:
        pass
    except Exception:
        logger.warning("x")
def blessed():
    try:
        pass
    except Exception:  # graftlint: disable=silent-except
        pass
"""
    )
    silent = [f for f in active if f.rule == "silent-except"]
    assert {f.symbol.split(":")[-1] for f in silent} == {
        "swallow_pass",
        "swallow_default",
    }
    assert "silent-except" in rules_of(suppressed)


def test_unbounded_retry_matrix():
    active, suppressed = scan(
        """
import time
def unbounded_constant_sleep(op):
    while True:
        try:
            return op()
        except OSError:
            time.sleep(1)
def bounded_no_backoff(op):
    for attempt in range(5):
        try:
            return op()
        except OSError:
            time.sleep(1)
def bounded_backoff_ok(op):
    for attempt in range(5):
        try:
            return op()
        except OSError:
            time.sleep(0.1 * 2 ** attempt)
def deadline_guard_ok(op, delay):
    deadline = time.monotonic() + 5
    while True:
        try:
            return op()
        except OSError:
            if time.monotonic() >= deadline:
                raise
            delay *= 2
            time.sleep(delay)
def service_loop_not_retry(q):
    while True:
        try:
            q.get()
        except Exception:
            q.log()
def terminal_handler_not_retry(op):
    while True:
        try:
            return op()
        except OSError:
            raise
def blessed(op):
    while True:  # graftlint: disable=unbounded-retry
        try:
            return op()
        except OSError:
            time.sleep(1)
"""
    )
    found = {
        f.symbol.split(":")[-1]: f
        for f in active
        if f.rule == "unbounded-retry"
    }
    assert set(found) == {
        "unbounded_constant_sleep", "bounded_no_backoff"
    }, found
    assert "bound" in found["unbounded_constant_sleep"].key
    assert "backoff" in found["bounded_no_backoff"].key
    assert "unbounded-retry" in rules_of(suppressed)


def test_unbounded_retry_engine_fixes_hold():
    """The engine's own retry loops must satisfy the rule they drove:
    faults.retry_transient (bounded + exponential backoff) and the dp
    worker reconnect loop (deadline-bounded + backoff)."""
    idx = PackageIndex()
    for rel in ("engine/faults.py", "engine/dphost.py"):
        p = REPO / "sutro_tpu" / rel
        idx.add_file(p, rel)
    active, _ = core.apply_suppressions(idx, run_passes(idx))
    assert "unbounded-retry" not in rules_of(active), [
        f.render() for f in active
    ]


# -------------------------------------- baseline & suppression mechanics


def test_baseline_count_semantics():
    src_two = """
def f():
    try:
        pass
    except Exception:
        pass
    try:
        pass
    except Exception:
        pass
"""
    active, _ = scan(src_two)
    base = core.baseline_counts(active)
    new, stale = core.compare_baseline(active, base)
    assert not new and not stale
    # a third identical finding in the same function is NEW
    active3, _ = scan(
        src_two
        + """
    try:
        pass
    except Exception:
        pass
"""
    )
    new, _ = core.compare_baseline(active3, base)
    assert len(new) == 1


# ------------------------------------------------- self-scan & CLI gate


def test_self_scan_matches_committed_baseline():
    active, _suppressed, _ = core.analyze([str(REPO / "sutro_tpu")])
    # findings are path-keyed relative to the repo root in CI; re-key
    # the absolute scan the same way
    for f in active:
        f.path = str(Path(f.path).relative_to(REPO).as_posix())
    baseline = core.load_baseline(BASELINE)
    new, stale = core.compare_baseline(active, baseline)
    assert not new, [f.render() for f in new]
    assert not stale, stale
    # pin the accepted-debt count: growing it needs a conscious
    # baseline regeneration in the same commit
    assert len(active) == sum(baseline.values()) == 18


def run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "sutro_tpu.analysis", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=300,
    )


def test_cli_gate_green_on_tree():
    res = run_cli(["sutro_tpu"], cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 new" in res.stdout


def test_cli_unknown_rule_and_missing_path():
    assert run_cli(["--rules", "nope"], cwd=REPO).returncode == 2
    assert run_cli(["no/such/dir"], cwd=REPO).returncode == 2


def _copy_tree(tmp_path: Path) -> Path:
    dst = tmp_path / "sutro_tpu"
    shutil.copytree(
        REPO / "sutro_tpu",
        dst,
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    return dst


def test_injected_wall_clock_in_decode_path_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    sched = dst / "engine" / "scheduler.py"
    src = sched.read_text()
    anchor = "self._prep_pump(order)"
    assert anchor in src
    src = src.replace(
        anchor, anchor + "\n                _wall = time.time()", 1
    )
    sched.write_text(src)
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "sched-nondeterminism" in res.stdout


def test_injected_lock_inversion_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    metrics = dst / "engine" / "metrics.py"
    metrics.write_text(
        metrics.read_text()
        + """

def _injected_a(bus, jm):
    with bus._lock:
        with jm.lock:
            pass


def _injected_b(bus, jm):
    with jm.lock:
        with bus._lock:
            pass
"""
    )
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "lock-order" in res.stdout


def test_write_baseline_roundtrip(tmp_path):
    dst = _copy_tree(tmp_path)
    bl = tmp_path / "bl.json"
    res = run_cli(
        ["sutro_tpu", "--baseline", str(bl), "--write-baseline"],
        cwd=tmp_path,
    )
    assert res.returncode == 0
    data = json.loads(bl.read_text())
    assert data["tool"] == "graftlint" and data["counts"]
    res = run_cli(["sutro_tpu", "--baseline", str(bl)], cwd=tmp_path)
    assert res.returncode == 0


def test_json_report_shape():
    res = run_cli(
        ["sutro_tpu", "--no-baseline", "--format", "json"], cwd=REPO
    )
    assert res.returncode == 1  # findings exist without a baseline
    data = json.loads(res.stdout)
    assert data["tool"] == "graftlint"
    assert all(
        {"rule", "path", "line", "message", "fingerprint"}
        <= set(f)
        for f in data["findings"]
    )


# ----------------------------------------- engine fixes the pass drove


def test_datasets_corrupt_meta_logged_not_swallowed(tmp_path, caplog):
    from sutro_tpu.engine.datasets import DatasetStore

    store = DatasetStore(root=tmp_path)
    ds = store.create()
    (tmp_path / ds / ".meta.json").write_text("{not json")
    with caplog.at_level("WARNING", logger="sutro_tpu.engine.datasets"):
        listed = store.list_datasets()
    assert [d["dataset_id"] for d in listed] == [ds]
    assert any("unreadable .meta.json" in r.message for r in caplog.records)


def test_datasets_bad_schema_file_logged(tmp_path, caplog):
    from sutro_tpu.engine.datasets import DatasetStore

    store = DatasetStore(root=tmp_path)
    ds = store.create()
    (tmp_path / ds / "broken.parquet").write_bytes(b"not a parquet")
    with caplog.at_level("WARNING", logger="sutro_tpu.engine.datasets"):
        listed = store.list_datasets()
    assert listed[0]["schema"] == {}
    assert any("cannot read parquet schema" in r.message for r in caplog.records)


def test_jobstore_corrupt_record_skipped_with_log(tmp_path, caplog):
    from sutro_tpu.engine.jobstore import JobStore

    store = JobStore(root=tmp_path)
    good = store.create(model="m", num_rows=1)
    bad = tmp_path / "job-deadbeef"
    bad.mkdir()
    (bad / "record.json").write_text("{torn")
    with caplog.at_level("WARNING", logger="sutro_tpu.engine.jobstore"):
        listed = store.list_jobs()
    assert [r["job_id"] for r in listed] == [good.job_id]
    assert any("unreadable job record" in r.message for r in caplog.records)


def test_fsm_cpp_failure_classified_and_fallback_works(monkeypatch, caplog):
    import sutro_tpu.engine.constrain.cpp as cpp_mod
    from sutro_tpu.engine.constrain import TokenTable, compile_schema
    from sutro_tpu.engine.constrain.fsm import MaskCache
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    def boom(*a, **k):
        raise RuntimeError("simulated native failure")

    monkeypatch.setattr(cpp_mod, "CppMasker", boom)
    tok = ByteTokenizer(vocab_size=512)
    nfa = compile_schema(
        {
            "type": "object",
            "properties": {"x": {"type": "integer"}},
            "required": ["x"],
        }
    )
    with caplog.at_level("DEBUG", logger="sutro_tpu.engine.constrain.fsm"):
        cache = MaskCache(nfa, TokenTable(tok))
    assert cache._cpp is None
    assert any(
        "CppMasker init failed" in r.message for r in caplog.records
    )
    mask = cache.mask(nfa.initial())
    assert mask.any()  # pure-python walk still serves masks


def test_read_results_gated_on_terminal_status(tmp_path):
    """The finalize window (results.parquet renamed, SUCCEEDED not yet
    flipped) must be invisible: results serve only at SUCCEEDED."""
    import pandas as pd

    from sutro_tpu.engine.jobstore import JobStore
    from sutro_tpu.interfaces import JobStatus

    store = JobStore(root=tmp_path)
    rec = store.create(model="m", num_rows=1)
    store.set_status(rec.job_id, JobStatus.RUNNING)
    pd.DataFrame({"row_id": [0], "outputs": ["x"]}).to_parquet(
        tmp_path / rec.job_id / "results.parquet"
    )
    with pytest.raises(FileNotFoundError, match="status=RUNNING"):
        store.read_results(rec.job_id)
    store.set_status(rec.job_id, JobStatus.SUCCEEDED)
    assert store.read_results(rec.job_id)["outputs"].tolist() == ["x"]


def test_engine_close_joins_worker(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.engine.config import EngineConfig

    eng = LocalEngine(EngineConfig())
    assert eng._worker.is_alive()
    assert eng.close(timeout=10.0) is True
    assert not eng._worker.is_alive()


def test_reset_engine_closes_previous_singleton(tmp_path, monkeypatch):
    monkeypatch.setenv("SUTRO_HOME", str(tmp_path))
    from sutro_tpu.engine import api as api_mod

    eng = api_mod.get_engine()
    worker = eng._worker
    api_mod.reset_engine()
    worker.join(timeout=10.0)
    assert not worker.is_alive()


# ------------------------------------------------- resource lifecycle


def test_resource_leak_on_early_return():
    active, _ = scan(
        """
import socket

def f(flag):
    s = socket.create_connection(("h", 1))
    if flag:
        return None
    s.sendall(b"x")
    s.close()
    return s
"""
    )
    (f,) = [f for f in active if f.rule == "resource-leak"]
    assert f.key == "socket:s" and "early return" in f.message


def test_resource_leak_on_exception_edge():
    # the function owns kv-pages (it frees them on the happy path), so
    # a call that can raise between alloc and free leaks the pages
    active, _ = scan(
        """
def f(alloc, work):
    pages = alloc.alloc(4)
    work(1)
    alloc.free(pages)
"""
    )
    (f,) = [f for f in active if f.rule == "resource-leak"]
    assert f.key == "kv-pages:pages" and "exception path" in f.message


def test_resource_release_in_handler_is_clean():
    active, _ = scan(
        """
def g(alloc, work):
    pages = alloc.alloc(4)
    try:
        work(1)
    except Exception:
        alloc.free(pages)
        raise
    alloc.free(pages)
"""
    )
    assert "resource-leak" not in rules_of(active)


def test_resource_daemon_thread_untracked():
    active, _ = scan(
        """
import threading

def h(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
"""
    )
    assert "resource-leak" not in rules_of(active)


def test_resource_none_branch_refined_away():
    # `if h is None: return` is a miss, not a leak
    active, _ = scan(
        """
def f(store):
    h = store.lookup_pin("k")
    if h is None:
        return 0
    store.release(h)
    return 1
"""
    )
    assert "resource-leak" not in rules_of(active)


def test_resource_return_escape_transfers_ownership():
    active, _ = scan(
        """
from serving.channel import StreamChannel

def mk():
    ch = StreamChannel()
    return ch
"""
    )
    assert "resource-leak" not in rules_of(active)


def test_resource_leak_pragma_suppressed():
    active, suppressed = scan(
        """
import socket

def f(flag):
    s = socket.create_connection(("h", 1))  # graftlint: disable=resource-leak
    if flag:
        return None
    s.close()
    return None
"""
    )
    assert "resource-leak" not in rules_of(active)
    assert "resource-leak" in rules_of(suppressed)


def test_resource_double_release_flagged():
    active, _ = scan(
        """
def f(alloc):
    pages = alloc.alloc(2)
    alloc.free(pages)
    alloc.free(pages)
"""
    )
    (f,) = [f for f in active if f.rule == "resource-double-release"]
    assert f.key == "kv-pages:pages"


def test_resource_release_on_each_branch_is_clean():
    active, _ = scan(
        """
def f(alloc, ok):
    pages = alloc.alloc(2)
    if ok:
        alloc.free(pages)
    else:
        alloc.free(pages)
"""
    )
    assert "resource-double-release" not in rules_of(active)
    assert "resource-leak" not in rules_of(active)


# ------------------------------------------------- trace context


def test_trace_ctx_dropped_on_early_return():
    active, _ = scan(
        """
def f(store, flag):
    tr = store.start_trace("tr-1", "interactive")
    if flag:
        return None
    tr.end("ok")
    return None
"""
    )
    (f,) = [f for f in active if f.rule == "trace-ctx-dropped"]
    assert f.key == "trace-ctx:tr" and "early return" in f.message


def test_trace_ctx_ended_by_id_or_method_is_clean():
    active, _ = scan(
        """
def f(store, flag):
    tr = store.start_trace("tr-1")
    if flag:
        store.end_trace(tr)
        return 1
    tr.end("err")
    return 0
"""
    )
    assert "trace-ctx-dropped" not in rules_of(active)


def test_trace_ctx_bare_start_is_cross_function_handoff():
    # the gateway pattern: no handle bound, the id string IS the
    # propagated context — finish() ends it elsewhere
    active, _ = scan(
        """
def submit(store, rid):
    store.start_trace(f"tr-{rid}", "interactive")
    return rid
"""
    )
    assert "trace-ctx-dropped" not in rules_of(active)


def test_trace_ctx_return_escape_transfers_ownership():
    active, _ = scan(
        """
def start(store):
    tr = store.start_trace("tr-1")
    return tr
"""
    )
    assert "trace-ctx-dropped" not in rules_of(active)


def test_trace_ctx_pragma_suppressed():
    active, suppressed = scan(
        """
def f(store, flag):
    tr = store.start_trace("tr-1")  # graftlint: disable=trace-ctx-dropped
    if flag:
        return None
    tr.end("ok")
    return None
"""
    )
    assert "trace-ctx-dropped" not in rules_of(active)
    assert "trace-ctx-dropped" in rules_of(suppressed)


# ------------------------------------------------- wire protocol


def _wire_idx(src: str) -> PackageIndex:
    idx = PackageIndex()
    idx.add_source("dphost.py", src, "dphost")
    return idx


def test_wire_key_removed_vs_schema():
    from sutro_tpu.analysis import protocol

    idx = _wire_idx(
        """
def _send(sock, m):
    pass

def send_res(sock):
    _send(sock, {"t": "res", "rows": 1})
"""
    )
    schema = {
        "version": 1,
        "frames": {"res": ["t", "rows", "gone"], "hb": ["t"]},
    }
    fs = protocol.run(idx, schema=schema)
    assert sorted(f.key for f in fs if f.rule == "wire-key-removed") == [
        "hb",  # whole frame vanished
        "res.gone",  # one key vanished
    ]


def test_wire_added_keys_are_fine():
    from sutro_tpu.analysis import protocol

    idx = _wire_idx(
        """
def _send(sock, m):
    pass

def send_res(sock):
    m = {"t": "res", "rows": 1}
    m["extra"] = 2
    _send(sock, m)

def parse(m):
    return m.get("rows", 0)
"""
    )
    schema = {"version": 1, "frames": {"res": ["t", "rows"]}}
    assert protocol.run(idx, schema=schema) == []


def test_wire_strict_parse_flagged():
    from sutro_tpu.analysis import protocol

    idx = _wire_idx(
        """
def _send(sock, m):
    pass

def parse(m):
    if set(m) == {"t", "rows"}:
        pass
    for k in m:
        if k not in ("t", "rows"):
            raise ValueError(k)
"""
    )
    fs = protocol.run(idx, schema={"version": 1, "frames": {}})
    assert sorted(f.key for f in fs if f.rule == "wire-strict-parse") == [
        "shape-eq",
        "unknown-key-raise",
    ]


def test_wire_pass_ignores_non_wire_modules():
    # frame-shaped dicts in ordinary modules aren't wire frames
    active, _ = scan(
        """
def build():
    return {"t": "res", "rows": 1}

def parse(m):
    if set(m) == {"t"}:
        raise ValueError(m)
"""
    )
    assert "wire-strict-parse" not in rules_of(active)
    assert "wire-key-removed" not in rules_of(active)


# ------------------------------------------------- kill-switch zero-op


def test_killswitch_bare_metric_write_flagged():
    active, _ = scan(
        """
import os
import telemetry

ENABLED = os.environ.get("SUTRO_TELEMETRY", "1") not in ("0",)

def hot():
    telemetry.ROWS_TOTAL.inc(1.0, "ok")
"""
    )
    (f,) = [f for f in active if f.rule == "killswitch-ungated"]
    assert f.key == "telemetry:ROWS_TOTAL.inc"


def test_killswitch_gate_and_guard_clause_clean():
    active, _ = scan(
        """
import os
import telemetry

ENABLED = os.environ.get("SUTRO_TELEMETRY", "1") not in ("0",)

def gated():
    if ENABLED:
        telemetry.ROWS_TOTAL.inc(1.0, "ok")

def guarded():
    if not ENABLED:
        return
    telemetry.ROWS_TOTAL.inc(1.0, "ok")
"""
    )
    assert "killswitch-ungated" not in rules_of(active)


def test_killswitch_internally_gated_callee_clean():
    # stage_observe checks the flag itself; callers stay bare
    idx = PackageIndex()
    idx.add_source(
        "telemetry/__init__.py",
        """
import os

ENABLED = os.environ.get("SUTRO_TELEMETRY", "1") not in ("0",)

def stage_observe(stage, dur):
    if not ENABLED:
        return
    STAGE.observe(dur, stage)
""",
        "telemetry",
    )
    idx.add_source(
        "m.py",
        """
import telemetry

def hot():
    telemetry.stage_observe("decode", 0.1)
""",
        "m",
    )
    active, _ = core.apply_suppressions(idx, run_passes(idx))
    assert "killswitch-ungated" not in rules_of(active)


def test_killswitch_pragma_suppressed():
    active, suppressed = scan(
        """
import os
import telemetry

ENABLED = os.environ.get("SUTRO_TELEMETRY", "1") not in ("0",)

def hot():
    telemetry.ROWS_TOTAL.inc(1.0, "ok")  # graftlint: disable=killswitch-ungated
"""
    )
    assert "killswitch-ungated" not in rules_of(active)
    assert "killswitch-ungated" in rules_of(suppressed)


# ------------------------------------------------- telemetry cardinality


def test_cardinality_uncapped_and_identifier_labels():
    active, _ = scan(
        """
C_UNCAPPED = REGISTRY.counter("m_total", "h", labels=("stage",))
C_CAPPED = REGISTRY.counter("n_total", "h", labels=("stage",), max_series=8)

def f(stage, job_id):
    C_UNCAPPED.inc(1.0, stage)
    C_CAPPED.inc(1.0, job_id)
    C_CAPPED.inc(1.0, f"job-{job_id}")
"""
    )
    keys = sorted(
        f.key for f in active if f.rule == "telemetry-cardinality"
    )
    assert keys == [
        "m_total:uncapped",  # non-const label, no max_series budget
        "n_total:identifier",  # job_id name
        "n_total:identifier",  # f-string
    ]


def test_cardinality_capped_nonconst_and_const_labels_clean():
    active, _ = scan(
        """
C_CAPPED = REGISTRY.counter("n_total", "h", labels=("stage",), max_series=8)

def f(stage):
    C_CAPPED.inc(1.0, stage)
    C_CAPPED.inc(1.0, "const")
"""
    )
    assert "telemetry-cardinality" not in rules_of(active)


# ------------------------------------------------- stale suppressions


def scan_with_stale(src: str):
    idx = PackageIndex()
    idx.add_source("m.py", src, "m")
    active, suppressed = core.apply_suppressions(idx, run_passes(idx))
    active.extend(core.stale_suppression_findings(idx, suppressed))
    return active, suppressed


def test_stale_suppression_flagged():
    active, _ = scan_with_stale(
        """
x = 1  # graftlint: disable=lock-order
"""
    )
    (f,) = [f for f in active if f.rule == "stale-suppression"]
    assert "lock-order" in f.message


def test_masking_suppression_is_not_stale():
    active, suppressed = scan_with_stale(
        """
import socket

def f(flag):
    s = socket.create_connection(("h", 1))  # graftlint: disable=resource-leak
    if flag:
        return None
    s.close()
    return None
"""
    )
    assert active == []
    assert len(suppressed) == 1


# --------------------------------------- injection gates: new passes


def test_injected_wire_key_removal_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    dp = dst / "engine" / "dphost.py"
    src = dp.read_text()
    anchor = '{"t": "reshard", "rows": sorted(rows)}'
    assert anchor in src
    dp.write_text(src.replace(anchor, '{"t": "reshard"}', 1))
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "wire-key-removed" in res.stdout
    assert "reshard" in res.stdout


def test_injected_dropped_release_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    sched = dst / "engine" / "scheduler.py"
    src = sched.read_text()
    anchor = "store.release(handle)"
    assert anchor in src
    sched.write_text(src.replace(anchor, 'logger.debug("skip")', 1))
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "resource-leak" in res.stdout


def test_injected_ungated_metric_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    js = dst / "engine" / "jobstore.py"
    js.write_text(
        js.read_text()
        + """

def _injected_hot(n):
    telemetry.ROWS_TOTAL.inc(float(n), "injected")
"""
    )
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "killswitch-ungated" in res.stdout


def test_injected_dropped_trace_handle_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    gw = dst / "serving" / "gateway.py"
    gw.write_text(
        gw.read_text()
        + """

def _injected_trace(flag):
    tr = telemetry.TRACES.start_trace("tr-injected")
    if flag:
        return None
    tr.end("ok")
    return None
"""
    )
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "trace-ctx-dropped" in res.stdout


def test_injected_unforwarded_fleet_trace_fails_gate(tmp_path):
    """The fleet sub-pass of trace-ctx-dropped: strip the router's
    ``trace_id=tid`` forwarding from its upstream relay — the request
    still works, but the replica half of every cross-process stitch is
    silently lost, and the gate must catch exactly that."""
    dst = _copy_tree(tmp_path)
    rt = dst / "fleet" / "router.py"
    src = rt.read_text()
    anchor = "                    trace_id=tid,\n"
    assert anchor in src
    rt.write_text(src.replace(anchor, "", 1))
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "trace-ctx-dropped" in res.stdout
    assert "fleet/router.py" in res.stdout
    assert "never forwarded" in res.stdout


def test_injected_identifier_label_fails_gate(tmp_path):
    dst = _copy_tree(tmp_path)
    js = dst / "engine" / "jobstore.py"
    js.write_text(
        js.read_text()
        + """

def _injected_label(job_id):
    if telemetry.ENABLED:
        telemetry.ROWS_TOTAL.inc(1.0, f"job-{job_id}")
"""
    )
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "telemetry-cardinality" in res.stdout
    assert "killswitch-ungated" not in res.stdout  # the gate is honored


# --------------------------------------------------------- diff mode


def test_diff_mode_scopes_findings_to_changed_lines(tmp_path):
    dst = _copy_tree(tmp_path)

    def git(*a):
        subprocess.run(
            ["git", "-c", "user.email=t@t.t", "-c", "user.name=t", *a],
            cwd=tmp_path,
            check=True,
            capture_output=True,
        )

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    # clean tree: baselined findings exist, but no changed lines
    res = run_cli(["sutro_tpu", "--diff", "HEAD"], cwd=tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 finding(s) on lines changed" in res.stdout
    # a violation on a changed line is reported without a baseline
    sched = dst / "engine" / "scheduler.py"
    src = sched.read_text()
    anchor = "self._prep_pump(order)"
    src = src.replace(
        anchor, anchor + "\n                _wall = time.time()", 1
    )
    sched.write_text(src)
    res = run_cli(["sutro_tpu", "--diff", "HEAD"], cwd=tmp_path)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "sched-nondeterminism" in res.stdout
    assert "finding(s) on lines changed vs HEAD" in res.stdout


# ----------------------------- engine fixes the new passes drove


def test_openai_collect_cancels_channel_on_decoder_error():
    from types import SimpleNamespace

    from sutro_tpu.serving import openai as oai
    from sutro_tpu.serving.channel import StreamChannel

    ch = StreamChannel()
    ch.put_token(0, 1, 0.0)

    def bad_decoder():
        def d(tok):
            raise ValueError("decoder boom")

        return d

    ir = SimpleNamespace(
        channel=ch,
        decoder=bad_decoder,
        prompt_tokens=1,
        id="req-1",
        created_unix=0,
        model="m",
    )
    with pytest.raises(ValueError, match="decoder boom"):
        oai.collect(ir, chat=False, timeout=5.0)
    # the producer side must stop too: without cancel() the scheduler
    # keeps generating tokens for a stream nobody reads
    assert ch.cancelled


def test_prefix_store_counters_gated_on_kill_switch():
    import numpy as np

    from sutro_tpu import telemetry
    from sutro_tpu.engine.prefixstore import PrefixStore

    def misses():
        return (
            telemetry.REGISTRY.collect()
            .get("sutro_prefix_store_misses_total", {})
            .get("series", {})
            .get("", 0.0)
        )

    prev = telemetry.ENABLED
    try:
        telemetry.set_enabled(False)
        s = PrefixStore(8)
        before = misses()
        h = s.lookup_pin(np.arange(32, dtype=np.int32))
        s.release(h)
        assert misses() == before  # switch off means zero work
        telemetry.set_enabled(True)
        h = s.lookup_pin(np.arange(64, dtype=np.int32) + 1000)
        s.release(h)
        assert misses() == before + 1
    finally:
        telemetry.set_enabled(prev)


def test_stage_observe_is_zero_op_when_disabled():
    from sutro_tpu import telemetry

    prev = telemetry.ENABLED
    try:
        telemetry.set_enabled(False)
        before = (
            telemetry.REGISTRY.collect()
            .get("sutro_stage_seconds", {})
            .get("series", {})
        )
        telemetry.stage_observe("zz_probe_disabled", 1.0)
        after = (
            telemetry.REGISTRY.collect()
            .get("sutro_stage_seconds", {})
            .get("series", {})
        )
        assert before == after
    finally:
        telemetry.set_enabled(prev)


def test_preemption_priority_labels_bounded():
    from sutro_tpu.engine.control import _prio_label

    assert _prio_label(3) == "3"
    assert _prio_label(-1) == "-1"
    assert _prio_label(0) == "0"
    # out-of-ladder priorities collapse instead of minting new series
    assert _prio_label(999) == "other"
    assert _prio_label(-7) == "other"


def test_failure_log_label_collapses_nonstring_kind(tmp_path):
    from sutro_tpu import telemetry
    from sutro_tpu.engine.jobstore import JobStore

    prev = telemetry.ENABLED
    try:
        telemetry.set_enabled(True)
        store = JobStore(root=tmp_path)
        rec = store.create(model="m", num_rows=1)
        store.append_failure_log(rec.job_id, {"event": 123})
        series = telemetry.REGISTRY.collect()[
            "sutro_failure_events_total"
        ]["series"]
        assert "123" not in series
        assert series.get("unknown", 0) >= 1
    finally:
        telemetry.set_enabled(prev)


# ------------------------------------------ data races / atomicity (v3)

RACE_RULES = {
    "shared-state-unlocked",
    "lockset-inconsistent",
    "check-then-act",
}


def race_findings(findings):
    return [f for f in findings if f.rule in RACE_RULES]


def test_shared_state_unlocked_flagged():
    active, _ = scan(
        """
import threading

class C:
    def __init__(self):
        self.n = 0
        self._t = threading.Thread(target=self._work, daemon=True)
        self._t.start()

    def _work(self):
        self.n += 1

    def read(self):
        return self.n
"""
    )
    hits = race_findings(active)
    assert [f.rule for f in hits] == ["shared-state-unlocked"]
    assert "C.n" in hits[0].message


def test_shared_state_common_lock_clean():
    active, _ = scan(
        """
import threading

class C:
    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._work, daemon=True)
        self._t.start()

    def _work(self):
        with self._lock:
            self.n += 1

    def read(self):
        with self._lock:
            return self.n
"""
    )
    assert race_findings(active) == []


def test_lockset_inconsistent_disjoint_locks():
    active, _ = scan(
        """
import threading

class C:
    def __init__(self):
        self.n = 0
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._t = threading.Thread(target=self._work, daemon=True)
        self._t.start()

    def _work(self):
        with self._a:
            self.n += 1

    def read(self):
        with self._b:
            return self.n
"""
    )
    assert [f.rule for f in race_findings(active)] == [
        "lockset-inconsistent"
    ]


def test_join_orders_spawner_accesses():
    active, _ = scan(
        """
import threading

class C:
    def run(self):
        t = threading.Thread(target=self._work)
        t.start()
        t.join()
        return self.n

    def _work(self):
        self.n += 1
"""
    )
    assert race_findings(active) == []


def test_queue_handoff_counts_as_happens_before():
    active, _ = scan(
        """
import queue
import threading

class C:
    def __init__(self):
        self.q = queue.Queue()
        self.latest = None
        self._t = threading.Thread(target=self._work, daemon=True)
        self._t.start()

    def _work(self):
        while True:
            item = self.q.get()
            self.latest = item

    def peek(self):
        self.q.put(1)
        return self.latest
"""
    )
    assert race_findings(active) == []


def test_publication_before_start_exempt_after_start_flagged():
    """Writes in the spawner BEFORE .start() are publication (clean);
    the same write moved after the start races the fresh thread."""
    before = """
import threading

class C:
    def __init__(self):
        self.cfg = {}
        self._t = threading.Thread(target=self._work, daemon=True)
        self.cfg = {"ready": True}
        self._t.start()

    def _work(self):
        if self.cfg:
            pass
"""
    active, _ = scan(before)
    assert race_findings(active) == []
    after = before.replace(
        '        self.cfg = {"ready": True}\n        self._t.start()',
        '        self._t.start()\n        self.cfg = {"ready": True}',
    )
    assert after != before
    active, _ = scan(after)
    assert [f.rule for f in race_findings(active)] == [
        "shared-state-unlocked"
    ]


def test_shared_state_suppressed():
    active, suppressed = scan(
        """
import threading

class C:
    def __init__(self):
        self.n = 0
        self._t = threading.Thread(target=self._work, daemon=True)
        self._t.start()

    def _work(self):
        self.n += 1  # graftlint: disable=shared-state-unlocked

    def read(self):
        return self.n
"""
    )
    assert race_findings(active) == []
    assert [f.rule for f in race_findings(suppressed)] == [
        "shared-state-unlocked"
    ]


def test_check_then_act_split_rmw_flagged():
    active, _ = scan(
        """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            cur = self.count
        with self._lock:
            self.count = cur + 1
"""
    )
    hits = race_findings(active)
    assert [f.rule for f in hits] == ["check-then-act"]
    assert "C.count" in hits[0].message


def test_check_then_act_single_block_and_rebind_clean():
    active, _ = scan(
        """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.cache = None

    def bump(self):
        with self._lock:
            self.count += 1

    def rebuild(self):
        # double-checked publish: `tok` is rebuilt from scratch between
        # the two critical sections, so no stale read flows into the
        # second write
        with self._lock:
            tok = self.cache
        if tok is None:
            tok = object()
        with self._lock:
            self.cache = tok
"""
    )
    assert race_findings(active) == []


def test_threads_inventory_cli():
    res = run_cli(["sutro_tpu", "--threads"], cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    out = res.stdout
    assert "Monitor._loop" in out
    assert "LocalEngine._worker_loop" in out
    assert "KVTierPool._run_worker" in out
    # one line per root, not per spawn re-visit (dedupe regression)
    assert out.count("KVTierPool._run_worker") == 1
    assert "thread root(s)" in out


def test_sarif_report_shape():
    res = run_cli(
        ["sutro_tpu", "--no-baseline", "--format", "sarif"], cwd=REPO
    )
    assert res.returncode == 1  # findings exist without a baseline
    doc = json.loads(res.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert run["results"]
    for r in run["results"]:
        assert r["ruleId"] in rule_ids
        assert r["partialFingerprints"]["graftlint/v1"]
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1


def test_injected_unlocked_write_fails_gate(tmp_path):
    """Deleting a real lock acquisition (set_rules' guard on the rule
    tables) must trip shared-state-unlocked against the baseline."""
    dst = _copy_tree(tmp_path)
    mon = dst / "telemetry" / "monitor.py"
    src = mon.read_text()
    old = (
        "        with self._lock:\n"
        "            self._rules = list(rules)\n"
        "            self._rule_state = "
        "{r.name: _RuleState() for r in self._rules}"
    )
    assert old in src
    new = (
        "        self._rules = list(rules)\n"
        "        self._rule_state = "
        "{r.name: _RuleState() for r in self._rules}"
    )
    mon.write_text(src.replace(old, new, 1))
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "shared-state-unlocked" in res.stdout


def test_injected_split_rmw_fails_gate(tmp_path):
    """Splitting a guarded RMW (the prep-overlap counter) across two
    critical sections must trip check-then-act against the baseline."""
    dst = _copy_tree(tmp_path)
    sched = dst / "engine" / "scheduler.py"
    src = sched.read_text()
    old = (
        "            with self._prep_lock:\n"
        "                self.prep_overlap_s += dt"
    )
    assert old in src
    new = (
        "            with self._prep_lock:\n"
        "                _cur = self.prep_overlap_s\n"
        "            with self._prep_lock:\n"
        "                self.prep_overlap_s = _cur + dt"
    )
    sched.write_text(src.replace(old, new, 1))
    res = run_cli(
        ["sutro_tpu", "--baseline", str(BASELINE)], cwd=tmp_path
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "check-then-act" in res.stdout


def test_lint_wall_time_within_tier1_budget():
    """The whole-tree scan must fit the 60s tier-1 budget the Makefile
    enforces (timeout would hard-fail CI; this catches creep early)."""
    t0 = time.perf_counter()
    core.analyze([str(REPO / "sutro_tpu")])
    assert time.perf_counter() - t0 < 60.0
