"""Ring-attention sequence parallelism (ops/ring_attention.py) on the
8-way virtual CPU mesh: numerical parity with dense attention, TP
composition, and full-model prefill parity (SURVEY §5.7 TPU plan)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS
from sutro_tpu.ops.attention import chunk_attention
from sutro_tpu.ops.ring_attention import ring_self_attention
from sutro_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    B, T, NH, KVH, Dh = 2, 32, 4, 2, 8
    q = jnp.asarray(rng.standard_normal((B, T, NH, Dh)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, KVH, Dh)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, KVH, Dh)), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    valid = jnp.asarray([20, 32], jnp.int32)
    return q, k, v, pos, valid


def _assert_close(out, ref, valid):
    # compare only valid query rows (padding queries are undefined)
    for b, n in enumerate(np.asarray(valid)):
        np.testing.assert_allclose(
            np.asarray(out[b, :n]), np.asarray(ref[b, :n]), atol=1e-5
        )


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
@pytest.mark.parametrize("sp,tp", [(4, 1), (8, 1), (2, 2), (1, 2)])
def test_ring_matches_dense(eight_devices, qkv, sp, tp):
    q, k, v, pos, valid = qkv
    ref = chunk_attention(q, k, v, positions=pos, valid_len=valid)
    mesh = make_mesh(1, 1, tp, eight_devices[: sp * tp], sp=sp)
    out = ring_self_attention(mesh, q, k, v, positions=pos, valid_len=valid)
    _assert_close(out, ref, valid)


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
def test_ring_window_and_sink(eight_devices, qkv):
    q, k, v, pos, valid = qkv
    sink = jnp.asarray(
        np.random.default_rng(1).standard_normal(q.shape[2]), jnp.float32
    )
    win = jnp.asarray(8, jnp.int32)
    ref = chunk_attention(
        q, k, v, positions=pos, valid_len=valid, window=win, sink=sink
    )
    mesh = make_mesh(1, 1, 2, eight_devices, sp=4)
    out = ring_self_attention(
        mesh, q, k, v, positions=pos, valid_len=valid, window=win, sink=sink
    )
    _assert_close(out, ref, valid)


def test_ring_rejects_indivisible_t(eight_devices):
    mesh = make_mesh(1, 1, 1, eight_devices[:4], sp=4)
    q = jnp.zeros((1, 30, 4, 8), jnp.float32)
    kv = jnp.zeros((1, 30, 2, 8), jnp.float32)
    pos = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        ring_self_attention(
            mesh, q, kv, kv, positions=pos,
            valid_len=jnp.asarray([30], jnp.int32),
        )


def _ecfg(**kw):
    base = dict(
        kv_page_size=8, max_pages_per_seq=8, decode_batch_size=4,
        max_model_len=64, use_pallas=False, param_dtype="float32",
    )
    base.update(kw)
    return EngineConfig(**base)


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
@pytest.mark.parametrize("model", ["tiny-dense", "tiny-oss"])
def test_sp_prefill_matches_single_device(eight_devices, model):
    """Full-model prefill + follow-on greedy decode must be identical with
    the prompt sharded over the seq axis (incl. sliding-window + sink
    layers via tiny-oss)."""
    cfg = MODEL_CONFIGS[model]
    prompt = (np.arange(23, dtype=np.int32) * 7) % 199

    def run(mesh):
        runner = ModelRunner(cfg, _ecfg(), mesh=mesh)
        table = np.zeros((8,), np.int32)
        table[:4] = [1, 2, 3, 4]
        logits = runner.prefill(prompt, table)
        tok = int(np.argmax(logits))
        toks, _ = runner.decode_step(
            np.array([tok, 0, 0, 0], np.int32),
            np.array([len(prompt), 0, 0, 0], np.int32),
            np.stack([table] + [np.zeros((8,), np.int32)] * 3),
            jax.random.PRNGKey(0),
            np.zeros(4, np.float32),
            np.ones(4, np.float32),
        )
        return np.asarray(logits), int(toks[0])

    ref_logits, ref_tok = run(None)
    sp_logits, sp_tok = run(make_mesh(1, 1, 2, eight_devices, sp=4))
    np.testing.assert_allclose(sp_logits, ref_logits, atol=2e-4)
    assert sp_tok == ref_tok
