"""Tiered paged-KV pool + session hibernation (engine/kvtier.py).

HBM -> pinned host RAM -> disk page migration: cold prefix-store
leaves DEMOTE instead of evicting, preempted rows HIBERNATE their
pages and resume by page-upload + sub-page tail prefill, and chat
sessions checkpoint their transcript KV between turns. The contract
under test, in order of importance:

1. ``SUTRO_KV_TIERS=0`` / no pool => bit-identical to the untiered
   engine, with ZERO ops in the pool's census.
2. Demoted pages store int8 regardless of pool dtype (half the host
   bytes of bf16); the quantize/dequantize error is bounded by half a
   step of each token's scale. On an int8 pool the round trip is
   bit-exact, so demote->promote and hibernate->resume reproduce the
   untiered outputs EXACTLY at temperature 0.
3. Page accounting is exact across every hop: demotion frees device
   pages only after the pool owns the payload, pinned (hibernated)
   entries never drop, and a close returns every page.
4. Fault sites ``kvtier.demote`` / ``kvtier.promote`` /
   ``kvtier.disk_write`` degrade to regenerate / re-prefill / plain
   eviction — mid-flight migration kills never corrupt a row.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from sutro_tpu.engine import faults
from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.kvtier import (
    KVTierPool,
    dequantize_payload,
    quantize_payload,
)
from sutro_tpu.engine.prefixstore import PrefixStore
from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest, JobCtx
from sutro_tpu.models.configs import MODEL_CONFIGS

PREFIX = "You are a terse classifier. Decide the sentiment of this: "
TAILS = ["great!", "bad movie", "meh", "totally awesome ride"]


@pytest.fixture()
def mktier():
    """Factory for pools that are always closed (the migration worker
    is a daemon thread, but tests must not leak inflight state)."""
    pools = []

    def make(page_size=8, **kw):
        p = KVTierPool(page_size, **kw)
        pools.append(p)
        return p

    yield make
    faults.clear()
    for p in pools:
        p.close(timeout=5)


@pytest.fixture(scope="module")
def int8_runner():
    """A tiny runner over an int8-quantized KV pool: tier payloads ARE
    the pool format, so every migration hop is bit-exact and the
    hibernate/demote bit-identity legs assert token equality."""
    from sutro_tpu.engine.runner import ModelRunner

    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", kv_quantize="int8",
        interactive_slots=2,
    )
    return ModelRunner(MODEL_CONFIGS["tiny-dense"], ecfg)


def _payload(n_pages=1, seed=0, L=2, PS=8, KD=4):
    rng = np.random.default_rng(seed)
    return {
        "k": rng.standard_normal((L, n_pages, PS, KD)).astype(np.float32),
        "v": rng.standard_normal((L, n_pages, PS, KD)).astype(np.float32),
    }


def _payload_equal(a, b):
    return set(a) == set(b) and all(
        np.array_equal(a[k], b[k]) for k in a
    )


# ---------------------------------------------------------------------
# payload quantization units (satellite: int8 below HBM, always)
# ---------------------------------------------------------------------


def test_quantize_parity_bound_and_capacity():
    """A float payload quantizes to int8 + f32 per-token scales with
    error <= half a quantization step — and at half the value bytes
    (the host-tier capacity win PERF.md claims)."""
    raw = _payload(n_pages=3, seed=1)
    q = quantize_payload(raw)
    assert q["k"].dtype == np.int8 and q["v"].dtype == np.int8
    assert q["ks"].dtype == np.float32 and q["vs"].dtype == np.float32
    assert q["ks"].shape == raw["k"].shape[:-1]
    deq = dequantize_payload(q, np.float32)
    for vk, sk in (("k", "ks"), ("v", "vs")):
        tol = q[sk][..., None] * 0.5 + 1e-6
        assert (np.abs(raw[vk] - deq[vk]) <= tol).all()
    # int8 values are half the f32 bytes; scales add 1/KD overhead
    assert q["k"].nbytes * 4 == raw["k"].nbytes
    assert (q["ks"].nbytes + q["k"].nbytes) < raw["k"].nbytes


def test_quantize_int8_passthrough_is_bit_exact():
    """An int8 pool's payload (values + scales) passes through
    untouched — the demote path adds no second quantization."""
    q0 = quantize_payload(_payload(n_pages=2, seed=2))
    again = quantize_payload(q0)
    assert again is q0  # same object: zero-copy passthrough


# ---------------------------------------------------------------------
# pool units (no model)
# ---------------------------------------------------------------------


def test_put_get_page_roundtrip_and_census(mktier):
    pool = mktier(8, host_pages=64)
    raw = _payload(seed=3)
    key = b"page:a"
    pool.put_page(key, raw)
    assert pool.drain(10)
    got = pool.get_page(key)
    assert got is not None
    assert _payload_equal(got, quantize_payload(raw))
    c = pool.op_census()
    assert c["demotes"] == 1 and c["promotes"] == 1
    assert c["dropped"] == 0 and c["disk_writes"] == 0
    assert pool.get_page(b"page:missing") is None


def test_prefix_key_is_exact_token_content():
    a = np.arange(16, dtype=np.int32)
    assert KVTierPool.prefix_key(a) == KVTierPool.prefix_key(a.copy())
    b = a.copy()
    b[-1] += 1
    assert KVTierPool.prefix_key(a) != KVTierPool.prefix_key(b)


def test_host_lru_spills_to_disk_and_reads_back(mktier, tmp_path):
    pool = mktier(8, host_pages=2, disk_dir=tmp_path / "kvtier")
    raws = {b"p%d" % i: _payload(seed=10 + i) for i in range(4)}
    for key, raw in raws.items():
        pool.put_page(key, raw)
        assert pool.drain(10)
    assert pool.pages("host") <= 2
    assert pool.pages("disk") >= 2
    # every page is still promotable, wherever it landed
    for key, raw in raws.items():
        got = pool.get_page(key)
        assert got is not None and _payload_equal(
            got, quantize_payload(raw)
        )
    c = pool.op_census()
    assert c["disk_writes"] >= 2 and c["disk_reads"] >= 1
    assert c["dropped"] == 0


def test_pinned_rows_never_drop_without_disk(mktier):
    """A hibernated row's payload is pinned: host pressure sheds
    unpinned prefix pages around it, never the row itself."""
    pool = mktier(8, host_pages=1)
    row = _payload(n_pages=2, seed=20)
    pool.put_row(b"row:1", row)  # 2 pages, already over budget
    for i in range(3):
        pool.put_page(b"p%d" % i, _payload(seed=30 + i))
        assert pool.drain(10)
    assert pool.op_census()["dropped"] >= 1  # unpinned pressure victims
    got = pool.take_row(b"row:1")
    assert got is not None and _payload_equal(got, quantize_payload(row))
    # take_row removed it: a resumed row re-demotes fresh next time
    assert pool.get_page(b"row:1") is None


def test_take_row_after_discard_misses(mktier):
    pool = mktier(8, host_pages=8)
    pool.put_row(b"row:x", _payload(seed=4))
    pool.discard([b"row:x", b"never-there"])
    assert pool.take_row(b"row:x") is None


def test_disk_tier_persists_across_pools(mktier, tmp_path):
    d = tmp_path / "kvtier"
    pool1 = mktier(8, host_pages=1, disk_dir=d)
    raws = {b"a": _payload(seed=40), b"b": _payload(seed=41)}
    for key, raw in raws.items():
        pool1.put_page(key, raw)
        assert pool1.drain(10)
    # push both to disk (host budget 1 forces the spill)
    assert pool1.pages("disk") >= 1
    pool1.close(timeout=5)
    pool2 = mktier(8, host_pages=4, disk_dir=d)
    hits = sum(
        1
        for key, raw in raws.items()
        if (got := pool2.get_page(key)) is not None
        and _payload_equal(got, quantize_payload(raw))
    )
    assert hits >= 1  # the spilled bundle survived the process "restart"


def test_closed_pool_drops_async_and_refuses_rows(mktier):
    pool = mktier(8)
    pool.close(timeout=5)
    pool.put_page(b"late", _payload(seed=5))  # silently dropped
    assert pool.get_page(b"late") is None
    with pytest.raises(RuntimeError):
        pool.put_row(b"row", _payload(seed=6))


def test_demote_request_queue_roundtrip(mktier):
    pool = mktier(8)
    toks = np.arange(24, dtype=np.int32)
    pool.request_demote(toks)
    pool.request_demote(toks[:8])
    got = pool.pop_demote_requests()
    assert len(got) == 2 and np.array_equal(got[0], toks)
    assert pool.pop_demote_requests() == []


# ---------------------------------------------------------------------
# chaos: the three tier-hop fault sites (units)
# ---------------------------------------------------------------------


def test_torn_async_demotion_drops_entry_never_blocks(mktier):
    pool = mktier(8)
    faults.configure("kvtier.demote:error")
    try:
        pool.put_page(b"torn", _payload(seed=7))
        assert pool.drain(10)
    finally:
        faults.clear()
    assert pool.get_page(b"torn") is None  # plain eviction semantics
    assert pool.op_census()["dropped"] == 1


def test_torn_promotion_retries_once_then_misses(mktier):
    pool = mktier(8)
    pool.put_page(b"k", _payload(seed=8))
    assert pool.drain(10)
    faults.configure("kvtier.promote:error:times=1")
    try:
        got = pool.get_page(b"k")  # first attempt torn, retry lands
        assert got is not None
    finally:
        faults.clear()
    faults.configure("kvtier.promote:error")
    try:
        assert pool.get_page(b"k") is None  # both attempts torn: miss
    finally:
        faults.clear()
    assert pool.get_page(b"k") is not None  # the entry itself survived


def test_torn_disk_write_keeps_host_copy_and_quarantines(
    mktier, tmp_path
):
    """A spill that dies between write and rename leaves a truncated
    bundle at the final name. The host copy stays authoritative (the
    entry never leaves RAM) and the next scan quarantines the torn
    file instead of serving it."""
    d = tmp_path / "kvtier"
    pool = mktier(8, host_pages=1, disk_dir=d)
    raw_a, raw_b = _payload(seed=50), _payload(seed=51)
    faults.configure("kvtier.disk_write:torn")
    try:
        pool.put_page(b"a", raw_a)
        assert pool.drain(10)
        pool.put_page(b"b", raw_b)  # forces the (torn) spill of a
        assert pool.drain(10)
    finally:
        faults.clear()
    # both entries still promotable from host; nothing made it to disk
    assert pool.pages("disk") == 0
    for key, raw in ((b"a", raw_a), (b"b", raw_b)):
        got = pool.get_page(key)
        assert got is not None and _payload_equal(
            got, quantize_payload(raw)
        )
    pool.close(timeout=5)
    # the truncated bundle at the final name quarantines on scan
    pool2 = mktier(8, disk_dir=d)
    assert pool2.pages("disk") == 0
    corrupt = list((d / ".corrupt").glob("*.npz"))
    assert len(corrupt) >= 1


# ---------------------------------------------------------------------
# scheduler level (tiny model)
# ---------------------------------------------------------------------


def _reqs(tok, tails=TAILS, row_base=0, **kw):
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("temperature", 0.0)
    return [
        GenRequest(
            row_id=row_base + i,
            prompt_ids=np.array(tok.encode(PREFIX + t), np.int32),
            **kw,
        )
        for i, t in enumerate(tails)
    ]


def _batcher(runner, tok, store=None, tier=None):
    return ContinuousBatcher(
        runner, stop_ids=tok.stop_ids(), prefix_store=store,
        kv_tier=tier,
    )


def _run(b, reqs, **kw):
    res = {}
    out = b.run(
        reqs, on_result=lambda r: res.__setitem__(r.row_id, r), **kw
    )
    return out, {i: r.token_ids for i, r in res.items()}


def test_kill_switch_off_bit_identical_zero_ops(
    tiny_runner, byte_tok, mktier
):
    """The acceptance bar: no pool (and an attached-but-unexercised
    pool) produce EXACTLY the untiered outputs, and the pool's op
    census reads zero everywhere."""
    _, r_plain = _run(_batcher(tiny_runner, byte_tok), _reqs(byte_tok))
    pool = mktier(8)
    b = _batcher(tiny_runner, byte_tok, tier=pool)
    out, r_tier = _run(b, _reqs(byte_tok))
    assert out == "completed" and r_tier == r_plain
    assert all(v == 0 for v in pool.op_census().values())
    assert b.tier_demotes == 0 and b.tier_promotes == 0
    # a geometry-mismatched pool detaches entirely (tiering off)
    pool16 = mktier(16)
    b16 = _batcher(tiny_runner, byte_tok, tier=pool16)
    assert b16._kv_tier is None
    out16, r16 = _run(b16, _reqs(byte_tok))
    assert out16 == "completed" and r16 == r_plain
    assert all(v == 0 for v in pool16.op_census().values())


def test_store_demotion_frees_pages_conserved(
    tiny_runner, byte_tok, mktier
):
    """Demoting cold store leaves moves their payloads host-ward and
    returns the device pages to the allocator — the pool-wide page sum
    stays exact through every hop, and a close returns everything."""
    pool = mktier(8, host_pages=64)
    store = PrefixStore(8)
    b = _batcher(tiny_runner, byte_tok, store, pool)
    pristine = b.free_page_count
    out, _ = _run(b, _reqs(byte_tok))
    assert out == "completed" and store.n_pages > 0
    assert b.free_page_count + store.n_pages == pristine
    freed = b._demote_store_pages(2)
    assert freed > 0
    assert pool.drain(10)
    assert pool.pages("host") >= freed
    assert b.tier_demotes == freed and store.demotions == freed
    assert b.free_page_count + store.n_pages == pristine
    # the next identical job promotes (or re-prefills) and re-extends
    out2, _ = _run(b, _reqs(byte_tok))
    assert out2 == "completed"
    assert b.free_page_count + store.n_pages == pristine
    store.close()
    b2 = _batcher(tiny_runner, byte_tok, store, pool)
    assert b2.free_page_count == pristine


def test_demote_promote_roundtrip_bit_identical_int8(
    int8_runner, byte_tok, mktier
):
    """On the int8 pool the tier payload IS the pool format: demoting
    the whole store and re-running the job promotes pages back with
    outputs bit-identical to the storeless untiered run."""
    _, r_plain = _run(_batcher(int8_runner, byte_tok), _reqs(byte_tok))
    pool = mktier(8, host_pages=256)
    store = PrefixStore(8)
    b1 = _batcher(int8_runner, byte_tok, store, pool)
    out, r1 = _run(b1, _reqs(byte_tok))
    assert out == "completed" and r1 == r_plain
    n_before = store.n_pages
    freed = b1._demote_store_pages(n_before)
    assert freed > 0 and pool.drain(10)
    b2 = _batcher(int8_runner, byte_tok, store, pool)
    out2, r2 = _run(b2, _reqs(byte_tok))
    assert out2 == "completed"
    assert r2 == r_plain  # bit-identity through the host tier
    assert b2.tier_promotes > 0 and store.promotions > 0
    c = pool.op_census()
    assert c["demotes"] >= freed and c["promotes"] > 0


# -- hibernation: preemption suspends by demote, resumes by upload ----


def _preempt_session(runner, tok, tier, *, batch_max_new=24):
    """A 4-row batch job fills every slot; one interactive request
    arrives mid-flight and preempts a victim inside the
    interactive_slots budget. Returns (state, batch ctx, batch
    results, interactive results, batcher)."""
    b = _batcher(runner, tok, tier=tier)
    got, igot, done = {}, {}, []
    bctx = JobCtx(
        job_id="batch",
        pending=_reqs(
            tok, max_new_tokens=batch_max_new, temperature=0.0
        ),
        on_result=lambda r: got.__setitem__(r.row_id, r),
        priority=1,
        seq=0,
    )
    ictx = JobCtx(
        job_id="chat",
        pending=_reqs(
            tok, tails=["quick probe"], row_base=100,
            max_new_tokens=4, temperature=0.0,
        ),
        on_result=lambda r: igot.__setitem__(r.row_id, r),
        priority=-1,
        seq=1,
        interactive=True,
    )
    handed = []

    def poll_new():
        if not handed and bctx.stats.get("out", 0) > 8:
            handed.append(True)
            return ictx
        return None

    state = b.run_multi(
        [bctx],
        on_job_done=lambda c, o: done.append((c.job_id, o)),
        poll_new=poll_new,
    )
    assert handed, "interactive ctx was never attached"
    assert dict(done) == {"batch": "completed", "chat": "completed"}
    return state, bctx, got, igot, b


def test_hibernate_resume_bit_identical_int8(
    int8_runner, byte_tok, mktier
):
    """The tentpole bar: a preempted row hibernates its aligned pages
    into the pool and resumes by page-upload + sub-page tail prefill —
    with outputs BIT-IDENTICAL to the uninterrupted run, zero lost
    rows, and the migration recorded in the census."""
    _, r_solo = _run(
        _batcher(int8_runner, byte_tok),
        _reqs(byte_tok, max_new_tokens=24, temperature=0.0),
    )
    _, r_isolo = _run(
        _batcher(int8_runner, byte_tok),
        _reqs(byte_tok, tails=["quick probe"], row_base=100,
              max_new_tokens=4, temperature=0.0),
    )
    pool = mktier(8, host_pages=256)
    state, bctx, got, igot, b = _preempt_session(
        int8_runner, byte_tok, pool
    )
    assert state == "completed"
    assert {i: r.token_ids for i, r in got.items()} == r_solo
    assert {i: r.token_ids for i, r in igot.items()} == r_isolo
    assert bctx.stats.get("resumes_upload", 0) >= 1
    assert bctx.stats.get("resumes_reprefill", 0) == 0
    assert b.tier_demotes > 0 and b.tier_promotes > 0
    c = pool.op_census()
    assert c["demotes"] >= 1 and c["promotes"] >= 1
    # take_row semantics: nothing lingers once every row resumed
    assert pool.pages("host") == 0 and b._hibernated == {}


@pytest.mark.slow  # multi-device XLA compiles: excluded from the
#   single-process tier-1 run (in-process compile accumulation is
#   what trips this host's XLA:CPU flake, see run_tests_chunked.sh);
#   the chunked full-suite CI runs it per-file
def test_hibernate_sharded_runner_pure_upload_bit_identical(
    byte_tok, mktier, eight_devices
):
    """ROADMAP KV follow-up 3: hibernation is no longer gated on
    sp==pp==1. On a ring-attention sp=2 mesh the slot captures its
    pages CEIL-aligned — the partial tail page rides along — so resume
    is a PURE page upload with no sub-page tail prefill (a sharded
    prefill cannot start mid-sequence). Outputs bit-identical to the
    uninterrupted run on the same mesh."""
    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.parallel.mesh import make_mesh

    ecfg = EngineConfig(
        kv_page_size=8, max_pages_per_seq=16, decode_batch_size=4,
        max_model_len=128, use_pallas=False, param_dtype="float32",
        activation_dtype="float32", kv_quantize="int8",
        interactive_slots=2,
    )
    runner = ModelRunner(
        MODEL_CONFIGS["tiny-dense"], ecfg,
        mesh=make_mesh(1, 1, 1, eight_devices[:2], sp=2),
    )
    assert runner.sp == 2 and runner.pp == 1
    _, r_solo = _run(
        _batcher(runner, byte_tok),
        _reqs(byte_tok, max_new_tokens=24, temperature=0.0),
    )
    _, r_isolo = _run(
        _batcher(runner, byte_tok),
        _reqs(byte_tok, tails=["quick probe"], row_base=100,
              max_new_tokens=4, temperature=0.0),
    )
    pool = mktier(8, host_pages=256)
    state, bctx, got, igot, b = _preempt_session(runner, byte_tok, pool)
    assert state == "completed"
    assert b._can_hibernate  # the sp==pp==1 gate is gone
    assert {i: r.token_ids for i, r in got.items()} == r_solo
    assert {i: r.token_ids for i, r in igot.items()} == r_isolo
    assert bctx.stats.get("resumes_upload", 0) >= 1
    # the whole point of ceil-aligned capture: nothing re-prefills
    assert bctx.stats.get("resumes_reprefill", 0) == 0
    assert pool.pages("host") == 0 and b._hibernated == {}


def test_torn_hibernation_demote_falls_back_to_regenerate(
    int8_runner, byte_tok, mktier
):
    """Fault site kvtier.demote: the synchronous put_row raises BEFORE
    the device pages free, so the preemption degrades to the plain
    regenerate suspend — outputs identical, zero lost rows, nothing
    half-demoted in the pool."""
    _, r_solo = _run(
        _batcher(int8_runner, byte_tok),
        _reqs(byte_tok, max_new_tokens=24, temperature=0.0),
    )
    pool = mktier(8)
    faults.configure("kvtier.demote:error")
    try:
        state, bctx, got, _igot, _b = _preempt_session(
            int8_runner, byte_tok, pool
        )
    finally:
        faults.clear()
    assert state == "completed"
    assert {i: r.token_ids for i, r in got.items()} == r_solo
    assert bctx.stats.get("resumes_upload", 0) == 0
    c = pool.op_census()
    assert c["demotes"] == 0 and pool.pages("host") == 0


def test_torn_hibernation_promote_degrades_to_reprefill(
    int8_runner, byte_tok, mktier
):
    """Fault site kvtier.promote: the resume's take_row retries once
    then misses; the row re-admits through the normal path and
    regenerates — outputs identical, zero lost rows."""
    _, r_solo = _run(
        _batcher(int8_runner, byte_tok),
        _reqs(byte_tok, max_new_tokens=24, temperature=0.0),
    )
    pool = mktier(8)
    faults.configure("kvtier.promote:error")
    try:
        state, bctx, got, _igot, _b = _preempt_session(
            int8_runner, byte_tok, pool
        )
    finally:
        faults.clear()
    assert state == "completed"
    assert {i: r.token_ids for i, r in got.items()} == r_solo
    assert bctx.stats.get("resumes_reprefill", 0) >= 1
    assert bctx.stats.get("resumes_upload", 0) == 0


# ---------------------------------------------------------------------
# engine + serving level (shared live fixture)
# ---------------------------------------------------------------------


def test_engine_kill_switch_resolution(live_engine, monkeypatch):
    eng, _url, _home = live_engine
    key = "tiny-dense"
    monkeypatch.delenv("SUTRO_KV_TIERS", raising=False)
    assert eng._kv_tier_for(key) is None  # default is OFF
    monkeypatch.setenv("SUTRO_KV_TIERS", "0")
    assert eng._kv_tier_for(key) is None
    monkeypatch.setenv("SUTRO_KV_TIERS", "1")
    tier = eng._kv_tier_for(key)
    assert tier is not None
    assert eng._kv_tier_for(key) is tier  # one pool per engine key
    monkeypatch.setenv("SUTRO_KV_TIERS", "off")
    assert eng._kv_tier_for(key) is None


def _post_chat(url, prompt, session_id=None, max_tokens=8):
    body = {
        "model": "tiny-dense",
        "messages": [{"role": "user", "content": prompt}],
        "temperature": 0.0,
        "max_tokens": max_tokens,
    }
    if session_id is not None:
        body["session_id"] = session_id
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={
            "Content-Type": "application/json",
            "Authorization": "Bearer test-key",
        },
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        out = json.loads(resp.read())
    return out


def test_session_chat_checkpoints_and_resumes(live_engine, monkeypatch):
    """Sticky chat sessions: ``session_id`` carries the transcript
    server-side, the finished turn's KV checkpoints into the prefix
    store (tier pool on), and an idle sweep demotes it host-ward. A
    replayed session produces the same answers at temperature 0."""
    eng, url, _home = live_engine
    monkeypatch.setenv("SUTRO_KV_TIERS", "1")
    gw = eng.gateway
    assert gw is not None
    store = eng._prefix_store_for("tiny-dense")
    pages0 = store.n_pages

    t1 = _post_chat(url, "my favorite color is teal", session_id="s-a")
    c1 = t1["choices"][0]["message"]["content"]
    assert t1["choices"][0]["finish_reason"] in ("stop", "length")
    # the turn's KV checkpointed into the radix store at release
    deadline = time.monotonic() + 30
    while store.n_pages <= pages0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert store.n_pages > pages0

    t2 = _post_chat(url, "what color did I say?", session_id="s-a")
    c2 = t2["choices"][0]["message"]["content"]
    # the server-side transcript grew: turn 2's prompt covers turn 1
    assert (
        t2["usage"]["prompt_tokens"]
        > t1["usage"]["prompt_tokens"] + t1["usage"]["completion_tokens"]
    )
    assert ("tiny-dense", "s-a") in gw._sessions
    assert gw._sessions[("tiny-dense", "s-a")].turns == 2

    # replayed session: same prompts, same answers (temp 0 — the warm
    # checkpointed pages are bit-identical store promotions)
    r1 = _post_chat(url, "my favorite color is teal", session_id="s-b")
    r2 = _post_chat(url, "what color did I say?", session_id="s-b")
    assert r1["choices"][0]["message"]["content"] == c1
    assert r2["choices"][0]["message"]["content"] == c2
    assert gw.session_count() >= 2

    # idle sweep: both sessions post demote requests; the next turn's
    # serving session drains them and demotes the cold pages host-ward
    posted = gw.checkpoint_idle(idle_s=0.0)
    assert posted >= 1
    pool = eng._kv_tiers.get("tiny-dense")
    assert pool is not None
    t3 = _post_chat(url, "and my favorite number is 41", session_id="s-a")
    assert t3["choices"][0]["message"]["content"]
    deadline = time.monotonic() + 30
    while (
        pool.op_census()["demotes"] == 0
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    assert pool.op_census()["demotes"] > 0


def test_session_id_rejected_outside_chat(live_engine):
    _eng, url, _home = live_engine
    req = urllib.request.Request(
        url + "/v1/completions",
        data=json.dumps(
            {"model": "tiny-dense", "prompt": "x", "session_id": "s"}
        ).encode(),
        headers={
            "Content-Type": "application/json",
            "Authorization": "Bearer test-key",
        },
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=60)
    assert e.value.code == 400


def test_set_host_budget_shrink_evicts_immediately(mktier, tmp_path):
    """The control plane's kv_tier_host_pages knob: shrinking the
    budget live evicts LRU entries down to the new cap (spilled to the
    disk tier here, so nothing is lost)."""
    pool = mktier(8, host_pages=8, disk_dir=tmp_path / "kvtier")
    raws = {b"b%d" % i: _payload(seed=30 + i) for i in range(6)}
    for key, raw in raws.items():
        pool.put_page(key, raw)
        assert pool.drain(10)
    assert pool.pages("host") == 6
    applied = pool.set_host_budget(2)
    assert applied == 2
    # evicted entries stage for the async disk spill; once the worker
    # drains, the resident footprint is back under the new budget
    assert pool.drain(10)
    assert pool.pages("host") <= 2
    # every page is still promotable after the squeeze
    for key, raw in raws.items():
        got = pool.get_page(key)
        assert got is not None and _payload_equal(
            got, quantize_payload(raw)
        )


def test_set_host_budget_grow_and_floor(mktier):
    pool = mktier(8, host_pages=2)
    assert pool.set_host_budget(16) == 16
    assert pool.host_pages == 16
    # floor at one page; a closed pool refuses the move
    assert pool.set_host_budget(0) == 1
    pool.close(timeout=5)
    assert pool.set_host_budget(64) == 1  # unchanged: closed


def test_migration_worker_starts_after_disk_tier_published(
    tmp_path, monkeypatch
):
    """Publication order regression: the migration worker reads
    disk_dir/_disk unlocked, so the ctor must fully decide the disk
    tier (including the OSError fallback) before the thread exists."""
    seen = {}
    orig = KVTierPool._scan_disk

    def probe(self):
        seen["worker_exists"] = hasattr(self, "_worker")
        return orig(self)

    monkeypatch.setattr(KVTierPool, "_scan_disk", probe)
    pool = KVTierPool(8, host_pages=4, disk_dir=tmp_path / "kvtier")
    try:
        assert seen == {"worker_exists": False}
    finally:
        pool.close(timeout=5)
