"""Benchmark: decode throughput of the engine on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: the BASELINE config-#1 model class (qwen3-0.6b, random bf16
weights — throughput is weight-value independent) running the real engine
decode path (paged KV gather, batched sampling) at full decode batch.
``vs_baseline`` compares against ``BENCH_baseline.json`` (written on first
run) so later rounds report their speedup over this round; the reference
publishes no numbers to compare against (BASELINE.md).

Env knobs: SUTRO_BENCH_MODEL, SUTRO_BENCH_BATCH, SUTRO_BENCH_STEPS,
SUTRO_BENCH_PROMPT, SUTRO_BENCH_MULTI (decode steps fused per device
program; 1 = legacy per-token dispatch).
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

import numpy as np


_BACKEND_UP = False


def _backend_watchdog(seconds: int = 180) -> None:
    """The axon TPU tunnel, when down, makes the first backend touch
    block FOREVER inside a C call (no error, signals can't preempt it) —
    a bench run would hang until the driver gives up. A daemon thread
    fails fast and loud instead so the outage is visible in the round
    record."""
    import threading

    def _fire():
        if _BACKEND_UP:
            return
        print(
            json.dumps(
                {
                    "metric": "bench-aborted: accelerator backend "
                    "unreachable (tunnel down?)",
                    "value": 0,
                    "unit": "error",
                    "vs_baseline": 0,
                }
            ),
            flush=True,
        )
        os._exit(3)

    t = threading.Timer(seconds, _fire)
    t.daemon = True  # never keep a finished bench process alive
    t.start()


def _probe_backend_with_retry(
    retries: int = 4, probe_timeout: int = 90
) -> bool:
    """A transient tunnel blip must not abort the round's only number.

    The first backend touch blocks unkillably in C when the tunnel is
    down, so this process cannot retry once committed — instead probe in
    EXPENDABLE subprocesses (killed on timeout) with backoff, and only
    touch the backend in-process after a probe succeeds. Worst case
    ~4 probes x 90 s + backoffs before giving up."""
    import subprocess
    import sys

    for attempt in range(retries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=probe_timeout,
                capture_output=True,
            )
            if r.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if attempt < retries - 1:
            time.sleep(min(30, 5 * 2**attempt))
    return False


def _cpu_pinned() -> bool:
    """True when this process is already pinned to CPU (smoke runs set
    jax.config.jax_platforms before invoking) — probing the tunnel from
    a subprocess would then test a backend we won't use."""
    import sys

    jx = sys.modules.get("jax")
    if jx is not None:
        try:
            if (jx.config.jax_platforms or "").split(",")[0] == "cpu":
                return True
        except Exception:
            pass
    return os.environ.get("JAX_PLATFORMS", "").split(",")[:1] == ["cpu"]


def main() -> None:
    # SUTRO_SOFT_DEADLINE_S: self-exit cleanly (tunnel-preserving)
    # before any supervisor's kill can orphan a live connection
    from sutro_tpu.engine.softdeadline import arm_from_env

    arm_from_env()
    if not _cpu_pinned() and not _probe_backend_with_retry():
        print(
            json.dumps(
                {
                    "metric": "bench-aborted: accelerator backend "
                    "unreachable after retries (tunnel down?)",
                    "value": 0,
                    "unit": "error",
                    "vs_baseline": 0,
                }
            ),
            flush=True,
        )
        raise SystemExit(3)
    # probes passed — the in-process touch should succeed promptly; the
    # watchdog stays as a backstop against a blip in this exact window
    _backend_watchdog()
    import jax

    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.engine.runner import ModelRunner
    from sutro_tpu.models.configs import MODEL_CONFIGS

    model_key = os.environ.get("SUTRO_BENCH_MODEL", "qwen3-0.6b")
    B = int(os.environ.get("SUTRO_BENCH_BATCH", "64"))
    steps = int(os.environ.get("SUTRO_BENCH_STEPS", "128"))
    prompt_len = int(os.environ.get("SUTRO_BENCH_PROMPT", "128"))
    multi = int(os.environ.get("SUTRO_BENCH_MULTI", "16"))

    on_tpu = jax.default_backend() not in ("cpu",)
    # backend is up — disarm the init watchdog (compiles may take longer
    # than its budget legitimately)
    global _BACKEND_UP
    _BACKEND_UP = True
    if not on_tpu:  # keep CPU smoke runs fast
        model_key = os.environ.get("SUTRO_BENCH_MODEL", "tiny-dense")
        B, steps, prompt_len = 4, 16, 16
        multi = min(multi, 4)
    steps = -(-steps // multi) * multi  # whole windows

    mcfg = MODEL_CONFIGS[model_key]
    ecfg = EngineConfig(
        kv_page_size=64 if on_tpu else 8,
        max_pages_per_seq=(prompt_len + steps) // (64 if on_tpu else 8) + 2,
        decode_batch_size=B,
        max_model_len=prompt_len + steps + 64,
        param_dtype="bfloat16" if on_tpu else "float32",
        use_pallas=None,
        # weight-only int8 (ops/quant.py) — lets 8B-class models fit a
        # single v5e chip (SUTRO_BENCH_QUANT=int8)
        quantize=os.environ.get("SUTRO_BENCH_QUANT") or None,
        # int8 KV cache (kvcache.py): halves decode HBM traffic
        # (SUTRO_BENCH_KV_QUANT=int8)
        kv_quantize=os.environ.get("SUTRO_BENCH_KV_QUANT") or None,
    )
    runner = ModelRunner(mcfg, ecfg)
    MP = ecfg.max_pages_per_seq
    PS = ecfg.kv_page_size

    # fill every slot with a prompt
    rng = np.random.default_rng(0)
    pages_per_seq = (prompt_len + steps) // PS + 1
    tables = np.zeros((B, MP), np.int32)
    next_page = 1
    for b in range(B):
        tables[b, :pages_per_seq] = np.arange(
            next_page, next_page + pages_per_seq
        )
        next_page += pages_per_seq
    prompt = rng.integers(0, min(mcfg.vocab_size, 50000), prompt_len).astype(
        np.int32
    )
    pbs = ecfg.prefill_batch_size
    if prompt_len > ecfg.prefill_chunk:
        # long prompts: per-row chunked prefill (bounded transients)
        runner.prefill(prompt, tables[0])  # warmup/compile
        t_prefill0 = time.monotonic()
        for b in range(1, B):
            runner.prefill(prompt, tables[b])
        t_prefill = time.monotonic() - t_prefill0
        prefill_tok_s = (B - 1) * prompt_len / max(t_prefill, 1e-9)
    else:
        # warm the batched-prefill compile outside the timed window
        pbs = min(pbs, B)
        runner.prefill_batch([prompt] * pbs, tables[:pbs])
        t_prefill0 = time.monotonic()
        timed_rows = 0
        if B > pbs:
            for off in range(pbs, B, pbs):
                group = list(range(off, min(off + pbs, B)))
                runner.prefill_batch([prompt] * len(group), tables[group])
                timed_rows += len(group)
        else:  # whole batch fit the warmup group: time a steady rerun
            runner.prefill_batch([prompt] * pbs, tables[:pbs])
            timed_rows = pbs
        t_prefill = time.monotonic() - t_prefill0
        prefill_tok_s = timed_rows * prompt_len / max(t_prefill, 1e-9)

    last = rng.integers(0, 256, B).astype(np.int32)
    past_len = np.full((B,), prompt_len, np.int32)
    temp = np.full((B,), 0.7, np.float32)
    top_p = np.full((B,), 0.95, np.float32)

    # warmup (compile)
    if multi > 1:
        toks_w, _ = runner.decode_multi_async(
            last, past_len, tables, jax.random.PRNGKey(0), temp, top_p,
            multi,
        )
        past_len += multi
        last = toks_w[-1]
        jax.block_until_ready(toks_w)
    else:
        toks, _ = runner.decode_step(
            last, past_len, tables, jax.random.PRNGKey(0), temp, top_p
        )
        past_len += 1
        last = toks.astype(np.int32)

    t0 = time.monotonic()
    if multi > 1:
        # pipelined windows: chain each window off the previous one's
        # device-resident last-token row, fetching window i-1's tokens
        # while window i computes — exactly the scheduler's pipelined
        # path (decode_lookahead=2), so the tunnel round trip overlaps
        # device compute on both the dispatch and the fetch side
        prev = None
        for i in range(steps // multi):
            toks_w, _ = runner.decode_multi_async(
                last, past_len, tables, jax.random.PRNGKey(i + 1),
                temp, top_p, multi,
            )
            past_len += multi
            last = toks_w[-1]
            if prev is not None:
                np.asarray(prev)  # host-side consume, one window behind
            prev = toks_w
        np.asarray(prev)
    else:
        for i in range(steps):
            toks, _ = runner.decode_step(
                last, past_len, tables, jax.random.PRNGKey(i + 1), temp,
                top_p,
            )
            past_len += 1
            last = toks.astype(np.int32)
    dt = time.monotonic() - t0

    n_chips = max(jax.device_count(), 1)
    decode_tok_s = B * steps / dt
    value = decode_tok_s / n_chips

    # self-grading vs the hardware roofline (VERDICT r3 weak #5): every
    # captured number carries its analytic denominator so wins and
    # regressions are machine-readable without hand math
    from sutro_tpu.engine import roofline

    device_kind = jax.devices()[0].device_kind
    grade = roofline.grade_decode(
        value,
        batch=B,
        bytes_per_step=roofline.decode_bytes_per_step(
            param_bytes=roofline.param_bytes_of(runner.params),
            batch=B,
            avg_ctx=prompt_len + steps / 2,
            num_layers=mcfg.num_layers,
            kv_heads=mcfg.num_kv_heads,
            head_dim=mcfg.head_dim,
            kv_dtype_bytes=(
                1 if ecfg.kv_quantize == "int8" else (2 if on_tpu else 4)
            ),
        ),
        device_kind=device_kind,
    )
    grade.update(
        roofline.grade_prefill(
            # MFU is per chip: prefill_tok_s aggregates all devices
            prefill_tok_s / n_chips,
            n_params=roofline.param_count_of(runner.params),
            device_kind=device_kind,
        )
    )

    baseline_path = Path(__file__).parent / "BENCH_baseline.json"
    vs = 1.0
    quant = ecfg.quantize or "none"
    record = {
        "model": model_key,
        "backend": jax.default_backend(),
        "quant": quant,
        "kv_quant": ecfg.kv_quantize or "none",
        "batch": B,
        "steps": steps,
        "prompt_len": prompt_len,
        "decode_tok_s_per_chip": value,
        "prefill_s_total": t_prefill,
        "prefill_tok_s": round(prefill_tok_s, 1),
        **grade,
    }
    if baseline_path.exists():
        try:
            base = json.loads(baseline_path.read_text())
            if (
                base.get("model") == model_key
                and base.get("backend") == jax.default_backend()
                # legacy baselines predate the quant fields: they were
                # all unquantized
                and base.get("quant", "none") == quant
                and base.get("kv_quant", "none")
                == (ecfg.kv_quantize or "none")
                and base.get("decode_tok_s_per_chip", 0) > 0
            ):
                vs = value / base["decode_tok_s_per_chip"]
        except Exception:
            pass
    else:
        baseline_path.write_text(json.dumps(record, indent=2))

    print(
        json.dumps(
            {
                "metric": f"decode tokens/sec/chip ({model_key}, bs{B}, "
                f"{jax.default_backend()})",
                "value": round(value, 2),
                "unit": "tok/s/chip",
                "vs_baseline": round(vs, 3),
                "pct_hbm_roofline": grade.get("pct_hbm_roofline"),
                "mfu_prefill": grade.get("mfu_prefill"),
            }
        )
    )


if __name__ == "__main__":
    main()
