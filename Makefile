# Top-level developer/CI entry points (reference analogue: Makefile +
# .github/monorepo-ci.sh, which only compile-checked; this one actually
# builds the native runtime and runs the suite).

PY ?= python

.PHONY: all native test test-oneshot test-fast compile-check lint lint-baseline \
	lint-schema chaos telemetry-check monitor-check control-check control-bench \
	prefix-check tier-check fleet-check fleet-obs-check graph-check bench \
	bench-e2e bench-fleet bench-replay serve-bench bench-trend dryrun \
	chip-validate bench-8b cost golden host-profile clean

all: native compile-check

native:
	$(MAKE) -C native

# full suite (CPU, 8 virtual devices via tests/conftest.py), run
# per-file with crash-only retries: this build host's XLA:CPU compiler
# segfaults rarely but nondeterministically inside
# backend_compile_and_load under load (observed twice, different test
# files, both pass in isolation) — a single-process run can die at ~60%
# through no fault of the code. Real test failures still fail fast.
test: native
	bash .github/run_tests_chunked.sh

# single-process run (faster when the host's XLA CPU compiler is
# healthy; see `test` for why the chunked runner is the default)
test-oneshot: native
	$(PY) -m pytest tests/ -q

# quick gate: everything except the slow multi-device / golden suites
test-fast: native
	$(PY) -m pytest tests/ -q -x \
		--ignore=tests/test_pipeline.py \
		--ignore=tests/test_golden.py \
		--ignore=tests/test_parallel.py \
		--ignore=tests/test_ring.py

# the reference CI ran `python -m compileall` only (SURVEY §4); kept as
# the cheapest smoke layer
compile-check:
	$(PY) -m compileall -q sutro_tpu tests bench.py bench_e2e.py \
		bench_interactive.py

# graftlint: engine-aware static analysis (lock discipline, jit purity,
# thread/exception hygiene) gated against the committed baseline —
# non-zero exit on any NEW finding (README "Static analysis")
# wall-time budget: the whole-tree scan (all passes, including the
# inter-procedural data-race walk) must stay under 60s to hold its
# place as a tier-1 gate
lint:
	timeout -k 5 60 $(PY) -m sutro_tpu.analysis sutro_tpu

# accept the current findings as the new baseline (review the diff of
# sutro_tpu/analysis/baseline.json before committing!)
lint-baseline:
	$(PY) -m sutro_tpu.analysis sutro_tpu --write-baseline

# regenerate the dp/elastic wire-frame schema from the senders and fail
# if the committed analysis/wire_schema.json drifted (CI runs this: a
# frame/key change must land WITH its schema update — removals are then
# caught by the wire-key-removed lint pass)
lint-schema:
	$(PY) -m sutro_tpu.analysis sutro_tpu --write-wire-schema
	git diff --exit-code -- sutro_tpu/analysis/wire_schema.json

# seeded chaos suite (FAILURES.md): deterministic fault injection
# end-to-end — row quarantine (incl. the 256-row poison-row acceptance
# case), transient I/O retry, torn chunks, device errors + resume
# bit-identity, crash-mid-finalize, dp liveness, plus the elastic
# fleet gate (worker crash/hang/mid-frame drop, SIGTERM preemption
# drain, late join, steal race, coordinator crash + resume), plus the
# replica-fleet chaos/degradation subset (replica kill mid-job with
# bit-identical failover, mid-stream crash -> structured error,
# old/new protocol skew -> probe-only routing). A tier-1 CI step.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py tests/test_elastic.py \
		-q -m "not slow" -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py -q -m "not slow" \
		-p no:cacheprovider -k "chaos or degradation"

# telemetry gate (OBSERVABILITY.md): exporter golden-file + flight-
# recorder/reconciliation tests + distributed telemetry (trace
# propagation, federation, doctor golden) + tail-latency forensics
# (exemplars, request traces, Perfetto export golden), then the
# telemetry-on vs telemetry-off host-overhead comparison (< 2% delta
# asserted in code, including the dp-coordinator wire leg and the
# exemplars-on forensics census). Tier-1 CI.
telemetry-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_telemetry.py \
		tests/test_distributed_telemetry.py tests/test_traces.py \
		-q -m "not slow" -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) benchmarks/profile_host_overhead.py --telemetry

# live-monitor gate (OBSERVABILITY.md "Live monitor"): SLO rule
# hysteresis/debounce, windowed percentiles, streaming doctor verdicts,
# tenant attribution + the monitor tick-cost leg (budget asserted in
# code; zero sampling work with telemetry off). Tier-1 CI.
monitor-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_monitor.py \
		-q -m "not slow" -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) benchmarks/profile_host_overhead.py --monitor

# enforcement gate (OBSERVABILITY.md "Enforcement"): token-bucket
# admission, priority-ladder policy, autotuner hysteresis, controller
# degradation-to-pass-through, the control-on/off host-overhead budget
# (zero-cost when SUTRO_CONTROL=0, asserted in code), and the
# mixed-tenant chaos bench smoke. Tier-1 CI.
control-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_control.py \
		tests/test_chaos.py -k "control" -q -m "not slow" \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) benchmarks/profile_host_overhead.py --control
	$(MAKE) control-bench

# mixed-tenant chaos bench -> BENCH_CONTROL.json: a noisy tenant
# floods the interactive tier while a victim tenant and a batch tenant
# share the engine. The STOCK interactive_ttft_p99 rule (GET /monitor)
# must fire with SUTRO_CONTROL=0 and never fire with token-bucket
# admission on. Not tier-1 (~2 min wall); run on control-plane changes.
control-bench:
	JAX_PLATFORMS=cpu $(PY) benchmarks/bench_control.py --smoke

# prefix-store gate (OBSERVABILITY.md "Prefix store"): radix-tree
# units (LRU order, pin refcounts, racer declines), scheduler
# integration (second identical-template job prefills the tail only,
# bit-identical to SUTRO_PREFIX_STORE=0), eviction-vs-admission and
# lookup-fault chaos, and the engine close()/page-conservation
# contract. Tier-1 CI.
prefix-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_prefix_store.py \
		-q -m "not slow" -p no:cacheprovider

# tiered-KV gate (OBSERVABILITY.md "KV tiers"): pool units (quantized
# payload parity, host LRU + disk spill, pinned hibernated rows),
# scheduler integration (demote->promote and hibernate->resume
# bit-identical on the int8 pool, SUTRO_KV_TIERS=0 bit-identical with
# a zero op census), tier-hop chaos (torn demote/promote/disk-write),
# exact page conservation, and the sticky-session chat checkpoint/
# resume path over the live gateway. Tier-1 CI.
tier-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_kv_tiers.py \
		-q -m "not slow" -p no:cacheprovider

# replica-fleet gate (FAILURES.md "Replica fleet"): breaker state
# machine + bounded backoff + flap detection, health-checked routing
# (warm-prefix affinity, least-loaded, drain exclusion), batch-job
# failover over the shared jobstore (zero rows lost or duplicated,
# bit-identical at temperature 0), mid-stream structured errors,
# protocol-skew degradation, SDK reconnect-with-cursor — then the
# --fleet op census (per-request routing decision under the same 2%
# host-overhead envelope as telemetry; zero ops when off). Tier-1 CI.
fleet-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py \
		-q -m "not slow" -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) benchmarks/profile_host_overhead.py --fleet

# fleet-observability gate (OBSERVABILITY.md "Fleet observability"):
# cross-replica trace propagation (X-Sutro-Trace forward + adoption,
# stitched GET /trace/{id} with per-process lanes pinned by golden
# export), federated /metrics under the replica label with the _fleet
# aggregate + route-latency exemplars, fleet monitor SLO rules firing
# AND resolving under live chaos, protocol skew both directions, the
# replay capture/load round-trip — then the --fleet-obs op census
# (per-request trace+exemplar cost under the same 2% host-overhead
# envelope; zero ops and zero federation sends when telemetry off).
# Tier-1 CI.
fleet-obs-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet_obs.py \
		-q -m "not slow" -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) benchmarks/profile_host_overhead.py --fleet-obs

# stage-graph gate (README "Stage graphs"): submit-time DAG validation
# (structured INVALID_GRAPH through API + SDK), generate->score->rank
# bit-identity vs the client-side job sequence at temp 0, streaming
# inter-stage admission (downstream first result before upstream done,
# asserted via stage spans), per-stage quarantine propagation, DAG
# crash/resume chaos (only missing stage chunks replayed), the elo
# tie-break pin, and the --stagegraph zero-overhead op census for
# stage-less jobs. Tier-1 CI.
graph-check:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_stagegraph.py \
		tests/test_evals.py -q -m "not slow" -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) benchmarks/profile_host_overhead.py --stagegraph

# replica-fleet scaling bench -> BENCH_FLEET.json: 1- vs 3-replica
# batch throughput through the router (device-time-emulating stub
# replicas; grade >=2x) + warm-prefix routed hit rate over two real
# engines. Grades are warn-only in `make bench-trend`; not tier-1
# (~40 s wall) — run on fleet/router changes.
bench-fleet:
	JAX_PLATFORMS=cpu $(PY) benchmarks/bench_fleet.py

# trace-replay load harness -> BENCH_REPLAY.json: replay the
# deterministic session-heavy synthetic workload (same JSONL schema as
# `sutro replay record`) open-loop against 1- vs 3-replica fleets at
# SUTRO_REPLAY_SPEEDUP x (default 2); grades p99 TTFT, throughput
# retention, and routed-prefix hit rate. Grades are warn-only in
# `make bench-trend`; not tier-1 (~20 s wall) — run on fleet/router or
# observability changes.
bench-replay:
	JAX_PLATFORMS=cpu $(PY) benchmarks/bench_replay.py

# raw decode microbench (one JSON line; driver contract)
bench:
	$(PY) bench.py

# full-engine workloads: classify / generate / embed -> BENCH_E2E.json
bench-e2e:
	$(PY) bench_e2e.py

# interactive-tier latency legs (TTFT/ITL idle vs co-resident batch)
# -> BENCH_INTERACTIVE.json; CI runs the CPU smoke, the chip run uses
# the same entry point without SUTRO_E2E_CPU
serve-bench:
	SUTRO_E2E_CPU=1 JAX_PLATFORMS=cpu $(PY) bench_interactive.py

# warn-only trend report over the accumulated bench artifacts
# (BENCH_r*.json, BENCH_E2E.json, BENCH_INTERACTIVE.json)
# -> BENCH_TREND.md; >15% regressions in graded metrics print WARN
# lines but never fail the build
bench-trend:
	$(PY) benchmarks/bench_trend.py

# multi-chip sharding dry run on 8 virtual CPU devices
dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# one-shot post-outage chip queue: numerics, batch/xrow/MULTI sweeps,
# sampling sweep, bf16-logits A/B, 8B-class bench -> CHIP_VALIDATION.json
chip-validate:
	$(PY) benchmarks/chip_validation.py

# realistically-sized models + HBM roofline fractions -> BENCH_8B.json
bench-8b:
	$(PY) benchmarks/bench_8b.py

# north-star $/job vs OpenAI Batch from the latest BENCH_E2E record
cost:
	$(PY) benchmarks/cost_northstar.py

# host-side overhead profile (stub runner, no chip): per-window micro
# legs + full-job-lifecycle e2e legs at 512/20k rows, with the
# pipelined-decode budget (host_ms_per_window <= window_ms x
# (lookahead-1)) and flat-scaling (20k <= 1.25x 512 per-row) asserted
# in code — non-zero exit on regression
host-profile:
	JAX_PLATFORMS=cpu $(PY) benchmarks/profile_host_overhead.py --e2e

# README 3-row quickstart on real trained weights -> GOLDEN.json
golden:
	$(PY) benchmarks/golden_quickstart.py

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
