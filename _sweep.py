import time, json, os
import numpy as np
import jax

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS

def run(B, multi, prompt_len=128, steps=256, ps=64, extra_ctx=0, use_pallas=None):
    mcfg = MODEL_CONFIGS["qwen3-0.6b"]
    ecfg = EngineConfig(
        kv_page_size=ps,
        max_pages_per_seq=(prompt_len + steps + extra_ctx) // ps + 2,
        decode_batch_size=B,
        max_model_len=prompt_len + steps + extra_ctx + 64,
        param_dtype="bfloat16",
        use_pallas=use_pallas,
    )
    runner = ModelRunner(mcfg, ecfg)
    MP = ecfg.max_pages_per_seq
    rng = np.random.default_rng(0)
    pages_per_seq = (prompt_len + steps) // ps + 1
    tables = np.zeros((B, MP), np.int32); n = 1
    for b in range(B):
        tables[b, :pages_per_seq] = np.arange(n, n + pages_per_seq); n += pages_per_seq
    prompt = rng.integers(0, 50000, prompt_len).astype(np.int32)
    rows = [prompt] * min(B, 8)
    t0 = time.monotonic()
    runner.prefill_batch(rows, tables[:len(rows)])
    t_pf = time.monotonic() - t0
    last = rng.integers(0, 256, B).astype(np.int32)
    past = np.full((B,), prompt_len, np.int32)
    temp = np.full((B,), 0.7, np.float32); top_p = np.full((B,), 0.95, np.float32)
    # warmup
    toks, _ = runner.decode_multi(last, past, tables, jax.random.PRNGKey(0), temp, top_p, multi)
    past += multi; last = toks[-1].astype(np.int32)
    t0 = time.monotonic()
    nwin = steps // multi
    for i in range(nwin):
        toks, _ = runner.decode_multi(last, past, tables, jax.random.PRNGKey(i+1), temp, top_p, multi)
        past += multi; last = toks[-1].astype(np.int32)
    dt = time.monotonic() - t0
    print(json.dumps({"B": B, "multi": multi, "ps": ps, "ctx_cap": MP*ps,
        "pallas": runner.use_pallas, "decode_tok_s": round(B*nwin*multi/dt, 1),
        "ms_per_step": round(1000*dt/(nwin*multi), 2),
        "prefill_batch8_s": round(t_pf, 2)}), flush=True)

import sys
for spec in sys.argv[1:]:
    kw = json.loads(spec)
    run(**kw)
